"""MXU engine suite (round 8, ops.mxu): oracle parity for the blocked
adjacency-tile matmul expansion, bit-identity under the density-based
direction switch (both lax.cond branches within one BFS), the Pallas
tile-chain interpret-mode parity, K sweep through the sub-batch
splitter, the analytic tile-FLOP counters, the shared density helpers
(ops.engine.frontier_activity / source_band) and the serve registry's
content-hash tile-index cache.

Fixtures are deliberately tiny (n <= 384): tile geometry, not scale, is
what the matmul formulation can get wrong, and a 16-wide tile on a
~300-vertex graph already spans hundreds of tiles.
"""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.mxu import (
    MxuEngine,
    MxuGraph,
    mxu_matmul_hits,
    resolve_tile,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.timing import (
    mxu_tile_counts,
    reset_mxu_tiles,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def _reference(n, edges, queries):
    f = np.asarray(
        [oracle_f(oracle_bfs(n, edges, q)) for q in queries], dtype=np.int64
    )
    return f, oracle_best(f.tolist())


@pytest.fixture(scope="module")
def rmat():
    n, edges = generators.rmat_edges(8, edge_factor=8, seed=801)
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 10, max_group=6, seed=802)
    queries[3] = np.zeros(0, dtype=np.int32)
    queries[7] = np.array([-1, n + 9], dtype=np.int32)
    f, best = _reference(n, edges, queries)
    return n, edges, g, pad_queries(queries), f, best


@pytest.fixture(scope="module")
def road():
    n, edges = generators.road_edges(18, 21, seed=803)
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 9, max_group=5, seed=804)
    queries[2] = np.zeros(0, dtype=np.int32)
    f, best = _reference(n, edges, queries)
    return n, edges, g, pad_queries(queries), f, best


def _assert_agrees(eng, padded, f, best):
    np.testing.assert_array_equal(np.asarray(eng.f_values(padded)), f)
    assert eng.best(padded) == best


# --- tile packing geometry ---------------------------------------------------


def test_tile_index_is_sorted_and_exact(rmat):
    n, edges, g, _, _, _ = rmat
    mg = MxuGraph.from_host(g, tile=16, device=False)
    row = np.asarray(mg.tile_row)
    col = np.asarray(mg.tile_col)
    # Sorted by (row, col): the segment-sum's indices_are_sorted contract.
    order = row.astype(np.int64) * mg.ntr + col
    assert (np.diff(order) > 0).all()
    # Every dedup edge lands in exactly one tile cell, and the tile set
    # holds nothing else.
    u, v, _ = g.deduped_pairs()
    assert int(np.asarray(mg.tiles).sum()) == u.size
    for b in np.random.default_rng(0).integers(0, mg.nt, size=4):
        tile = np.asarray(mg.tiles[b])
        uu, vv = np.nonzero(tile)
        base_u = row[b] * mg.tile
        base_v = col[b] * mg.tile
        got = set(zip((base_u + uu).tolist(), (base_v + vv).tolist()))
        want = {
            (a, b2)
            for a, b2 in zip(u.tolist(), v.tolist())
            if a // mg.tile == row[b] and b2 // mg.tile == col[b]
        }
        assert got == want


def test_tile_cap_and_size_validation(rmat):
    _, _, g, _, _, _ = rmat
    with pytest.raises(ValueError, match="too tile-dense"):
        MxuGraph.from_host(g, tile=8, max_tiles=4)
    with pytest.raises(ValueError, match="multiple of 8"):
        MxuGraph.from_host(g, tile=12)
    assert resolve_tile(64) == 64


def test_matmul_hits_equal_push_expansion(rmat):
    """One level of the matmul expansion == the brute-force neighbor OR."""
    import jax.numpy as jnp

    n, edges, g, _, _, _ = rmat
    mg = MxuGraph.from_host(g, tile=16)
    u, v, _ = g.deduped_pairs()
    rng = np.random.default_rng(5)
    fr_bytes = (rng.random((mg.n_pad, 32)) < 0.1).astype(np.uint8)
    fr_bytes[n:] = 0
    want = np.zeros_like(fr_bytes)
    for a, b in zip(u, v):
        want[a] |= fr_bytes[b]
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        pack_byte_planes,
        unpack_byte_planes,
    )

    frontier = pack_byte_planes(jnp.asarray(fr_bytes))
    got = unpack_byte_planes(mxu_matmul_hits(mg, frontier))
    np.testing.assert_array_equal(np.asarray(got), want)


# --- oracle parity across drive modes ---------------------------------------


@pytest.mark.slow  # ~10 s; tier-1 keeps the test_engines_agree mxu arms
@pytest.mark.parametrize(
    "kwargs",
    [
        {},  # unchunked fused best
        {"level_chunk": 2},  # chunked drive loop
        {"level_chunk": 2, "megachunk": 3},  # megachunk fusion
        {"switch": 0},  # never push: pure matmul
        {"switch": 10**9, "push_budget": 10**9},  # always push (clamped)
        {"switch": 40, "level_chunk": 3},  # both directions in one BFS
    ],
)
def test_rmat_parity(rmat, kwargs):
    n, edges, g, padded, f, best = rmat
    _assert_agrees(MxuEngine(MxuGraph.from_host(g, tile=16), **kwargs),
                   padded, f, best)


@pytest.mark.slow  # tier-1 covers the road regime via the banded mxu arm
def test_road_parity_high_skip(road):
    """Banded lattice: most of the tile grid is all-zero, the skip index
    carries the level."""
    n, edges, g, padded, f, best = road
    mg = MxuGraph.from_host(g, tile=32)
    assert mg.nt < mg.tiles_total // 2
    _assert_agrees(MxuEngine(mg, level_chunk=4), padded, f, best)


@pytest.mark.slow  # ~7 s: three compiles over the stranded fixture
def test_stranded_component_parity():
    """A path graph plus a disconnected clique: unreached vertices stay
    -1 through the matmul route, and sources in the stranded component
    never leak distances across."""
    path = np.array([[i, i + 1] for i in range(40)], dtype=np.int32)
    clique = np.array(
        [[u, v] for u in range(60, 66) for v in range(u + 1, 66)],
        dtype=np.int32,
    )
    edges = np.concatenate([path, clique])
    n = 96
    g = CSRGraph.from_edges(n, edges)
    queries = [
        np.array([0], dtype=np.int32),
        np.array([62], dtype=np.int32),
        np.array([5, 63], dtype=np.int32),
        np.array([90], dtype=np.int32),  # isolated vertex
    ]
    f, best = _reference(n, edges, queries)
    padded = pad_queries(queries)
    for kwargs in ({}, {"level_chunk": 2, "switch": 0}, {"switch": 10**6}):
        _assert_agrees(MxuEngine(MxuGraph.from_host(g, tile=16), **kwargs),
                       padded, f, best)


@pytest.mark.slow  # ~12 s: three K-shapes, each its own compile
def test_k_sweep_subbatch(rmat):
    """K=1 (single word), K=64 (two words) and K=320 through the
    SubBatchEngine splitter (strict-< winner merge) all agree."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        SubBatchEngine,
    )

    n, edges, g, _, _, _ = rmat
    mg = MxuGraph.from_host(g, tile=16)
    for k, wrap in ((1, False), (64, False), (320, True)):
        queries = generators.random_queries(n, k, max_group=4, seed=900 + k)
        f, best = _reference(n, edges, queries)
        padded = pad_queries(queries)
        eng = MxuEngine(mg, level_chunk=4)
        if wrap:
            eng = SubBatchEngine(eng, batch_k=128)
        _assert_agrees(eng, padded, f, best)


# --- Pallas tile chain -------------------------------------------------------


@pytest.mark.slow  # ~10 s: interpret-mode chain is slow off-TPU
def test_pallas_kernel_parity(rmat):
    """kernel=True runs the gridless Pallas tile-product chain (interpret
    mode on CPU) and must be bit-identical to the XLA einsum route."""
    n, edges, g, padded, f, best = rmat
    mg = MxuGraph.from_host(g, tile=16)
    eng = MxuEngine(mg, kernel=True, level_chunk=4)
    assert eng.kernel  # the chain imported and was selected
    _assert_agrees(eng, padded, f, best)


def test_pallas_chain_chunks_batches():
    """The tile chain cuts the batch under the VMEM product budget."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.pallas_mxu import (
        MAX_OUT_BYTES,
        tile_batch,
    )

    assert tile_batch(128, 256) == MAX_OUT_BYTES // (128 * 256 * 4)
    assert tile_batch(128, 1 << 20) == 1  # never zero


# --- direction switch --------------------------------------------------------


def test_direction_trace_flips_and_is_consistent(rmat):
    n, edges, g, padded, _, _ = rmat
    eng = MxuEngine(MxuGraph.from_host(g, tile=16), switch=40)
    trace = eng.level_direction_trace(padded)
    assert trace and trace is eng.last_direction_trace
    dirs = {s["direction"] for s in trace}
    assert dirs == {"push", "matmul"}  # the fixture exercises BOTH
    for s in trace:
        want = (
            "push"
            if s["active_rows"] <= eng.switch
            and s["active_edges"] <= eng.push_budget
            else "matmul"
        )
        assert s["direction"] == want


def test_push_budget_is_clamped(rmat):
    """A huge budget must clamp to n_pad + e: sparse_hits_or allocates
    budget-sized static intermediates."""
    _, _, g, _, _, _ = rmat
    mg = MxuGraph.from_host(g, tile=16)
    eng = MxuEngine(mg, push_budget=10**9)
    assert eng.push_budget <= mg.n_pad + int(np.asarray(mg.vals).size)


# --- telemetry ---------------------------------------------------------------


def test_tile_flop_accounting(rmat):
    """Chunked best() under switch=0 records exactly levels * analytic
    per-level FLOPs/skips (the regime where the issued-if-matmul model
    is exact)."""
    n, edges, g, padded, _, best = rmat
    mg = MxuGraph.from_host(g, tile=16)
    eng = MxuEngine(mg, switch=0, level_chunk=1, megachunk=1)
    eng.compile(padded.shape)
    reset_mxu_tiles()
    assert eng.best(padded) == best
    flops, skipped, total = mxu_tile_counts()
    assert total and total % mg.tiles_total == 0
    levels = total // mg.tiles_total
    k = -(-padded.shape[0] // 32) * 32
    assert flops == levels * mg.level_flops * k
    assert skipped == levels * (mg.tiles_total - mg.nt)
    reset_mxu_tiles()
    assert mxu_tile_counts() == (0, 0, 0)


# --- shared density helpers (satellite: ops.engine) --------------------------


def test_frontier_activity():
    import jax.numpy as jnp

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        frontier_activity,
    )

    frontier = jnp.asarray(
        [[0, 0], [1, 0], [0, 2], [0, 0]], dtype=jnp.uint32
    )
    edge_counts = jnp.asarray([10, 20, 30, 40], dtype=jnp.int32)
    active, cnt, edges = frontier_activity(frontier, edge_counts)
    np.testing.assert_array_equal(
        np.asarray(active), [False, True, True, False]
    )
    assert int(cnt) == 2 and int(edges) == 50


def test_source_band():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        source_band,
    )

    assert source_band(np.array([[5, 2], [9, -1]]), 20) == [2, 10]
    assert source_band(np.array([[-1, 25]]), 20) == [0, 0]  # none valid


# --- serve registry tile cache (satellite: warm reload) ----------------------


def test_serve_registry_reuses_tile_index(tmp_path, monkeypatch):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.registry import (
        GraphRegistry,
        mxu_tile_cache_stats,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_graph_bin,
    )

    monkeypatch.setenv("MSBFS_BACKEND", "mxu")
    monkeypatch.setenv("MSBFS_MXU_TILE", "16")
    n, edges = generators.gnm_edges(90, 300, seed=51)
    gpath = str(tmp_path / "g.bin")
    save_graph_bin(gpath, n, edges)
    reg = GraphRegistry()
    before = mxu_tile_cache_stats()
    e1 = reg.load("g", gpath)
    mid = mxu_tile_cache_stats()
    assert mid["entries"] == before["entries"] + 1
    e2 = reg.reload("g")
    after = mxu_tile_cache_stats()
    # The reload re-read identical bytes: same digest, same tile size,
    # so the packed tile index is REUSED (one hit, no new entry) and the
    # two engines share the same device-resident MxuGraph.
    assert after["entries"] == mid["entries"]
    assert after["hits"] == mid["hits"] + 1
    assert e2.supervisor.engine.graph is e1.supervisor.engine.graph
    assert e2.version == e1.version + 1
    # And the cached layout still answers correctly.
    queries = generators.random_queries(n, 6, max_group=4, seed=52)
    f, best = _reference(n, edges, queries)
    got = e2.supervisor.best(pad_queries(queries))
    assert tuple(int(x) for x in np.asarray(got)) == best


def test_tile_cache_byte_cap_evicts_lru(tmp_path, monkeypatch):
    """Satellite (round 9): the tile cache is BOUNDED.  Distinct digests
    past the byte cap evict oldest-first (counted in the stats hook),
    resident bytes stay under the cap, and cap <= 0 disables caching
    entirely — a long-lived fleet replica must not pin device memory
    proportional to its reload history."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.registry import (
        GraphRegistry,
        mxu_tile_cache_stats,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_graph_bin,
    )

    monkeypatch.setenv("MSBFS_BACKEND", "mxu")
    monkeypatch.setenv("MSBFS_MXU_TILE", "16")

    def register(seed: int) -> None:
        n, edges = generators.gnm_edges(90, 300, seed=seed)
        gpath = str(tmp_path / f"g{seed}.bin")
        save_graph_bin(gpath, n, edges)
        GraphRegistry().load(f"g{seed}", gpath)

    register(61)
    first = mxu_tile_cache_stats()
    assert first["bytes"] > 0
    # Cap the cache at exactly the current footprint: the next distinct
    # digest must push the oldest entry out, never the byte total over.
    monkeypatch.setenv("MSBFS_MXU_CACHE_BYTES", str(first["bytes"]))
    register(62)
    bounded = mxu_tile_cache_stats()
    assert bounded["evictions"] > first["evictions"]
    assert 0 < bounded["bytes"] <= first["bytes"]
    assert bounded["cap_bytes"] == first["bytes"]
    # cap <= 0: builds still succeed, nothing parks in the cache.
    monkeypatch.setenv("MSBFS_MXU_CACHE_BYTES", "0")
    register(63)
    after = mxu_tile_cache_stats()
    assert after["entries"] == bounded["entries"]
    assert after["bytes"] == bounded["bytes"]
