"""Static lock-discipline analysis for ``serve/`` and ``runtime/``.

Two rules per the lock contract (docs/ANALYSIS.md):

* ``mixed-lock-write`` — an instance attribute assigned both inside and
  outside ``with self._lock`` (``__init__`` is pre-publication and
  exempt).  Mixed writes are how PR 11's journal-compaction race
  shipped: one path updated state under the lock, another didn't.
* ``lock-order-cycle`` — the cross-class lock-acquisition-order graph
  contains a cycle.  Edges come from nested ``with`` statements and
  from calls made while holding a lock, expanded transitively through
  same-class method calls and through cross-class calls whose method
  name is unique among the analyzed classes.

Locks are attributes assigned ``threading.Lock()``/``RLock()``;
``threading.Condition(self._lock)`` aliases the condition attribute to
its underlying lock (a bare ``Condition()`` is its own lock).  Guarding
is recognized through ``with self.<lock>:`` — the repo convention; the
dynamic watchdog (analysis.lockwatch) covers manual acquire/release.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ParsedFile, dotted

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
COND_CTORS = {"threading.Condition", "Condition"}


@dataclass
class ClassInfo:
    name: str
    path: str
    locks: Set[str] = field(default_factory=set)  # attr names that ARE locks
    aliases: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    # attr -> (guarded write lines, unguarded write lines)
    writes: Dict[str, Tuple[List[int], List[int]]] = field(default_factory=dict)
    # method name -> locks directly acquired in it (attr names)
    acquires: Dict[str, Set[str]] = field(default_factory=dict)
    # method name -> [(held lock attr, callee expr, line)]
    calls_under_lock: Dict[str, List[Tuple[str, str, int]]] = field(default_factory=dict)
    # direct nested-with edges: (attrA, attrB, line)
    nested: List[Tuple[str, str, int]] = field(default_factory=list)
    methods: Set[str] = field(default_factory=set)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_class(cls: ast.ClassDef, path: str) -> ClassInfo:
    info = ClassInfo(cls.name, path)
    # Pass 1: lock attribute discovery, anywhere in the class.
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func) or ""
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if ctor in LOCK_CTORS:
                    info.locks.add(attr)
                elif ctor in COND_CTORS:
                    if node.value.args:
                        under = _self_attr(node.value.args[0])
                        if under:
                            info.aliases[attr] = under
                            continue
                    info.locks.add(attr)

    def resolve(attr: str) -> str:
        return info.aliases.get(attr, attr)

    def is_lock(attr: str) -> bool:
        return resolve(attr) in info.locks

    # Pass 2: per-method walk with the held-lock stack.
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info.methods.add(meth.name)
        info.acquires.setdefault(meth.name, set())
        info.calls_under_lock.setdefault(meth.name, [])
        in_init = meth.name == "__init__"

        def record_write(attr: str, line: int, held: Tuple[str, ...]) -> None:
            if in_init or is_lock(attr) or attr in info.aliases:
                return
            guarded, unguarded = info.writes.setdefault(attr, ([], []))
            (guarded if held else unguarded).append(line)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and is_lock(attr):
                        lock = resolve(attr)
                        info.acquires[meth.name].add(lock)
                        if held and held[-1] != lock:
                            info.nested.append((held[-1], lock, node.lineno))
                        acquired.append(lock)
                inner = held + tuple(a for a in acquired if a not in held)
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not meth:
                return  # nested def: different execution context
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        record_write(attr, node.lineno, held)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                attr = _self_attr(node.target)
                if attr is not None:
                    record_write(attr, node.lineno, held)
            elif isinstance(node, ast.Call) and held:
                name = dotted(node.func)
                if name is not None and "." in name:
                    info.calls_under_lock[meth.name].append((held[-1], name, node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        walk(meth, ())
    return info


def _closure_acquires(classes: Dict[str, ClassInfo]) -> Dict[Tuple[str, str], Set[str]]:
    """(class, method) -> lock node keys ('Cls.attr') transitively
    acquired, expanding self-calls and unique-name cross-class calls."""
    method_owner: Dict[str, List[str]] = {}
    for cname, info in classes.items():
        for m in info.methods:
            method_owner.setdefault(m, []).append(cname)

    out: Dict[Tuple[str, str], Set[str]] = {}
    for cname, info in classes.items():
        for m in info.methods:
            out[(cname, m)] = {f"{cname}.{a}" for a in info.acquires.get(m, set())}

    def callees(cname: str, meth: str):
        info = classes[cname]
        for _, call_name, _ in info.calls_under_lock.get(meth, []):
            parts = call_name.split(".")
            leaf = parts[-1]
            if parts[0] == "self" and len(parts) == 2 and leaf in info.methods:
                yield (cname, leaf)
            else:
                owners = method_owner.get(leaf, [])
                if len(owners) == 1 and owners[0] != cname:
                    yield (owners[0], leaf)

    changed = True
    while changed:
        changed = False
        for key in out:
            for callee in callees(*key):
                if callee in out and not out[callee] <= out[key]:
                    out[key] |= out[callee]
                    changed = True
    return out


def _order_edges(classes: Dict[str, ClassInfo]) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Edge (lockA -> lockB) -> (path, line) witness."""
    closure = _closure_acquires(classes)
    method_owner: Dict[str, List[str]] = {}
    for cname, info in classes.items():
        for m in info.methods:
            method_owner.setdefault(m, []).append(cname)

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add(a: str, b: str, path: str, line: int) -> None:
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (path, line)

    for cname, info in classes.items():
        for a, b, line in info.nested:
            add(f"{cname}.{a}", f"{cname}.{b}", info.path, line)
        for meth, calls in info.calls_under_lock.items():
            for held, call_name, line in calls:
                parts = call_name.split(".")
                leaf = parts[-1]
                targets: List[Tuple[str, str]] = []
                if parts[0] == "self" and len(parts) == 2 and leaf in info.methods:
                    targets.append((cname, leaf))
                else:
                    owners = method_owner.get(leaf, [])
                    if len(owners) == 1 and owners[0] != cname:
                        targets.append((owners[0], leaf))
                for tkey in targets:
                    for lock in closure.get(tkey, set()):
                        add(f"{cname}.{held}", lock, info.path, line)
    return edges


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    for start in sorted(graph):
        # DFS from start looking for a path back to start.
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        visited: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = tuple(sorted(path))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(path + [start])
                elif nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
    # Deduplicate rotations: keep one witness per node set.
    uniq: Dict[Tuple[str, ...], List[str]] = {}
    for c in cycles:
        uniq.setdefault(tuple(sorted(set(c))), c)
    return list(uniq.values())


def run(files: List[ParsedFile]) -> List[Finding]:
    findings: List[Finding] = []
    classes: Dict[str, ClassInfo] = {}
    for pf in files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                info = _collect_class(node, pf.path)
                if info.locks:
                    classes[info.name] = info

    for cname in sorted(classes):
        info = classes[cname]
        for attr in sorted(info.writes):
            guarded, unguarded = info.writes[attr]
            if guarded and unguarded:
                findings.append(Finding(
                    "locks", "mixed-lock-write", info.path, unguarded[0],
                    cname, f"{cname}.{attr}",
                    f"{cname}.{attr} written under the lock (line {guarded[0]}) "
                    f"and without it (line {unguarded[0]})",
                ))

    edges = _order_edges(classes)
    for cycle in _find_cycles(edges):
        a, b = cycle[0], cycle[1]
        path, line = edges.get((a, b), ("", 0))
        findings.append(Finding(
            "locks", "lock-order-cycle", path, line, "",
            " -> ".join(cycle),
            f"lock acquisition order cycle: {' -> '.join(cycle)}",
        ))
    return findings


def build_order_report(files: List[ParsedFile]) -> Dict[str, object]:
    """The full tables for --json consumers: per-class write discipline
    and the order graph (used by docs and by tests)."""
    classes: Dict[str, ClassInfo] = {}
    for pf in files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                info = _collect_class(node, pf.path)
                if info.locks:
                    classes[info.name] = info
    edges = _order_edges(classes)
    return {
        "classes": {
            cname: {
                "locks": sorted(info.locks),
                "mixed": sorted(
                    attr for attr, (g, u) in info.writes.items() if g and u
                ),
            }
            for cname, info in sorted(classes.items())
        },
        "order_edges": sorted(f"{a} -> {b}" for a, b in edges),
    }
