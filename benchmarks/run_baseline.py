#!/usr/bin/env python3
"""BASELINE.md measurement harness: runs the five BASELINE.json configs.

The reference publishes no numbers (BASELINE.md); this harness produces the
framework-side column of the measurement table.  Each config prints one JSON
line; ``--all`` runs every config feasible on the current host and writes
``benchmarks/results.json`` (override with ``--out``).

Configs (BASELINE.md "Measurement plan"):
  1. Single-source BFS, RMAT-16, 1 query group          (latency-dominated)
  2. Multi-source BFS, 64 groups, RMAT-20, single chip  (the headline TEPS)
  3. Round-robin query sharding across 8 chips, RMAT-22 (when fewer than 8
     devices are present, re-runs itself in a subprocess on a virtual
     8-device CPU mesh; scale capped by RAM)
  4. Grid road-network (USA-road-d stand-in), high diameter
  5. Vertex-sharded CSR (RMAT-27-class; scaled-down shape on one host;
     needs >= 2 devices, same virtual-mesh fallback as config 3)
  6. Road-class graph on the vertex-sharded engine (chunked dispatches +
     compacted sparse halo + in-block push; needs >= 2 devices)

Usage: python benchmarks/run_baseline.py [--config N] [--all] [--scale-cap S]
                                         [--engine bitbell|bell|packed] [--out F]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from virtual_cpu import virtual_cpu_env  # noqa: E402


ENGINE = "bitbell"  # set by --engine


def _engine_for(graph, kind: str = None, edge_chunks: int = 8):
    kind = kind or ENGINE
    if kind in ("bell", "bitbell"):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
            BellGraph,
        )

        bg = BellGraph.from_host(graph)
        if kind == "bitbell":
            from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
                BitBellEngine,
            )

            return BitBellEngine(bg)
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bell import (
            BellEngine,
        )

        return BellEngine(bg)
    if kind != "packed":
        raise ValueError(kind)
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        PackedEngine,
    )

    return PackedEngine(graph.to_device(), edge_chunks=edge_chunks)


def _run(engine, queries, e_directed: int, repeats: int = 3):
    import jax

    engine.compile(queries.shape)
    times = []
    out = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = engine.best(queries)
        times.append(time.perf_counter() - t0)
    best_s = min(times)
    k = queries.shape[0]
    return {
        "computation_s": round(best_s, 6),
        "teps": round(k * e_directed / best_s),
        "mean_per_query_s": round(float(np.median(times)) / max(k, 1), 6),
        "minF": int(out[0]),
        "minK_1based": int(out[1]) + 1,
        "device": str(jax.devices()[0]),
        "runs_s": [round(t, 6) for t in times],
    }


def config1():
    """Single-source BFS on RMAT-16."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )

    n, edges = generators.rmat_edges(16, edge_factor=16, seed=42)
    g = CSRGraph.from_edges(n, edges)
    queries = np.array([[0]], dtype=np.int32)
    r = _run(_engine_for(g, edge_chunks=1), queries, g.num_directed_edges)
    return {"config": 1, "workload": "RMAT-16, 1 query, 1 source", **r}


def config2(scale=20):
    """The headline: 64 query groups on RMAT-scale-20, single chip."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 64, max_group=64, seed=43), pad_to=64
    )
    r = _run(_engine_for(g), queries, g.num_directed_edges)
    return {"config": 2, "workload": f"RMAT-{scale}, 64 query groups", **r}


class NeedsDevices(RuntimeError):
    """Config needs more devices than present; main() retries the config in
    a subprocess on a virtual 8-device CPU mesh."""

    def __init__(self, needed: int):
        super().__init__(f"needs >= {needed} devices")
        self.needed = needed


def config3(scale=22):
    """Query sharding over 8 devices."""
    import jax

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.distributed import (
        DistributedEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    # Prefer degraded-but-real sharding on 2..7 accelerators; only a
    # single-device host falls back to the virtual 8-device CPU mesh.
    ndev = len(jax.devices())
    if ndev < 2:
        raise NeedsDevices(8)
    w = min(8, ndev)
    n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 64, max_group=64, seed=43), pad_to=64
    )
    mesh = make_mesh(num_query_shards=w)
    engine = DistributedEngine(mesh, g)
    r = _run(engine, queries, g.num_directed_edges)
    return {
        "config": 3,
        "workload": f"RMAT-{scale}, 64 groups, {w}-way query sharding",
        "devices": w,
        **r,
    }


def config4(scale=20, kind="road"):
    """High-diameter road-network distance-to-set (BASELINE config 4).

    ``kind="road"``: the USA-road-d-calibrated synthetic road network
    (models.generators.road_edges — the real dataset is unreachable from
    this sandbox; `gen_cli --convert` ingests it on hosts that have it).
    ``kind="grid"`` keeps the round-1 512x512 plain-grid workload for
    comparability with earlier rounds.

    Headline = the CLI's actual auto route for this graph class: the
    HYBRID bitbell with bounded dispatches (on road graphs nearly every
    level qualifies for the budgeted push scatter, so it is NOT O(D*E) in
    practice — measured 10.7 s vs the vmapped push engine's 77.5 s on
    road-1024/K=16, benchmarks/raw_r4/road_single_shootout2.txt).  The
    push engines stay as comparison rows: ``push`` (vmapped per-query)
    and ``ppush`` (packed-lane union frontier, ops.push_packed).
    """
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.push import (
        PaddedAdjacency,
        PushEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.push_packed import (
        PackedPushEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    side = 1 << (scale // 2)
    if kind == "road":
        n, edges = generators.road_edges(side, side, seed=46)
        name = f"synthetic-road {side}x{side} (USA-road-d calibrated)"
    else:
        n, edges = generators.grid_edges(side, side)
        name = f"{side}x{side} grid (diam ~{2 * side})"
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 16, max_group=8, seed=44), pad_to=8
    )
    # The CLI's auto bound so the row measures the product path, dispatch
    # bound included (imported, not copied: if the policy retunes, this
    # row must keep tracking it).
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
        _AUTO_LEVEL_CHUNK,
    )

    headline = _run(
        BitBellEngine(BellGraph.from_host(g), level_chunk=_AUTO_LEVEL_CHUNK),
        queries,
        g.num_directed_edges,
    )
    rec = {
        "config": 4,
        "workload": f"{name}, 16 groups, chunked hybrid bitbell "
        "(the -gn 1 auto route)",
        **headline,
    }
    adj = PaddedAdjacency.from_host(g)  # capacity state lives on engines
    for key, build in (
        ("push", lambda: PushEngine(adj)),
        ("ppush", lambda: PackedPushEngine(adj)),
    ):
        r = _run(build(), queries, g.num_directed_edges)
        rec.update({f"{key}_{kk}": vv for kk, vv in r.items()})
        if r["minF"] != headline["minF"] or (
            r["minK_1based"] != headline["minK_1based"]
        ):
            raise AssertionError(f"config 4 engine disagreement: {key}")
    return rec


def config5(scale=20):
    """Vertex-sharded CSR over the full ('q','v') mesh."""
    import jax

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_csr import (
        ShardedEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    ndev = len(jax.devices())
    if ndev < 2:
        raise NeedsDevices(2)
    n_v = 2
    n_q = max(1, min(4, ndev // n_v))
    n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 16, max_group=16, seed=45), pad_to=16
    )
    mesh = make_mesh(num_query_shards=n_q, num_vertex_shards=n_v)
    if ENGINE == "bitbell":
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
            ShardedBellEngine,
        )

        engine = ShardedBellEngine(mesh, g)
    else:  # bell/packed: the boolean-halo sharded CSR path
        engine = ShardedEngine(mesh, g)
    r = _run(engine, queries, g.num_directed_edges)
    return {
        "config": 5,
        "workload": f"RMAT-{scale}, CSR sharded ({n_q}q x {n_v}v mesh)",
        **r,
    }


def config6(scale=18):
    """Road-class graph on the VERTEX-SHARDED engines: the round-3
    sharded bitbell (chunked + compacted sparse halo + in-block push)
    vs the round-4 owner-partitioned push (parallel.push_sharded), the
    work-optimal path whose per-level cost follows the wavefront instead
    of the edge partition.  Complements config 4 (single-chip push
    engine) with the multi-chip path; the ``sharded_push`` sub-record is
    the headline, the bitbell one the pull-side comparison."""
    import jax

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.push_sharded import (
        ShardedPushEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
        ShardedBellEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    ndev = len(jax.devices())
    if ndev < 2:
        raise NeedsDevices(2)
    n_v = min(4, ndev)
    n_q = max(1, ndev // n_v)
    side = 1 << (scale // 2)
    n, edges = generators.road_edges(side, side, seed=46)
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 16, max_group=8, seed=44), pad_to=8
    )
    mesh = make_mesh(num_query_shards=n_q, num_vertex_shards=n_v)
    push = _run(
        ShardedPushEngine(mesh, g), queries, g.num_directed_edges
    )
    bitbell = _run(
        ShardedBellEngine(mesh, g, level_chunk=32),
        queries,
        g.num_directed_edges,
    )
    return {
        "config": 6,
        "workload": (
            f"synthetic-road {side}x{side}, 16 groups, vertex-sharded "
            f"({n_q}q x {n_v}v)"
        ),
        **{f"sharded_push_{k}": v for k, v in push.items()},
        **{f"sharded_bitbell_{k}": v for k, v in bitbell.items()},
        # Headline fields stay the best of the two (the row's purpose is
        # "fastest multi-chip road path").
        **(
            push
            if push["computation_s"] <= bitbell["computation_s"]
            else bitbell
        ),
    }


CONFIGS = {
    1: config1, 2: config2, 3: config3, 4: config4, 5: config5, 6: config6,
}
# Default RMAT scale per config, cappable with --scale-cap (RAM-limited hosts).
SCALES = {2: 20, 3: 22, 4: 20, 5: 20, 6: 18}



def _call(c: int, args):
    kwargs = {}
    if c in SCALES:
        kwargs["scale"] = (
            min(SCALES[c], args.scale_cap) if args.scale_cap else SCALES[c]
        )
    return CONFIGS[c](**kwargs)


def _run_in_cpu_mesh(c: int, args):
    """Re-run one config in a subprocess with a virtual 8-device CPU mesh
    (the multi-chip test posture of tests/conftest.py)."""
    import subprocess

    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--config",
        str(c),
        "--engine",
        args.engine,
    ]
    if args.scale_cap:
        cmd += ["--scale-cap", str(args.scale_cap)]
    env = virtual_cpu_env(8)
    # Sentinel so the child doesn't recurse into another fallback; a user's
    # own JAX_PLATFORMS=cpu must NOT suppress the fallback (their plain CPU
    # run has one device and still needs the virtual mesh).
    env["MSBFS_BASELINE_CPU_MESH"] = "1"
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
    for line in proc.stdout.splitlines():
        try:
            return {**json.loads(line), "cpu_mesh_fallback": True}
        except json.JSONDecodeError:
            continue
    return {
        "config": c,
        "error": f"cpu-mesh subprocess failed: {proc.stderr.strip()[-400:]}",
    }


def main() -> int:
    # Persistent XLA cache: compiles through the tunnel run minutes-long
    # (docs/PERF_NOTES.md), and every config otherwise pays its own.
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.xla_cache import (
        configure_compilation_cache,
    )

    configure_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, choices=sorted(CONFIGS))
    ap.add_argument("--all", action="store_true")
    ap.add_argument(
        "--scale-cap",
        type=int,
        default=None,
        help="cap RMAT scales (configs 2/3/5) for RAM-limited hosts",
    )
    ap.add_argument(
        "--engine", choices=("bitbell", "bell", "packed"), default="bitbell"
    )
    ap.add_argument(
        "--out",
        default=None,
        help="results JSON path (default with --all: benchmarks/results.json)",
    )
    args = ap.parse_args()
    global ENGINE
    ENGINE = args.engine
    if args.all and args.out is None:
        args.out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results.json")

    todo = sorted(CONFIGS) if args.all or args.config is None else [args.config]
    results = []
    for c in todo:
        try:
            r = _call(c, args)
        except NeedsDevices as exc:
            if os.environ.get("MSBFS_BASELINE_CPU_MESH"):
                r = {"config": c, "error": f"{type(exc).__name__} on CPU mesh"}
            else:
                r = _run_in_cpu_mesh(c, args)
        except Exception as exc:  # keep going: one infeasible config
            r = {"config": c, "error": f"{type(exc).__name__}: {exc}"}
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
