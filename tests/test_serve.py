"""Serving-runtime tests (docs/SERVING.md): protocol framing, shape
bucketing, the graph registry, micro-batch coalescing, the
compiled-executable and result caches (hit/invalidate on reload),
backpressure rejection, and fault-injected requests failing typed while
the daemon keeps serving — all against an in-process server on a real
unix socket.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.scheduler import (
    pack_padded_requests,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (
    BackpressureError,
    MsbfsError,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve import (
    protocol,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.batcher import (
    pow2_pad,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.caches import (
    ExecutableCache,
    LRUCache,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
    MsbfsClient,
    ServerError,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.registry import (
    GraphRegistry,
    content_hash,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.server import (
    MsbfsServer,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils import (
    faults,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    save_graph_bin,
)

from oracle import oracle_bfs, oracle_f


# ---------------------------------------------------------------------------
# Pure units: framing, bucketing, packing, caches
# ---------------------------------------------------------------------------


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    with a, b:
        protocol.send_frame(a, {"op": "ping", "n": 3})
        assert protocol.recv_frame(b) == {"op": "ping", "n": 3}
        a.close()
        assert protocol.recv_frame(b) is None  # clean EOF


def test_frame_rejects_oversized_prefix():
    a, b = socket.socketpair()
    with a, b:
        a.sendall(struct.pack("!I", protocol.max_frame_bytes() + 1))
        with pytest.raises(protocol.ProtocolError, match="bound"):
            protocol.recv_frame(b)


def test_frame_rejects_non_object_and_mid_frame_eof():
    a, b = socket.socketpair()
    with a, b:
        body = b"[1,2,3]"
        a.sendall(struct.pack("!I", len(body)) + body)
        with pytest.raises(protocol.ProtocolError, match="object"):
            protocol.recv_frame(b)
    a, b = socket.socketpair()
    with b:
        with a:
            a.sendall(struct.pack("!I", 10) + b"tru")
        with pytest.raises(protocol.ProtocolError, match="mid-frame"):
            protocol.recv_frame(b)


def test_parse_address_forms():
    assert protocol.parse_address("unix:/tmp/x.sock") == (
        socket.AF_UNIX,
        "/tmp/x.sock",
    )
    assert protocol.parse_address("127.0.0.1:9999") == (
        socket.AF_INET,
        ("127.0.0.1", 9999),
    )
    for bad in ("unix:", "nohost", "host:notaport"):
        with pytest.raises(ValueError):
            protocol.parse_address(bad)


def test_pow2_bucketing_policy():
    assert [pow2_pad(x) for x in (0, 1, 2, 3, 4, 5, 63, 64, 65)] == [
        1, 1, 2, 4, 4, 8, 64, 64, 128,
    ]


def test_pack_padded_requests_layout_and_bounds():
    b1 = np.array([[1, 2], [3, -1]], dtype=np.int32)
    b2 = np.array([[7]], dtype=np.int32)
    batch, offsets = pack_padded_requests([b1, b2], k_exec=4, s_pad=4)
    assert batch.shape == (4, 4) and offsets == [0, 2, 3]
    assert batch[0].tolist() == [1, 2, -1, -1]
    assert batch[2].tolist() == [7, -1, -1, -1]
    assert (batch[3] == -1).all()
    with pytest.raises(ValueError, match="exceed"):
        pack_padded_requests([b1, b1, b1], k_exec=4, s_pad=4)
    wide = np.zeros((1, 8), dtype=np.int32)
    with pytest.raises(ValueError, match="width"):
        pack_padded_requests([wide], k_exec=8, s_pad=4)


def test_lru_cache_evicts_counts_and_disables():
    c = LRUCache(2)
    c.put("a", 1), c.put("b", 2)
    assert c.get("a") == 1  # refreshes a
    c.put("c", 3)  # evicts b (LRU)
    assert c.get("b") is None and c.get("c") == 3
    snap = c.snapshot()
    assert snap["evictions"] == 1 and snap["hits"] == 2 and snap["misses"] == 1
    off = LRUCache(0)
    off.put("a", 1)
    assert off.get("a") is None and len(off) == 0


def test_executable_cache_warms_once():
    ex = ExecutableCache()
    calls = []
    assert ex.warm(("g", 1, 4, 4), "g:4x4", lambda: calls.append(1)) is True
    assert ex.warm(("g", 1, 4, 4), "g:4x4", lambda: calls.append(1)) is False
    assert ex.warm(("g", 1, 8, 4), "g:8x4", lambda: calls.append(1)) is True
    assert calls == [1, 1]
    assert ex.compiles() == {"g:4x4": 1, "g:8x4": 1}
    assert ex.total_compiles() == 2


# ---------------------------------------------------------------------------
# Registry: load-once, content hashing, reload versioning
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def graph_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_graphs")
    n, edges = generators.gnm_edges(120, 360, seed=5)
    n2, edges2 = generators.gnm_edges(120, 360, seed=6)
    p1, p2 = str(d / "g1.bin"), str(d / "g2.bin")
    save_graph_bin(p1, n, edges)
    save_graph_bin(p2, n2, edges2)
    return (n, edges, p1), (n2, edges2, p2)


def test_registry_load_once_and_conflict(graph_files):
    (n, _, p1), (_, _, p2) = graph_files
    reg = GraphRegistry()
    e1 = reg.load("g", p1)
    assert e1.version == 1 and e1.graph.n == n
    assert reg.load("g", p1) is e1  # same bytes: no reload, same entry
    with pytest.raises(MsbfsError, match="different content"):
        reg.load("g", p2)
    assert "no graph registered" in str(
        pytest.raises(MsbfsError, reg.get, "missing").value
    )


def test_registry_reload_bumps_version_and_key(graph_files, tmp_path):
    (n, edges, p1), (n2, edges2, _) = graph_files
    path = str(tmp_path / "mut.bin")
    save_graph_bin(path, n, edges)
    reg = GraphRegistry()
    e1 = reg.load("g", path)
    save_graph_bin(path, n2, edges2)  # operator swaps the file in place
    e2 = reg.reload("g")
    assert e2.version == 2 and e2.hash != e1.hash and e2.key != e1.key
    assert e2.hash == content_hash(path)


# ---------------------------------------------------------------------------
# In-process server over a real unix socket
# ---------------------------------------------------------------------------


@pytest.fixture()
def server(graph_files, tmp_path, monkeypatch):
    """Daemon on a unix socket with fast knobs: zero-length coalescing
    window (tests drive coalescing via hold()), tiny retry budget so
    fault rehearsals are quick, result cache on."""
    (_, _, p1), _ = graph_files
    monkeypatch.setenv("MSBFS_RETRIES", "0")
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    sock = str(tmp_path / "msbfs.sock")
    srv = MsbfsServer(
        listen=f"unix:{sock}",
        graphs={"default": p1},
        queue_capacity=2,
        window_s=0.0,
        request_timeout_s=30.0,
    )
    srv.start()
    yield srv, f"unix:{sock}"
    faults.activate(None)
    srv.stop()


def _mk_queries(rng, n, k, s):
    return [[int(v) for v in rng.integers(0, n, size=s)] for _ in range(k)]


def test_warm_bucket_zero_new_compiles_and_cache_hit(server, graph_files):
    """The acceptance rehearsal: a warm daemon answers a repeat
    same-bucket query with zero new compiles AND a result-cache hit; a
    cold different-bucket query compiles exactly once — all verified by
    the stats verb."""
    srv, addr = server
    (n, edges, _), _ = graph_files
    rng = np.random.default_rng(3)
    qa = _mk_queries(rng, n, 3, 2)  # bucket (4, 2)
    qb = _mk_queries(rng, n, 3, 2)  # same bucket, different ids
    qc = _mk_queries(rng, n, 5, 2)  # bucket (8, 2): cold
    with MsbfsClient(addr) as client:
        ra = client.query(qa)
        assert ra["compiled"] and not ra["cached"]
        assert ra["bucket"] == [4, 2]
        rb = client.query(qb)
        assert rb["bucket"] == ra["bucket"]
        assert not rb["compiled"] and not rb["cached"]
        ra2 = client.query(qa)  # repeat: result-cache hit, no dispatch
        assert ra2["cached"] and ra2["min_f"] == ra["min_f"]
        stats1 = client.stats()
        assert stats1["compiles_total"] == 1
        assert stats1["result_cache"]["hits"] == 1
        rc = client.query(qc)
        assert rc["compiled"] and rc["bucket"] == [8, 2]
        rc2 = client.query(qc)
        assert rc2["cached"]
        stats2 = client.stats()
    # Exactly one compile per bucket, flat across repeats.
    assert stats2["compiles_total"] == 2
    assert sorted(stats2["compiles"].values()) == [1, 1]
    assert stats2["requests_failed"] == 0
    # Results agree with the oracle (the serving path must not change
    # semantics: same F and selection as the batch engines).
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in qa]
    assert ra["f_values"] == want
    assert ra["min_f"] == min(want)
    assert ra["min_k"] == want.index(min(want))


def test_result_cache_invalidated_on_reload(server, graph_files, tmp_path):
    srv, addr = server
    (n, edges, _), (n2, edges2, _) = graph_files
    path = str(tmp_path / "mut.bin")
    save_graph_bin(path, n, edges)
    rng = np.random.default_rng(4)
    q = _mk_queries(rng, min(n, n2), 2, 2)
    with MsbfsClient(addr) as client:
        client.load(path, graph="mut")
        r1 = client.query(q, graph="mut")
        assert client.query(q, graph="mut")["cached"]
        save_graph_bin(path, n2, edges2)
        info = client.reload(graph="mut")
        assert info["graph"]["version"] == 2
        assert info["invalidated_results"] >= 1
        r2 = client.query(q, graph="mut")
        # Fresh compute against the new content, not a stale hit.
        assert not r2["cached"] and r2["version"] == 2
        want = [oracle_f(oracle_bfs(n2, edges2, g)) for g in q]
        assert r2["f_values"] == want
    assert r1["version"] == 1


def test_backpressure_rejects_typed_and_recovers(server, graph_files):
    """Queue capacity 2: hold the batcher, fill the queue, and the next
    request is rejected NOW with the typed BackpressureError (exit 7)
    without being executed; after release the held requests complete and
    new requests are served again."""
    srv, addr = server
    (n, _, _), _ = graph_files
    rng = np.random.default_rng(5)
    srv.batcher.hold()
    held_results = []

    def held_query(k):
        with MsbfsClient(addr) as c:
            held_results.append(c.query(_mk_queries(rng, n, k, 2)))

    threads = [
        threading.Thread(target=held_query, args=(k,)) for k in (2, 3)
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 10
    while srv.batcher.depth() < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert srv.batcher.depth() == 2
    with MsbfsClient(addr) as c:
        with pytest.raises(ServerError, match="queue full") as exc:
            c.query(_mk_queries(rng, n, 2, 2))
        assert exc.value.type_name == "BackpressureError"
        assert exc.value.exit_code == BackpressureError.exit_code == 7
    srv.batcher.release()
    for t in threads:
        t.join(30)
    assert len(held_results) == 2 and all(r["ok"] for r in held_results)
    with MsbfsClient(addr) as c:
        assert c.query(_mk_queries(rng, n, 2, 2))["ok"]
        stats = c.stats()
    assert stats["queue"]["rejected"] == 1
    assert stats["queue"]["depth"] == 0


def test_coalesced_batch_single_dispatch(server, graph_files):
    """Two same-bucket requests queued together execute as ONE batch
    (stats: coalesced >= 1) and both get correct per-request slices."""
    srv, addr = server
    (n, edges, _), _ = graph_files
    rng = np.random.default_rng(6)
    q1, q2 = _mk_queries(rng, n, 2, 2), _mk_queries(rng, n, 2, 2)
    srv.batcher.hold()
    results = {}

    def go(tag, q):
        with MsbfsClient(addr) as c:
            results[tag] = c.query(q)

    threads = [
        threading.Thread(target=go, args=("a", q1)),
        threading.Thread(target=go, args=("b", q2)),
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 10
    while srv.batcher.depth() < 2 and time.time() < deadline:
        time.sleep(0.01)
    srv.batcher.release()
    for t in threads:
        t.join(30)
    assert results["a"]["batched_with"] == 1
    assert results["b"]["batched_with"] == 1
    # 2+2 rows -> one (4, 2) execution for both requests.
    assert results["a"]["bucket"] == results["b"]["bucket"] == [4, 2]
    for q, r in ((q1, results["a"]), (q2, results["b"])):
        assert r["f_values"] == [oracle_f(oracle_bfs(n, edges, g)) for g in q]


def test_fault_injected_request_fails_typed_daemon_survives(
    server, graph_files
):
    """MSBFS_FAULTS rehearsal (satellite): with the retry budget at 0, a
    transient dispatch fault fails exactly one request with the typed
    TransientError (exit 5) on the wire; the daemon answers the next
    request normally."""
    srv, addr = server
    (n, _, _), _ = graph_files
    rng = np.random.default_rng(8)
    with MsbfsClient(addr) as c:
        assert c.query(_mk_queries(rng, n, 2, 2))["ok"]  # warm, fault-free
        plan = faults.FaultPlan.parse("transient:dispatch:1")
        faults.activate(plan)
        with pytest.raises(ServerError) as exc:
            c.query(_mk_queries(rng, n, 2, 2))
        assert exc.value.type_name == "TransientError"
        assert exc.value.exit_code == 5
        faults.activate(None)
        after = c.query(_mk_queries(rng, n, 2, 2))
        assert after["ok"] and not after["compiled"]
        stats = c.stats()
    assert stats["requests_failed"] == 1
    assert stats["graphs"]["default"]["version"] == 1  # same engine, alive


def test_fault_plan_from_env_fires_on_nth_dispatch(
    graph_files, tmp_path, monkeypatch
):
    """The daemon arms MSBFS_FAULTS at start() exactly like the batch
    CLI: dispatches count across warm compile (1) and first query (2),
    so a plan at trip 3 fails the second query, typed, and the third
    succeeds."""
    (_, _, p1), _ = graph_files
    monkeypatch.setenv("MSBFS_RETRIES", "0")
    monkeypatch.setenv("MSBFS_FAULTS", "transient:dispatch:3")
    sock = str(tmp_path / "f.sock")
    srv = MsbfsServer(
        listen=f"unix:{sock}", graphs={"default": p1}, window_s=0.0
    )
    srv.start()
    try:
        rng = np.random.default_rng(9)
        n = srv.registry.get("default").graph.n
        with MsbfsClient(f"unix:{sock}") as c:
            assert c.query(_mk_queries(rng, n, 2, 2))["ok"]
            with pytest.raises(ServerError) as exc:
                c.query(_mk_queries(rng, n, 2, 2))
            assert exc.value.type_name == "TransientError"
            assert c.query(_mk_queries(rng, n, 2, 2))["ok"]
    finally:
        faults.activate(None)
        srv.stop()


def test_wire_input_errors_are_typed(server):
    srv, addr = server
    with MsbfsClient(addr) as c:
        for req, mark in (
            ({"op": "nope"}, "unknown op"),
            ({"op": "query", "graph": "default"}, "non-empty"),
            ({"op": "query", "graph": "default", "queries": [[]]},
             "non-empty"),
            ({"op": "query", "graph": "ghost", "queries": [[1]]},
             "no graph registered"),
            ({"op": "load"}, "path"),
        ):
            with pytest.raises(ServerError, match=mark) as exc:
                c.call(req)
            assert exc.value.exit_code == 1  # InputError on the wire
        # The connection survives every typed error above.
        assert c.ping()


def test_query_main_cli_end_to_end(server, graph_files, tmp_path, capsys):
    """The thin client CLI: reference-style selection lines on stdout,
    exit 0; --stats renders the report; server errors map to exit
    codes."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
        main,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_query_bin,
    )

    srv, addr = server
    (n, edges, _), _ = graph_files
    qpath = str(tmp_path / "q.bin")
    queries = generators.random_queries(n, 3, max_group=2, seed=13)
    save_query_bin(qpath, queries)
    rc = main(["main.py", "query", "--connect", addr, "-q", qpath])
    out = capsys.readouterr()
    assert rc == 0
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    assert f"Minimum F value: {min(want)}" in out.out
    assert f"minimum F value: {want.index(min(want)) + 1}" in out.out
    rc = main(["main.py", "query", "--connect", addr, "--stats"])
    out = capsys.readouterr()
    assert rc == 0 and "result cache:" in out.out
    rc = main(
        ["main.py", "query", "--connect", addr, "--graph", "ghost",
         "-q", qpath]
    )
    out = capsys.readouterr()
    assert rc == 1 and "no graph registered" in out.err


def test_batch_cli_contract_untouched(graph_files, tmp_path, capsys):
    """The reference argv contract survives the subcommand dispatch:
    plain -g/-q/-gn runs the batch path and short argv still usages."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
        main,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_query_bin,
    )

    (n, edges, p1), _ = graph_files
    qpath = str(tmp_path / "q.bin")
    queries = generators.random_queries(n, 2, max_group=2, seed=14)
    save_query_bin(qpath, queries)
    rc = main(["main.py", "-g", p1, "-q", qpath, "-gn", "1"])
    out = capsys.readouterr()
    assert rc == 0 and "Minimum F value:" in out.out
    rc = main(["main.py", "-g", "x"])
    out = capsys.readouterr()
    assert rc == -1 and "Usage:" in out.err
