"""Backend identity helpers.

One definition of "running on TPU hardware" for the whole package: the
axon tunnel platform reports itself as ``axon`` rather than ``tpu``, and a
missed site means a guard or test-skip silently stops firing there.
"""

from __future__ import annotations

TPU_BACKENDS = ("tpu", "axon")


def is_tpu_backend() -> bool:
    import jax

    return jax.default_backend() in TPU_BACKENDS
