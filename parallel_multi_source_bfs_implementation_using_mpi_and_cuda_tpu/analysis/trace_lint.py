"""Trace-safety lint for ``ops/`` and ``parallel/``.

Three rules over functions that run under a JAX trace — decorated with
``jit``/``donating_jit`` (directly or via ``partial``), passed by name
or as a lambda into a trace-entering combinator (``lax.while_loop``,
``scan``, ``cond``, ``switch``, ``fori_loop``, ``vmap``, ``pmap``,
``shard_map``, ``remat``/``checkpoint``, ``jit`` as a call), or defined
inside such a function:

* ``host-sync-in-trace`` — ``int()``/``bool()``/``float()`` on a value
  that is not provably concrete (shape/len/ndim/constant arguments are
  exempt), ``.item()``, ``np.asarray``/``np.array``, and
  ``jax.device_get`` all force a device→host transfer of a tracer.
* ``impure-read-in-trace`` — ``time.*``, ``random.*``/``np.random.*``,
  ``os.environ``/``os.getenv`` and ``knobs.*`` reads are frozen at
  trace time; under the compilation cache they silently stop varying.
* ``unrecorded-commit`` — a function that blocks on device results
  (``.block_until_ready()``, ``jax.block_until_ready``, or a top-level
  ``jax.device_get``) without calling ``utils.timing.record_dispatch``
  breaks the one-dispatch-per-commit accounting the perf gates pin.

Resolution is per-module and single-level by design: a helper called
*from* a traced function is not followed.  That keeps the pass O(tree)
and its findings local enough to act on.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, ParsedFile, dotted, enclosing_symbols

TRACE_ENTRY_CALLS = {
    "while_loop", "fori_loop", "scan", "cond", "switch",
    "vmap", "pmap", "jit", "shard_map", "remat", "checkpoint",
}
TRACE_DECORATORS = {"jit", "donating_jit"}
CONCRETE_MARKERS = {"shape", "ndim", "len", "range", "size"}
IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")
IMPURE_EXACT = {"os.getenv", "os.environ.get"}
KNOB_READS = {"knobs.raw", "knobs.get_int", "knobs.get_float", "knobs.get_str"}
BLOCKING_ATTRS = {"block_until_ready"}
RECORDERS = {"record_dispatch"}


def _is_concrete_arg(node: ast.AST) -> bool:
    """True when the argument of int()/bool()/float() is provably a host
    value: a constant, or any expression mentioning .shape/.ndim/len()."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in CONCRETE_MARKERS:
            return True
        if isinstance(sub, ast.Call):
            name = dotted(sub.func)
            if name in ("len", "range"):
                return True
    return False


def _decorated_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in TRACE_DECORATORS:
            return True
        # functools.partial(jax.jit, ...) / partial(donating_jit, ...)
        if isinstance(dec, ast.Call) and leaf == "partial" and dec.args:
            inner = dotted(dec.args[0]) or ""
            if inner.rsplit(".", 1)[-1] in TRACE_DECORATORS:
                return True
    return False


def _collect_traced(tree: ast.AST) -> Set[ast.AST]:
    """All function/lambda nodes whose bodies run under a trace."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and _decorated_traced(node):
            traced.add(node)
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name.rsplit(".", 1)[-1] not in TRACE_ENTRY_CALLS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name):
                    for d in defs_by_name.get(arg.id, []):
                        traced.add(d)

    # Functions defined inside a traced function execute at trace time.
    grew = True
    while grew:
        grew = False
        for t in list(traced):
            for sub in ast.walk(t):
                if sub is not t and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ) and sub not in traced:
                    traced.add(sub)
                    grew = True
    return traced


def _scan_traced_body(pf: ParsedFile, fn: ast.AST, symbol: str, findings: List[Finding]) -> None:
    own_nested = {
        sub for sub in ast.walk(fn)
        if sub is not fn
        and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
    }

    def nodes():
        # Walk the body but attribute nested-def findings to the nested
        # def's own scan (they are traced too); avoid double reports.
        for sub in ast.walk(fn):
            if any(sub is n or _contains(n, sub) for n in own_nested):
                continue
            yield sub

    for sub in nodes():
        if isinstance(sub, ast.Call):
            name = dotted(sub.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if name in ("int", "bool", "float") and sub.args and not _is_concrete_arg(sub.args[0]):
                findings.append(Finding(
                    "trace", "host-sync-in-trace", pf.path, sub.lineno, symbol,
                    f"{name}()",
                    f"{name}() on a possibly-traced value forces a host sync inside a traced function",
                ))
            elif leaf == "item" and isinstance(sub.func, ast.Attribute):
                findings.append(Finding(
                    "trace", "host-sync-in-trace", pf.path, sub.lineno, symbol,
                    ".item()", ".item() forces a host sync inside a traced function",
                ))
            elif name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
                findings.append(Finding(
                    "trace", "host-sync-in-trace", pf.path, sub.lineno, symbol,
                    name, f"{name} materialises a tracer on host inside a traced function",
                ))
            elif name in ("jax.device_get", "device_get"):
                findings.append(Finding(
                    "trace", "host-sync-in-trace", pf.path, sub.lineno, symbol,
                    "device_get", "device_get inside a traced function",
                ))
            elif name.startswith(IMPURE_PREFIXES) or name in IMPURE_EXACT or name in KNOB_READS:
                findings.append(Finding(
                    "trace", "impure-read-in-trace", pf.path, sub.lineno, symbol,
                    name, f"{name} is frozen at trace time inside a traced function",
                ))
        elif isinstance(sub, ast.Subscript):
            if (dotted(sub.value) or "") == "os.environ":
                findings.append(Finding(
                    "trace", "impure-read-in-trace", pf.path, sub.lineno, symbol,
                    "os.environ[]", "os.environ read is frozen at trace time inside a traced function",
                ))


def _contains(parent: ast.AST, node: ast.AST) -> bool:
    return any(sub is node for sub in ast.walk(parent))


def _scan_commits(pf: ParsedFile, symbols: Dict[ast.AST, str], findings: List[Finding]) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        blocking: List[ast.Call] = []
        records = False
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and sub is not node:
                continue
            if isinstance(sub, ast.Call):
                name = dotted(sub.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                if leaf in BLOCKING_ATTRS or name in ("jax.block_until_ready", "jax.device_get"):
                    blocking.append(sub)
                if leaf in RECORDERS:
                    records = True
        if blocking and not records:
            first = blocking[0]
            findings.append(Finding(
                "trace", "unrecorded-commit", pf.path, first.lineno,
                symbols.get(node, node.name), node.name,
                f"{node.name} blocks on device results without record_dispatch "
                "(one-dispatch-per-commit accounting)",
            ))


def run(files: List[ParsedFile]) -> List[Finding]:
    findings: List[Finding] = []
    for pf in files:
        symbols = enclosing_symbols(pf.tree)
        traced = _collect_traced(pf.tree)
        for fn in traced:
            sym = symbols.get(fn, "")
            name = getattr(fn, "name", "<lambda>")
            label = sym if sym.endswith(name) or name == "<lambda>" else (sym or name)
            _scan_traced_body(pf, fn, label or name, findings)
        _scan_commits(pf, symbols, findings)
    return findings
