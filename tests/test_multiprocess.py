"""Real multi-process bring-up: the mpirun analog, actually executed.

The reference runs W OS processes joined by MPI_Init over a network
(main.cu:197-201); its collectives then move graph/query/result data
between them (main.cu:242-368).  The TPU-native analog is
``jax.distributed.initialize`` + a global mesh whose devices span
processes, with XLA inserting the collectives.  This test launches TWO
actual OS processes (each holding 2 virtual CPU devices), runs
DistributedEngine over the resulting 4-device global mesh, and asserts
both processes independently report the single-process answer — the
replicated result array IS the broadcast the reference does by hand.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from virtual_cpu import virtual_cpu_env  # noqa: E402

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E402
    generators,
)

from oracle import oracle_best, oracle_bfs, oracle_f  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# The two-process cluster tests cannot pass on this container's jaxlib:
# every cross-process collective dies with "Multiprocess computations
# aren't implemented on the CPU backend" (raised from device_put's
# multihost assert_equal before any BFS work starts), and the three
# spin-ups burn ~14 s of the tier-1 wall-clock budget failing.  They
# are slow-marked so tier-1 skips the known-impossible arms; run them
# explicitly (python -m pytest tests/test_multiprocess.py) on a jaxlib
# with multi-process CPU support.  test_initialize_distributed_
# propagates_bad_cluster needs no collective and stays tier-1.
_two_process = pytest.mark.slow


@_two_process
def test_two_process_cluster_matches_single_process():
    nproc, local_devices = 2, 2
    port = _free_port()
    env = virtual_cpu_env(local_devices)
    worker = os.path.join(REPO, "tests", "mp_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{port}", str(nproc), str(pid)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    # Same seeds as mp_worker.py: independent single-process oracle answer.
    n, edges = generators.gnm_edges(120, 400, seed=821)
    queries = generators.random_queries(n, 10, max_group=5, seed=822)
    want_f, want_k = oracle_best(
        [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    )

    for r in outs:
        assert r["process_count"] == nproc
        assert r["global_devices"] == nproc * local_devices
        assert r["local_devices"] == local_devices
        assert (r["min_f"], r["min_k"]) == (want_f, want_k), r
        # Vertex-sharded run whose halo collectives crossed the process
        # boundary (mp_worker interleaves the 'v' axis over processes).
        assert (r["sharded_min_f"], r["sharded_min_k"]) == (want_f, want_k), r
        # Owner-partitioned push whose boundary-pair exchange crossed the
        # process boundary (round 4).
        assert (r["push_min_f"], r["push_min_k"]) == (want_f, want_k), r
    assert outs[0]["process_id"] != outs[1]["process_id"]


def test_initialize_distributed_propagates_bad_cluster():
    """Explicit-arg bring-up failures must NOT be swallowed (VERDICT: the
    old try/except hid genuine errors).  Run in a subprocess: a failed
    jax.distributed.initialize must not poison this test process."""
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu."
        "parallel.mesh import initialize_distributed\n"
        "try:\n"
        "    initialize_distributed(coordinator_address='127.0.0.1:1',"
        " num_processes=2, process_id=1, initialization_timeout=5)\n"
        "except Exception as e:\n"
        "    print('RAISED', type(e).__name__, flush=True); sys.exit(0)\n"
        "print('SWALLOWED', flush=True); sys.exit(1)\n" % REPO
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=virtual_cpu_env(2),
        capture_output=True,
        text=True,
        timeout=300,
        cwd=REPO,
    )
    # A genuine bring-up failure must be LOUD: either a raised exception
    # (rc 0 + RAISED marker) or the coordination client's own fatal abort
    # (nonzero rc WITH a recognizable bring-up signature — an unrelated
    # crash, e.g. a broken import, must still fail this test).  What it
    # must never do is return as if the cluster came up — the swallow bug
    # this test was written against.
    assert "SWALLOWED" not in proc.stdout, proc.stdout
    if proc.returncode == 0:
        assert "RAISED" in proc.stdout, (proc.stdout, proc.stderr[-2000:])
    else:
        blob = proc.stderr + proc.stdout
        assert any(
            sig in blob
            for sig in (
                "DEADLINE_EXCEEDED",
                "Coordination",
                "coordination",
                "distributed service",
            )
        ), (proc.returncode, blob[-2000:])


@_two_process
def test_two_process_cli_end_to_end(tmp_path):
    """The full reference surface across processes: two OS processes run
    ``main.py`` itself (one per "host", MSBFS_COORDINATOR env bring-up —
    the mpirun analog at the CLI level), over the same graph/query files.
    Process 0 prints the reference report with the oracle answer; process
    1 computes but stays silent on stdout (rank-0-only contract,
    main.cu:403-414)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_graph_bin,
        save_query_bin,
    )

    n, edges = generators.gnm_edges(100, 320, seed=823)
    queries = generators.random_queries(n, 8, max_group=4, seed=824)
    gpath, qpath = str(tmp_path / "g.bin"), str(tmp_path / "q.bin")
    save_graph_bin(gpath, n, edges)
    save_query_bin(qpath, [list(map(int, q)) for q in queries])
    want_f, want_k = oracle_best(
        [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    )

    nproc, port = 2, _free_port()
    base = virtual_cpu_env(2)
    procs = []
    for pid in range(nproc):
        env = dict(
            base,
            MSBFS_COORDINATOR=f"127.0.0.1:{port}",
            MSBFS_NUM_PROCESSES=str(nproc),
            MSBFS_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, os.path.join(REPO, "main.py"),
                    "-g", gpath, "-q", qpath, "-gn", "4",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process CLI timed out")
        assert p.returncode == 0, f"CLI worker failed:\n{err[-3000:]}"
        outs.append(out)
    assert f"Query number (k) with minimum F value: {want_k + 1}" in outs[0]
    assert f"Minimum F value: {want_f}" in outs[0]
    assert "GPU # : 4 GPU" in outs[0]
    # Non-zero ranks print NO report (rank-0-only contract); the Gloo
    # transport may chat on stdout, so assert on the report lines.
    assert "Minimum F value" not in outs[1]
    assert "Graph:" not in outs[1]


@_two_process
def test_two_process_cli_gn_below_global(tmp_path):
    """Multi-host with -gn smaller than the global device count: -gn is
    devices PER HOST (the reference's per-rank binding, main.cu:227-228),
    so -gn 1 on a 2-host x 2-device cluster builds a 2-device mesh with
    one chip from EACH process — not host 0's two chips, which would hand
    rank 1 non-addressable devices (round-3 review finding)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_graph_bin,
        save_query_bin,
    )

    n, edges = generators.gnm_edges(80, 240, seed=825)
    queries = generators.random_queries(n, 6, max_group=3, seed=826)
    gpath, qpath = str(tmp_path / "g.bin"), str(tmp_path / "q.bin")
    save_graph_bin(gpath, n, edges)
    save_query_bin(qpath, [list(map(int, q)) for q in queries])
    want_f, want_k = oracle_best(
        [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    )

    nproc, port = 2, _free_port()
    base = virtual_cpu_env(2)
    procs = []
    for pid in range(nproc):
        env = dict(
            base,
            MSBFS_COORDINATOR=f"127.0.0.1:{port}",
            MSBFS_NUM_PROCESSES=str(nproc),
            MSBFS_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, os.path.join(REPO, "main.py"),
                    "-g", gpath, "-q", qpath, "-gn", "1",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process CLI (-gn 1) timed out")
        assert p.returncode == 0, f"CLI worker failed:\n{err[-3000:]}"
        outs.append(out)
    assert f"Query number (k) with minimum F value: {want_k + 1}" in outs[0]
    assert f"Minimum F value: {want_f}" in outs[0]
    assert "GPU # : 1 GPU" in outs[0]  # reported verbatim (main.cu:411)
    assert "Minimum F value" not in outs[1]
