"""Bucketed delta-stepping: distance-to-set over integer edge costs.

The level loop of every unit-cost engine in this repo is a degenerate
delta-stepping run with delta = 1: each "bucket" is one BFS level, every
edge is light, and the per-level OR over bit planes is the relaxation.
This module generalizes that loop to positive integer costs (Meyer &
Sanders' delta-stepping) while keeping the repo's execution shape:

* tentative distances are **word planes** — a (K, n) int32 array, one
  row per query group, exactly the layout the donation/megachunk/chunk-
  supervisor discipline already manages for bit planes;
* the drive loop walks buckets ``b = tent // delta`` in ascending
  order.  Within a bucket, **light** edges (cost <= delta) relax to a
  fixpoint — the bucket's frontier re-enters while improvements land in
  the same bucket, the weighted analog of the level loop's frontier OR;
* **heavy** edges (cost > delta) relax ONCE at bucket close: a heavy
  relaxation lands at least ``delta + 1`` past the bucket floor, so it
  can never re-open the bucket;
* the relaxation itself is the existing scatter-min seam
  (``tent.at[:, v].min(candidates)``) over the dedup CSR's parallel
  cost array — built by ``BellGraph.from_host`` /
  ``CSRGraph.deduped_weighted``.

With positive integer costs any label-correcting relaxation order
converges to the unique SSSP fixpoint, so every flavor here is
bit-identical to a host Dijkstra by construction — which is what the
weighted certificate (ops.certify) and the engines-agree matrix pin.

``MSBFS_DELTA`` overrides the bucket width; unset auto-derives it from
the mean edge cost (delta ~ mean cost keeps the light set near the
whole edge set on uniform costs — the measured sweet spot for
bucket-count vs re-relaxation on the road fixtures).

Three flavors, negotiated through ``ops.engine.negotiate_engine``
capability tokens (see ``weighted/__init__``):

* :class:`WeightedBitBellEngine` — full-edge relaxation over the
  BellGraph dedup CSR + cost array (the bit-plane engines' sparse
  seam);
* :class:`WeightedStencilEngine` — ``windowed``: each relaxation
  gathers only the active row band's slot window (banded/road graphs:
  the frontier band is narrow, so most slots never move);
* :class:`WeightedMesh2DEngine` — ``mesh2d``: the vertex axis is split
  into row tiles mirroring parallel.partition2d's row-block ownership;
  each tile scatter-mins only its own rows from a global gather (the
  per-device partial + min-combine shape; runs tile-sequential on one
  chip, the real-mesh execution is the runbook's silicon leg).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.csr import CSRGraph
from ..ops.engine import QueryEngineBase
from ..runtime.supervisor import InputError
from ..utils import faults, knobs
from ..utils.timing import record_dispatch

# Unreached sentinel for the tentative word planes.  int32 planes with
# headroom: every candidate is tent + w with tent <= INF and w bounded
# by the build-time guard below, so the sum never wraps.
INF = np.int32(1 << 30)


def resolve_delta(weights: np.ndarray) -> int:
    """The bucket width: ``MSBFS_DELTA`` when set to a positive int,
    else max(1, round(mean cost)) — delta ~ mean keeps roughly half the
    edges light on uniform costs, degenerating to the unit-cost level
    loop (delta = 1) on weightless-style all-ones costs."""
    override = knobs.get_int("MSBFS_DELTA", 0)
    if override > 0:
        return override
    if weights is None or len(weights) == 0:
        return 1
    return max(1, int(round(float(np.mean(np.asarray(weights))))))


@jax.jit
def _relax_scatter_min(tent, active, u, v, w, sel):
    """One relaxation pass over an edge-slot array: for every slot
    (u -> v, cost w) with ``sel`` set and u active, offer
    ``tent[:, u] + w`` to v; commit by scatter-min.  Scatter-min is
    order-independent (min is associative/commutative), so the result
    is deterministic regardless of XLA's scatter schedule — the same
    property the bit planes' scatter-OR leans on."""
    cand = jnp.where(
        active[:, u] & sel[None, :],
        tent[:, u] + w[None, :],
        jnp.int32(INF),
    )
    return tent.at[:, v].min(cand)


@jax.jit
def _min_pending(tent, settled):
    return jnp.min(jnp.where(settled, jnp.int32(INF), tent))


@jax.jit
def _bucket_frontier(tent, settled, b, delta):
    in_bucket = (tent < jnp.int32(INF)) & (tent // delta == b)
    return in_bucket & ~settled


def _pow2(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length()) if x > 1 else 1


class DeltaStepEngineBase(QueryEngineBase):
    """Shared drive loop; flavors override :meth:`_relax` (and the
    relax-array build).  Satisfies the :class:`ops.engine.
    QueryEngineBase` contract — ``f_values`` is the weighted objective
    (cost sum over reached vertices), so ``best``/``compile``/the
    supervisor/the serving stack all apply unchanged."""

    CAPABILITIES = frozenset({"weighted"})

    def __init__(self, graph: CSRGraph, delta: Optional[int] = None):
        if not isinstance(graph, CSRGraph) or not graph.has_weights:
            raise InputError(
                "weighted engines need a CSRGraph with edge_weights "
                "(generate costs with gen_cli --weights, or load a "
                "weighted .bin/.gr artifact)"
            )
        self.graph = graph
        self.n = int(graph.n)
        self.n_state = self.n  # flavors may pad (mesh tiles)
        u, v, w = self._relax_arrays(graph)
        max_w = int(w.max()) if w.size else 1
        if int(self.n - 1) * max_w >= int(INF):
            raise InputError(
                f"weighted diameter bound (n-1)*max_cost = "
                f"{(self.n - 1) * max_w} exceeds the int32 tentative-plane "
                f"range ({int(INF)}); reduce costs or graph size"
            )
        self.delta = int(delta) if delta else resolve_delta(w)
        if self.delta < 1:
            raise InputError(f"delta must be >= 1, got {self.delta}")
        self.max_cost = max_w
        # Host copies (the windowed flavor slices them per step) +
        # device residency for the full-edge flavors.
        self._u_host = u.astype(np.int32)
        self._v_host = v.astype(np.int32)
        self._w_host = w.astype(np.int32)
        self._light_host = self._w_host <= self.delta
        self._finalize_arrays()
        self.last_stats: dict = {}

    # -- flavor hooks --------------------------------------------------
    def _relax_arrays(
        self, graph: CSRGraph
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(u, v, w) dedup edge slots, sorted by (u, v)."""
        u, v, w, _ = graph.deduped_weighted()
        return u, v, w

    def _finalize_arrays(self) -> None:
        """Upload whatever the flavor's :meth:`_relax` reads."""
        self._u = jnp.asarray(self._u_host)
        self._v = jnp.asarray(self._v_host)
        self._w = jnp.asarray(self._w_host)
        self._sel_light = jnp.asarray(self._light_host)
        self._sel_heavy = jnp.asarray(~self._light_host)

    def _relax(self, tent, active, light: bool):
        """One relaxation pass; returns (new tent, slots examined)."""
        sel = self._sel_light if light else self._sel_heavy
        out = _relax_scatter_min(tent, active, self._u, self._v, self._w, sel)
        return out, int(self._u_host.size)

    # -- drive loop ----------------------------------------------------
    def distances(self, rows) -> np.ndarray:
        """(K, S) -1-padded source rows -> (K, n) int32 weighted
        distance-to-set fields, -1 = unreached.  Exact SSSP (= Dijkstra
        bit-identical); ``last_stats`` records the bucket accounting."""
        rows = np.asarray(rows, dtype=np.int32)
        if rows.ndim == 1:
            rows = rows[None, :]
        K = rows.shape[0]
        n, ns = self.n, self.n_state
        stats = {
            "delta": int(self.delta),
            "buckets": 0,
            "light_relaxations": 0,
            "heavy_relaxations": 0,
            "bucket_plane_bytes": 0,
        }
        if K == 0:
            self.last_stats = stats
            return np.zeros((0, n), dtype=np.int32)
        tent0 = np.full((K, ns), INF, dtype=np.int32)
        valid = (rows >= 0) & (rows < n)
        k_idx = np.repeat(np.arange(K), valid.sum(axis=1))
        tent0[k_idx, rows[valid]] = 0
        tent = jnp.asarray(tent0)
        settled = jnp.zeros((K, ns), dtype=bool)
        delta = jnp.int32(self.delta)
        plane_bytes = K * ns * 4  # one int32 tentative plane pass
        while True:
            m = int(_min_pending(tent, settled))
            record_dispatch()
            if m >= int(INF):
                break
            b = jnp.int32(m // self.delta)
            frontier = _bucket_frontier(tent, settled, b, delta)
            bucket_members = frontier
            # Light fixpoint: improvements landing back in bucket b
            # re-enter the frontier (the weighted frontier OR).
            while bool(frontier.any()):
                bucket_members = bucket_members | frontier
                new_tent, slots = self._relax(tent, frontier, light=True)
                improved = new_tent < tent
                tent = new_tent
                frontier = improved & (tent // delta == b)
                record_dispatch()
                stats["light_relaxations"] += K * slots
                stats["bucket_plane_bytes"] += plane_bytes
            # Heavy close: one pass from everything the bucket touched.
            tent, slots = self._relax(tent, bucket_members, light=False)
            record_dispatch()
            stats["heavy_relaxations"] += K * slots
            stats["bucket_plane_bytes"] += plane_bytes
            settled = settled | bucket_members
            stats["buckets"] += 1
        dist = np.asarray(tent[:, :n]).copy()
        dist[dist >= int(INF)] = -1
        if faults.corruption_armed():
            # Plane-materialize seam (``bitflip:wplane``): the weighted
            # planes get the same injectable corruption the bit planes
            # have — the certificate must flunk it (exit 9 through the
            # supervisor), never serve it.
            dist = np.asarray(faults.corrupt("wplane", dist))
        self.last_stats = stats
        return dist

    def f_values(self, queries) -> jax.Array:
        """(K, S) padded rows -> (K,) int64 weighted cost sums: F(U) =
        sum over reached v of dist(U, v) — the same objective contract
        as the unit-cost engines, distances now being travel costs."""
        dist = self.distances(queries)
        f = np.where(dist >= 0, dist, 0).sum(axis=1, dtype=np.int64)
        return jnp.asarray(f)

    def query_stats(self, queries):
        """(levels, reached, F) with ``levels`` = buckets processed —
        the weighted analog the serving trace spans record."""
        dist = self.distances(queries)
        f = np.where(dist >= 0, dist, 0).sum(axis=1, dtype=np.int64)
        reached = (dist >= 0).sum(axis=1).astype(np.int32)
        levels = np.full(
            dist.shape[0], self.last_stats.get("buckets", 0), dtype=np.int32
        )
        return levels, reached, f

    def weighted_stats(self) -> dict:
        """Bucket accounting of the LAST run: delta, buckets, light/
        heavy relaxation candidates, tentative-plane bytes."""
        return dict(self.last_stats)


class WeightedBitBellEngine(DeltaStepEngineBase):
    """Full-edge relaxation over the BellGraph dedup CSR and its
    parallel cost array (``BellGraph.sparse`` / ``sparse_weights``) —
    the weighted twin of the bitbell engines' sparse expand seam."""

    CAPABILITIES = frozenset({"weighted"})

    def _relax_arrays(self, graph):
        from ..models.bell import BellGraph

        bell = BellGraph.from_host(graph)
        if bell.sparse is not None and bell.sparse_weights is not None:
            _, count, vals = bell.sparse
            count_h = np.asarray(count, dtype=np.int64)
            u = np.repeat(np.arange(graph.n, dtype=np.int64), count_h)
            return (
                u,
                np.asarray(vals, dtype=np.int64),
                np.asarray(bell.sparse_weights, dtype=np.int32),
            )
        return super()._relax_arrays(graph)


class WeightedStencilEngine(DeltaStepEngineBase):
    """``windowed``: per relaxation pass, only the active row band's
    contiguous slot window is gathered (dedup slots are sorted by row,
    so rows [lo, hi) own slots [start[lo], start[hi]) exactly — the
    stencil engine's active-window discipline).  Window lengths are
    padded to powers of two so XLA compiles O(log E) programs, not one
    per band."""

    CAPABILITIES = frozenset({"weighted", "windowed"})

    def _finalize_arrays(self) -> None:
        super()._finalize_arrays()
        self._slot_start = np.zeros(self.n + 1, dtype=np.int64)
        np.cumsum(
            np.bincount(self._u_host, minlength=self.n),
            out=self._slot_start[1:],
        )

    def _relax(self, tent, active, light: bool):
        rows = np.asarray(active).any(axis=0)
        hot = np.flatnonzero(rows)
        if hot.size == 0:
            return tent, 0
        lo, hi = int(hot[0]), int(hot[-1]) + 1
        s0, s1 = int(self._slot_start[lo]), int(self._slot_start[hi])
        width = s1 - s0
        if width == 0:
            return tent, 0
        pad = _pow2(width)
        sel_band = (
            self._light_host[s0:s1] if light else ~self._light_host[s0:s1]
        )
        # Sentinel padding: sel=False slots offer INF to vertex 0 — a
        # no-op under scatter-min, so padded windows stay exact.
        u_w = np.zeros(pad, dtype=np.int32)
        v_w = np.zeros(pad, dtype=np.int32)
        w_w = np.ones(pad, dtype=np.int32)
        sel_w = np.zeros(pad, dtype=bool)
        u_w[:width] = self._u_host[s0:s1]
        v_w[:width] = self._v_host[s0:s1]
        w_w[:width] = self._w_host[s0:s1]
        sel_w[:width] = sel_band
        out = _relax_scatter_min(tent, active, u_w, v_w, w_w, sel_w)
        return out, int(width)


def _mesh_relax_build(tile: int):
    @jax.jit
    def relax(tent, active, U, VL, W, SEL):
        K = tent.shape[0]
        tiles = U.shape[0]

        def per_tile(cols, vl, w, sel, tent_tile):
            cand = jnp.where(
                active[:, cols] & sel[None, :],
                tent[:, cols] + w[None, :],
                jnp.int32(INF),
            )
            return tent_tile.at[:, vl].min(cand)

        tent_tiles = tent.reshape(K, tiles, tile)
        new_tiles = jax.vmap(
            per_tile, in_axes=(0, 0, 0, 0, 1), out_axes=1
        )(U, VL, W, SEL, tent_tiles)
        return new_tiles.reshape(K, tiles * tile)

    return relax


class WeightedMesh2DEngine(DeltaStepEngineBase):
    """``mesh2d``: the vertex axis splits into ``tiles`` row blocks
    (parallel.partition2d's row ownership); each block gathers offers
    from the GLOBAL tentative plane but scatter-mins only its own rows
    — the per-device partial + min-combine shape, run tile-sequential
    on one chip (the virtual-mesh rehearsal; real-mesh execution is the
    TPU runbook's weighted leg).  Jacobi-style: every tile reads the
    pre-pass plane, which still converges to the same fixpoint because
    relaxations only ever lower valid upper bounds and the bucket loop
    runs to fixpoint."""

    CAPABILITIES = frozenset({"weighted", "mesh2d"})

    def __init__(self, graph, delta=None, tiles: int = 4):
        self.tiles = max(1, int(tiles))
        super().__init__(graph, delta=delta)

    def _finalize_arrays(self) -> None:
        n, T = self.n, self.tiles
        tile = -(-max(n, 1) // T)
        self.tile = tile
        self.n_state = T * tile
        owner = self._v_host // tile if len(self._v_host) else self._v_host
        order = np.argsort(owner, kind="stable")
        u_s = self._u_host[order]
        v_s = self._v_host[order]
        w_s = self._w_host[order]
        light_s = self._light_host[order]
        counts = np.bincount(owner, minlength=T) if len(owner) else np.zeros(T, np.int64)
        L = _pow2(int(counts.max())) if counts.size and counts.max() else 1
        U = np.zeros((T, L), dtype=np.int32)
        VL = np.zeros((T, L), dtype=np.int32)
        W = np.ones((T, L), dtype=np.int32)
        SEL_L = np.zeros((T, L), dtype=bool)
        SEL_H = np.zeros((T, L), dtype=bool)
        off = 0
        for t in range(T):
            c = int(counts[t])
            sl = slice(off, off + c)
            U[t, :c] = u_s[sl]
            VL[t, :c] = v_s[sl] - t * tile
            W[t, :c] = w_s[sl]
            SEL_L[t, :c] = light_s[sl]
            SEL_H[t, :c] = ~light_s[sl]
            off += c
        self._U = jnp.asarray(U)
        self._VL = jnp.asarray(VL)
        self._W = jnp.asarray(W)
        self._SEL_L = jnp.asarray(SEL_L)
        self._SEL_H = jnp.asarray(SEL_H)
        self._mesh_relax = _mesh_relax_build(tile)

    def _relax(self, tent, active, light: bool):
        sel = self._SEL_L if light else self._SEL_H
        out = self._mesh_relax(tent, active, self._U, self._VL, self._W, sel)
        return out, int(self._u_host.size)
