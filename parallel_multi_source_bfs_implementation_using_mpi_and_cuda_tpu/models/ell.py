"""ELL-slab graph layout for the Pallas frontier kernel.

CSR rows are split into fixed-width *virtual rows* ("slabs") of ``width``
neighbor slots: a vertex of degree d occupies ceil(d / width) virtual rows.
This bounds per-row work (the reference kernel's thread-divergence problem
on power-law degrees, main.cu:26-35, solved by layout instead of by
scheduling) and gives the kernel a rectangular (width, R) tile structure
that matches TPU tiling.

Arrays (R virtual rows, padded up to a tile multiple):

* ``cols``        (width, R) int32  — neighbor ids, column-major so the
  lane (last) dimension runs over virtual rows; padding slots hold ``n``
  (a frontier index that is always 0);
* ``vrow_vertex`` (R,) int32       — owning vertex per virtual row, sorted
  ascending; padding rows hold ``n`` (dropped by the segment reduce).

The per-level reduce over virtual rows is ``width`` times smaller than the
per-edge-slot reduce of the flat CSR path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .csr import CSRGraph

LANE = 128


@jax.tree_util.register_pytree_node_class
class EllGraph:
    """Device-resident ELL-slab layout (see module docstring)."""

    def __init__(self, cols, vrow_vertex, n: int, num_vrows: int, width: int):
        self.cols = cols  # (width, R) int32
        self.vrow_vertex = vrow_vertex  # (R,) int32
        self.n = int(n)
        self.num_vrows = int(num_vrows)
        self.width = int(width)

    @property
    def n_pad(self) -> int:
        return self.n

    @staticmethod
    def from_host(g: CSRGraph, width: int = 16, tile_rows: int = 512) -> "EllGraph":
        if width < 1:
            raise ValueError("width must be >= 1")
        deg = g.degrees.astype(np.int64)
        vrows_per_vertex = -(-deg // width)  # ceil; 0 for isolated vertices
        r_used = int(vrows_per_vertex.sum())
        r = max(tile_rows, -(-max(r_used, 1) // tile_rows) * tile_rows)

        cols = np.full((r, width), g.n, dtype=np.int32)  # sentinel n
        vrow_vertex = np.full(r, g.n, dtype=np.int32)  # sentinel n (dropped)

        # Vertex of each virtual row, in vertex order (so vrow_vertex is
        # sorted and the segment reduce can use indices_are_sorted).
        owners = np.repeat(
            np.arange(g.n, dtype=np.int32), vrows_per_vertex.astype(np.int64)
        )
        vrow_vertex[:r_used] = owners
        # Slot (i, j) of virtual row i holds the j-th neighbor of that row's
        # chunk: flat position = row_offsets(vertex) + chunk_index*width + j.
        first_vrow = np.zeros(g.n + 1, dtype=np.int64)
        np.cumsum(vrows_per_vertex, out=first_vrow[1:])
        chunk_idx = np.arange(r_used, dtype=np.int64) - first_vrow[owners]
        flat_start = g.row_offsets[owners] + chunk_idx * width
        take = np.minimum(deg[owners] - chunk_idx * width, width)
        for j in range(width):
            mask = take > j
            cols[:r_used][mask, j] = g.col_indices[flat_start[mask] + j]

        return EllGraph(
            cols=jnp.asarray(np.ascontiguousarray(cols.T)),
            vrow_vertex=jnp.asarray(vrow_vertex),
            n=g.n,
            num_vrows=r,
            width=width,
        )

    def expand_frontier(self, dist, level):
        from ..ops.pallas_bfs import ell_expand  # lazy: models stays op-free

        return ell_expand(dist, level, self)

    def tree_flatten(self):
        return (self.cols, self.vrow_vertex), (self.n, self.num_vrows, self.width)

    @classmethod
    def tree_unflatten(cls, aux, children):
        cols, vrow_vertex = children
        return cls(cols, vrow_vertex, *aux)

    def __repr__(self):
        return (
            f"EllGraph(n={self.n}, vrows={self.num_vrows}, width={self.width})"
        )
