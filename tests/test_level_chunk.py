"""High-diameter safety at any -gn (round 3): the bit-plane engines can
bound per-dispatch work to ``level_chunk`` BFS levels, host-chunking the
level loop like the push engine does (ops.push.default_push_chunk), with
the carry preserved on device across dispatches.

The load-bearing case is a >= 500-level graph through DistributedEngine
and ShardedBellEngine on the virtual mesh — the reference handles any
graph at any -gn (per-rank serial BFS, main.cu:303-322), and these tests
pin that the chunked paths return bit-identical results to the unchunked
single-dispatch loops."""

import numpy as np
import pytest

import jax

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
    _AUTO_LEVEL_CHUNK,
    _level_chunk_policy,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.distributed import (
    DistributedEngine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
    make_mesh,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
    ShardedBellEngine,
)


def deep_problem():
    """A 600-vertex path: BFS from an endpoint runs 600 levels."""
    n = 600
    edges = np.stack(
        [np.arange(n - 1), np.arange(1, n)], axis=1
    ).astype(np.int64)
    queries = [
        np.array([0], dtype=np.int32),
        np.array([n - 1], dtype=np.int32),
        np.array([7, 300], dtype=np.int32),
        np.zeros(0, dtype=np.int32),
    ]
    return CSRGraph.from_edges(n, edges), pad_queries(queries)


@pytest.fixture(scope="module")
def deep():
    g, padded = deep_problem()
    ref = BitBellEngine(BellGraph.from_host(g)).query_stats(padded)
    assert ref[0].max() >= 500  # the >=500-level precondition
    return g, padded, ref


def assert_stats_equal(ref, got):
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("chunk", [1, 32, 1000])
def test_bitbell_chunked_matches_unchunked(deep, chunk):
    g, padded, ref = deep
    eng = BitBellEngine(BellGraph.from_host(g), level_chunk=chunk)
    assert_stats_equal(ref, eng.query_stats(padded))


def test_distributed_chunked_deep_graph(deep):
    g, padded, ref = deep
    mesh = make_mesh(num_query_shards=8)
    eng = DistributedEngine(mesh, g, level_chunk=32)
    assert_stats_equal(ref, eng.query_stats(padded))
    np.testing.assert_array_equal(
        np.asarray(eng.f_values(padded)), np.asarray(ref[2])
    )


def test_sharded_bell_chunked_deep_graph(deep):
    g, padded, ref = deep
    mesh = make_mesh(num_query_shards=4, num_vertex_shards=2)
    eng = ShardedBellEngine(mesh, g, level_chunk=32)
    assert_stats_equal(ref, eng.query_stats(padded))


def test_sharded_bell_chunked_uneven_blocks(deep):
    g, padded, ref = deep
    mesh = make_mesh(num_query_shards=1, num_vertex_shards=8)
    eng = ShardedBellEngine(mesh, g, level_chunk=7)  # 600 % 7 != 0 too
    assert_stats_equal(ref, eng.query_stats(padded))


def test_chunked_hybrid_power_law():
    """Chunking composes with the hybrid pull/push expansion."""
    n, edges = generators.rmat_edges(9, edge_factor=8, seed=31)
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 6, max_group=4, seed=32)
    padded = pad_queries(queries)
    ref = BitBellEngine(BellGraph.from_host(g), sparse_budget=64).query_stats(
        padded
    )
    got = BitBellEngine(
        BellGraph.from_host(g), sparse_budget=64, level_chunk=2
    ).query_stats(padded)
    assert_stats_equal(ref, got)


def test_distance_engines_chunked_match(deep):
    """Round 4: EVERY single-chip backend honors level_chunk — the
    generic Engine (CSR pull + dense MXU), PackedEngine and BellEngine
    run the shared host-chunked distance loop (ops.bfs.host_chunked_loop)
    and must be bit-identical to the unchunked bitbell reference."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bell import (
        BellEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.dense import (
        DenseGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        Engine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        PackedEngine,
    )

    g, padded, ref = deep
    engines = [
        Engine(g.to_device(), level_chunk=32),
        Engine(DenseGraph.from_host(g), level_chunk=32),
        PackedEngine(g.to_device(), edge_chunks=2, level_chunk=7),
        BellEngine(BellGraph.from_host(g, keep_sparse=False), level_chunk=32),
    ]
    for eng in engines:
        assert_stats_equal(ref, eng.query_stats(padded))
        np.testing.assert_array_equal(
            np.asarray(eng.f_values(padded)), np.asarray(ref[2])
        )


def test_distance_engines_chunked_respect_max_levels(deep):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        Engine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        PackedEngine,
    )

    g, padded, _ = deep
    ref = Engine(g.to_device(), max_levels=5).query_stats(padded)
    got = Engine(g.to_device(), max_levels=5, level_chunk=2).query_stats(
        padded
    )
    assert_stats_equal(ref, got)
    got = PackedEngine(
        g.to_device(), max_levels=5, level_chunk=2
    ).query_stats(padded)
    assert_stats_equal(ref, got)


def test_chunked_respects_max_levels(deep):
    g, padded, _ = deep
    ref = BitBellEngine(BellGraph.from_host(g), max_levels=5).query_stats(
        padded
    )
    got = BitBellEngine(
        BellGraph.from_host(g), max_levels=5, level_chunk=2
    ).query_stats(padded)
    assert_stats_equal(ref, got)
    mesh = make_mesh(num_query_shards=4, num_vertex_shards=2)
    sharded = ShardedBellEngine(mesh, g, max_levels=5, level_chunk=2)
    assert_stats_equal(ref, sharded.query_stats(padded))
    dist = DistributedEngine(
        make_mesh(num_query_shards=8), g, max_levels=5, level_chunk=2
    )
    assert_stats_equal(ref, dist.query_stats(padded))


def test_level_chunk_requires_bitbell_backend(deep):
    g, _, _ = deep
    mesh = make_mesh(num_query_shards=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError):
        DistributedEngine(mesh, g, backend="csr", level_chunk=8)


def test_policy_always_bounds(monkeypatch):
    """Round 4: the bound is unconditional — power-law hubs no longer
    disable it (the chunked loop exits on convergence, so shallow BFS
    pays one host sync; benchmarks/exp_chunk_cost.py)."""
    monkeypatch.delenv("MSBFS_LEVEL_CHUNK", raising=False)
    g_road, _ = deep_problem()
    assert _level_chunk_policy(g_road) == _AUTO_LEVEL_CHUNK
    n, edges = generators.rmat_edges(10, edge_factor=16, seed=7)
    g_rmat = CSRGraph.from_edges(n, edges)
    assert _level_chunk_policy(g_rmat) == _AUTO_LEVEL_CHUNK  # power-law graphs too
    monkeypatch.setenv("MSBFS_LEVEL_CHUNK", "0")
    assert _level_chunk_policy(g_road) is None  # explicit 0 disables
    monkeypatch.setenv("MSBFS_LEVEL_CHUNK", "64")
    assert _level_chunk_policy(g_rmat) == 64  # explicit wins


def test_policy_malformed_env_falls_back_to_auto(monkeypatch, capsys):
    """A typo in MSBFS_LEVEL_CHUNK must NOT switch off the safety bound
    (round-3 behavior mapped garbage to 'disabled'; ADVICE r3)."""
    g_road, _ = deep_problem()
    monkeypatch.setenv("MSBFS_LEVEL_CHUNK", "banana")
    assert _level_chunk_policy(g_road) == _AUTO_LEVEL_CHUNK
    assert "MSBFS_LEVEL_CHUNK" in capsys.readouterr().err
    monkeypatch.setenv("MSBFS_LEVEL_CHUNK", "")
    assert _level_chunk_policy(g_road) == _AUTO_LEVEL_CHUNK
    assert capsys.readouterr().err == ""  # empty = unset, no noise
    monkeypatch.setenv("MSBFS_LEVEL_CHUNK", "-32")  # sign typo != opt-out
    assert _level_chunk_policy(g_road) == _AUTO_LEVEL_CHUNK
    assert "negative" in capsys.readouterr().err


def test_chunked_engine_empty_query_set(deep):
    """K = 0 must return empty results on the chunked path too (it
    crashed on an empty concatenate; found in round-4 review)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        Engine,
    )

    g, _, _ = deep
    eng = Engine(g.to_device(), level_chunk=32)
    empty = np.zeros((0, 1), dtype=np.int32)
    assert np.asarray(eng.f_values(empty)).shape == (0,)
    levels, reached, f = eng.query_stats(empty)
    assert levels.shape == reached.shape == f.shape == (0,)
    eng.compile((0, 1))  # the CLI warm path
    assert eng.best(empty) == (-1, -1)


def test_nonpositive_level_chunk_rejected_at_build():
    """A chunk <= 0 would make every dispatch a no-op and the host driver
    spin forever; engines must fail loud at construction instead."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        Engine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        PackedEngine,
    )

    g, _ = deep_problem()
    for bad in (0, -1):
        with pytest.raises(ValueError):
            Engine(g.to_device(), level_chunk=bad)
        with pytest.raises(ValueError):
            PackedEngine(g.to_device(), level_chunk=bad)
        with pytest.raises(ValueError):
            BitBellEngine(BellGraph.from_host(g), level_chunk=bad)
        with pytest.raises(ValueError):
            ShardedBellEngine(
                make_mesh(num_query_shards=4, num_vertex_shards=2),
                g,
                level_chunk=bad,
            )


def hub_tail_problem(tail=2500, hub_fan=100):
    """generators.hub_tail_edges (the round-3 heuristic's blind spot) as a
    ready-made (graph, padded queries) problem."""
    n, edges = generators.hub_tail_edges(tail, hub_fan)
    queries = [
        np.array([tail - 1], dtype=np.int32),  # tail-deep BFS
        np.array([tail], dtype=np.int32),  # from the hub
    ]
    return CSRGraph.from_edges(n, edges), pad_queries(queries)


@pytest.mark.slow  # ~30 s: every engine against the adversary; tier-1
# keeps the CLI bound-engaged arm (test_cli.py::test_hub_tail_cli_bound
# _engaged), `make test` runs the full matrix
def test_hub_tail_adversary_bounded_all_engines(monkeypatch):
    """The adversarial graph gets the bound at any -gn, and the chunked
    engines agree with the unchunked oracle on it (reference: any graph
    at any rank count, main.cu:303-322)."""
    monkeypatch.delenv("MSBFS_LEVEL_CHUNK", raising=False)
    g, padded = hub_tail_problem()
    assert int(g.degrees.max()) > 64  # the round-3 heuristic's blind spot
    chunk = _level_chunk_policy(g)
    assert chunk == _AUTO_LEVEL_CHUNK
    ref = BitBellEngine(BellGraph.from_host(g)).query_stats(padded)
    assert ref[0].max() >= 2000  # the deep precondition
    engines = [
        BitBellEngine(BellGraph.from_host(g), level_chunk=chunk),
        DistributedEngine(
            make_mesh(num_query_shards=8), g, level_chunk=chunk
        ),
        ShardedBellEngine(
            make_mesh(num_query_shards=4, num_vertex_shards=2),
            g,
            level_chunk=chunk,
        ),
    ]
    for eng in engines:
        assert_stats_equal(ref, eng.query_stats(padded))
