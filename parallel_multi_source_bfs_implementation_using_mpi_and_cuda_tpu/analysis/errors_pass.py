"""Error-contract pass: raises stay inside the typed taxonomy and exit
codes stay inside the docs/RESILIENCE.md table.

* ``untyped-raise`` — a ``raise`` of ``RuntimeError``/``Exception`` (or
  a class that only reaches those) in package code.  Allowed: the
  ``MsbfsError`` taxonomy (runtime/supervisor.py), any class declaring
  an ``exit_code`` (wire-mirrored taxonomy like ``ServerError``), the
  builtins ``classify()`` knows how to map (ValueError, OSError,
  TimeoutError, MemoryError, ...), bare re-raises, raising a bound
  variable, and ``raise classify(...)``.  ``utils/faults.py`` is exempt
  by design — its ``Simulated*`` classes subclass RuntimeError exactly
  because they imitate raw XLA failures.
* ``undocumented-exit-code`` — an integer exit-code literal
  (``sys.exit``/``os._exit``/``SystemExit``/``exit_code = N``) missing
  from the RESILIENCE.md exit-code table.

Class bases resolve by leaf name across all scanned files, so the
taxonomy is discovered, not hard-coded.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set

from .core import Finding, ParsedFile, dotted, enclosing_symbols

TAXONOMY_ROOT = "MsbfsError"
EXEMPT_FILES = (
    "parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu/utils/faults.py",
)
# Builtins runtime.supervisor.classify() maps onto the taxonomy.
CLASSIFIABLE_BUILTINS = {
    "ValueError", "TypeError", "KeyError", "IndexError", "AttributeError",
    "OSError", "IOError", "FileNotFoundError", "NotADirectoryError",
    "PermissionError", "ConnectionError", "BrokenPipeError",
    "ConnectionResetError", "ConnectionRefusedError", "InterruptedError",
    "NotImplementedError", "MemoryError", "TimeoutError", "StopIteration",
    "ImportError", "ModuleNotFoundError",
}
FORBIDDEN_BUILTINS = {"RuntimeError", "Exception", "BaseException", "ArithmeticError"}
EXIT_TABLE_RE = re.compile(r"^\|\s*`?(-?\d+)`?\s*\|", re.MULTILINE)


def _class_graph(files: List[ParsedFile]) -> Dict[str, Set[str]]:
    """leaf class name -> set of leaf base names (package-wide)."""
    out: Dict[str, Set[str]] = {}
    for pf in files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                bases = set()
                for b in node.bases:
                    name = dotted(b)
                    if name:
                        bases.add(name.rsplit(".", 1)[-1])
                out.setdefault(node.name, set()).update(bases)
    return out


def _declares_exit_code(files: List[ParsedFile]) -> Set[str]:
    """Classes that carry an ``exit_code`` (class attr or self-assign):
    the wire-mirrored arm of the taxonomy."""
    out: Set[str] = set()
    for pf in files:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == "exit_code":
                            out.add(node.name)
                        elif (
                            isinstance(tgt, ast.Attribute)
                            and tgt.attr == "exit_code"
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            out.add(node.name)
    return out


def _allowed_classes(files: List[ParsedFile]) -> Set[str]:
    graph = _class_graph(files)
    allowed = set(CLASSIFIABLE_BUILTINS) | {TAXONOMY_ROOT} | _declares_exit_code(files)
    changed = True
    while changed:
        changed = False
        for cls, bases in graph.items():
            if cls not in allowed and bases & allowed:
                allowed.add(cls)
                changed = True
    return allowed


def _raised_class(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if exc is None:
        return None  # bare re-raise
    if isinstance(exc, ast.Call):
        name = dotted(exc.func)
    else:
        name = dotted(exc)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    # raise classify(err) / raise err (lowercase binding) are fine.
    if leaf == "classify" or (leaf and not leaf[0].isupper()):
        return None
    return leaf


def run(files: List[ParsedFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    allowed = _allowed_classes(files)
    known_classes = set(_class_graph(files))

    for pf in files:
        if pf.path in EXEMPT_FILES or not pf.path.startswith(
            "parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu/"
        ):
            continue
        symbols = enclosing_symbols(pf.tree)
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Raise):
                continue
            leaf = _raised_class(node)
            if leaf is None or leaf == "SystemExit":
                continue
            bad_builtin = leaf in FORBIDDEN_BUILTINS
            bad_local = leaf in known_classes and leaf not in allowed
            if bad_builtin or bad_local:
                findings.append(Finding(
                    "errors", "untyped-raise", pf.path, node.lineno,
                    symbols.get(node, ""), leaf,
                    f"raise {leaf} is outside the typed taxonomy "
                    "(subclass MsbfsError or a classifiable builtin)",
                ))

    documented = _documented_exit_codes(root)
    for pf in files:
        if pf.path.startswith(("tests/", "benchmarks/")):
            continue  # harness code exits with whatever pytest needs
        for line, code, ctx in _exit_code_literals(pf):
            if code not in documented:
                findings.append(Finding(
                    "errors", "undocumented-exit-code", pf.path, line, ctx,
                    str(code),
                    f"exit code {code} is not in the docs/RESILIENCE.md table",
                ))
    return findings


def _documented_exit_codes(root: str) -> Set[int]:
    path = os.path.join(root, "docs", "RESILIENCE.md")
    if not os.path.exists(path):
        return set()
    with open(path, "r") as fh:
        text = fh.read()
    return {int(m) for m in EXIT_TABLE_RE.findall(text)}


def _int_literal(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(
        node.value, bool
    ):
        return int(node.value)
    if (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
        and isinstance(node.operand.value, int)
    ):
        return -int(node.operand.value)
    return None


def _exit_code_literals(pf: ParsedFile):
    symbols = enclosing_symbols(pf.tree)
    # return <int> inside a main()/*_main() is an exit code too: the
    # CLI entry points are sys.exit(main()) wrappers.
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "main" or node.name.endswith("_main")
        ):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    code = _int_literal(sub.value)
                    if code is not None:
                        yield sub.lineno, code, symbols.get(node, node.name)
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name in ("sys.exit", "os._exit", "SystemExit", "exit") and node.args:
                code = _int_literal(node.args[0])
                if code is not None:
                    yield node.lineno, code, symbols.get(node, "")
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                is_attr = (
                    isinstance(tgt, ast.Attribute)
                    and tgt.attr == "exit_code"
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                )
                is_name = isinstance(tgt, ast.Name) and tgt.id == "exit_code"
                if (is_attr or is_name) and isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, int
                ):
                    yield node.lineno, int(node.value.value), symbols.get(node, "")
