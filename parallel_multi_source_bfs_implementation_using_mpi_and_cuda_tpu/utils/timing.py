"""Two-span wall-clock timing, mirroring the reference's report (SURVEY C11).

The reference times exactly two spans with ``chrono::high_resolution_clock``:
preprocessing = load + broadcast + H2D upload (main.cu:235-298) and
computation = all BFS runs + gather + argmin (main.cu:301-400).  Here the
spans keep the same boundaries, with jit compilation counted as
preprocessing (the CUDA reference's kernels are compiled offline by nvcc, so
charging XLA compilation to the compute span would mis-compare).  Callers
must ``block_until_ready`` before closing a span — XLA dispatch is async.
"""

from __future__ import annotations

import itertools
import threading
import time


class Span:
    """``with Span() as s: ...`` then ``s.seconds``."""

    def __init__(self):
        self.seconds = 0.0
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False


# --- Dispatch counter (round 6) ---------------------------------------------
# Every host-blocking device commit — a fetch the host driver waits on
# before it can issue more work — pays the ~100 ms tunnel round-trip floor
# on this platform (docs/PERF_NOTES.md "Dispatch floor").  The chunked
# drivers (ops.bfs.host_chunked_loop, ops.bitbell.fused_best_drive), the
# engines' final result fetches and the streamed level loop all call
# :func:`record_dispatch` at exactly those points, so floor elimination is
# OBSERVABLE (MSBFS_STATS=1, bench detail.dispatch.dispatch_count, and the
# make perf-smoke regression guard) rather than inferred from level counts.
# A thread-safe itertools counter: serving worker threads may drive engines
# concurrently, and a torn increment would corrupt the regression guard.

_dispatch_counter = itertools.count()
_dispatch_base = 0
_dispatch_lock = threading.Lock()


def record_dispatch(n: int = 1) -> None:
    """Count ``n`` blocking device commits (round-trips the host waited on)."""
    for _ in range(n):
        next(_dispatch_counter)


def dispatch_count() -> int:
    """Blocking commits recorded since the last :func:`reset_dispatch_count`."""
    with _dispatch_lock:
        # Peek without consuming: count() has no read API, so advance a
        # probe and account for it in the base.
        global _dispatch_base
        seen = next(_dispatch_counter)
        _dispatch_base += 1
        return seen - _dispatch_base + 1


def reset_dispatch_count() -> None:
    """Zero the counter (callers bracket a measured span with this)."""
    global _dispatch_counter, _dispatch_base
    with _dispatch_lock:
        _dispatch_counter = itertools.count()
        _dispatch_base = 0


# --- Plane-pass accounting (round 7) -----------------------------------------
# The stencil engine's level cost is a pure HBM stream: every masked-shift
# pass reads/writes plane-sized arrays, so "full-plane-equivalent bytes per
# level" IS its bandwidth model (docs/PERF_NOTES.md "Round-5 findings",
# bench.py stream_bytes_per_s).  The round-7 active-window and wavefront-
# blocked paths shrink exactly that quantity — the engines record the
# ANALYTIC bytes each dispatched chunk streams (rows-touched x words-per-
# vertex x levels, ops.stencil.stencil_level_bytes) at the same host sites
# that ride record_dispatch, so the roofline diet is CI-observable on CPU
# (make perf-smoke plane-pass guard) the way the dispatch diet is: wall
# clock on the tunnel measures nothing, counters measure everything.
# Thread-safe for the same reason as the dispatch counter: serving worker
# threads may drive engines concurrently.

_plane_pass_bytes = 0
_plane_pass_lock = threading.Lock()


def record_plane_pass(nbytes: int) -> None:
    """Account ``nbytes`` of full-plane-equivalent stencil stream traffic
    (one call per dispatched level chunk, analytic bytes)."""
    global _plane_pass_bytes
    with _plane_pass_lock:
        _plane_pass_bytes += int(nbytes)


def plane_pass_bytes() -> int:
    """Bytes recorded since the last :func:`reset_plane_pass`."""
    with _plane_pass_lock:
        return _plane_pass_bytes


def reset_plane_pass() -> None:
    """Zero the plane-pass accumulator (callers bracket a measured span)."""
    global _plane_pass_bytes
    with _plane_pass_lock:
        _plane_pass_bytes = 0


# --- Collective-bytes accounting (round 10) ----------------------------------
# The multi-chip engines' per-level cost is an ICI wire stream: the 1D
# vertex-sharded path all_gathers full frontier planes every level, the 2D
# partition replaces that with a row-axis segment gather plus a col-axis
# OR-reduce-scatter whose payload scales with n/(R*C), not n (docs/
# MULTIHOST.md "2D partition").  The engines record the ANALYTIC payload
# bytes each dispatched level chunk moves over the mesh (executed levels x
# per-level wire bytes, parallel.partition2d.level_collective_bytes /
# parallel.sharded_bell dense halo bytes) at the same host fetch sites that
# ride record_dispatch, so the 2D-vs-1D traffic diet is CI-observable on
# the virtual CPU mesh (bench detail.collective, the make perf-smoke
# multichip guard) exactly like the dispatch/plane/MXU diets: wall clock on
# a simulated mesh measures nothing, counters measure everything.
# Thread-safe for the same reason as the other counters.

_collective_bytes = 0
_collective_lock = threading.Lock()


def record_collective_bytes(nbytes: int) -> None:
    """Account ``nbytes`` of analytic inter-chip collective payload (one
    call per dispatched level chunk, whole-mesh totals)."""
    global _collective_bytes
    with _collective_lock:
        _collective_bytes += int(nbytes)


def collective_bytes() -> int:
    """Bytes recorded since the last :func:`reset_collective_bytes`."""
    with _collective_lock:
        return _collective_bytes


def reset_collective_bytes() -> None:
    """Zero the collective-bytes accumulator (callers bracket a span)."""
    global _collective_bytes
    with _collective_lock:
        _collective_bytes = 0


# --- Collective-round accounting (round 19) -----------------------------------
# The async 2D drive (MSBFS_ASYNC_LEVELS, parallel.partition2d) exists to
# pay FEWER collective barriers, not fewer bytes: each round a tile runs k
# local level steps and then one row-gather + col-reduce-scatter reconciles
# the deltas.  "Fewer barriers" is the claim, so it gets its own ground-
# truth counter recorded at every merge commit — the synchronous drive
# records one round per executed level, the async drive one per exchange —
# making the k=4-vs-k=1 round diet CI-observable on the virtual CPU mesh
# (bench detail.multichip.async, the perf-smoke async-collective-rounds
# guard, the MULTICHIP dryrun JSON) the same way the byte diets are.
# Thread-safe for the same reason as the other counters.

_collective_rounds = 0
_collective_rounds_lock = threading.Lock()


def record_collective_rounds(n: int = 1) -> None:
    """Account ``n`` collective merge commits (one per reconciling
    row-gather + col-reduce-scatter round the mesh executed)."""
    global _collective_rounds
    with _collective_rounds_lock:
        _collective_rounds += int(n)


def collective_rounds() -> int:
    """Rounds recorded since the last :func:`reset_collective_rounds`."""
    with _collective_rounds_lock:
        return _collective_rounds


def reset_collective_rounds() -> None:
    """Zero the collective-round accumulator (callers bracket a span)."""
    global _collective_rounds
    with _collective_rounds_lock:
        _collective_rounds = 0


# --- MXU tile accounting (round 8) -------------------------------------------
# The mxu engine's matmul level is FLOP-bound, not stream-bound: per level
# it issues 2*T*T*K FLOPs for every NONZERO adjacency tile (ops/mxu.py),
# and the host-built tile index skips the all-zero tiles entirely.  Both
# quantities are analytic — tiles are static per graph, levels are counted
# at the same host fetch sites that ride record_dispatch — so MXU
# utilization and the zero-tile diet are CI-observable on CPU (bench
# detail.mxu, the make perf-smoke mxu guard) exactly like the dispatch and
# plane-byte diets.  The FLOP counter is an ISSUED-IF-MATMUL model: chunked
# dispatches cannot see per-level direction decisions without extra
# round-trips, so push levels are counted at the matmul-equivalent rate
# (exact under MSBFS_MXU_SWITCH=0, which is what the smoke guard pins;
# MxuEngine.level_direction_trace gives the exact per-level split).

_mxu_flops = 0
_mxu_tiles_skipped = 0
_mxu_tiles_total = 0
_mxu_lock = threading.Lock()


def record_mxu_tiles(flops: int, skipped: int, total: int) -> None:
    """Account one (or more) mxu level expansions: ``flops`` analytic tile
    FLOPs issued, ``skipped`` all-zero tiles elided of ``total`` tiles in
    the full (n_tiles x n_tiles) grid."""
    global _mxu_flops, _mxu_tiles_skipped, _mxu_tiles_total
    with _mxu_lock:
        _mxu_flops += int(flops)
        _mxu_tiles_skipped += int(skipped)
        _mxu_tiles_total += int(total)


def mxu_tile_counts():
    """(flops, tiles_skipped, tiles_total) since the last
    :func:`reset_mxu_tiles`."""
    with _mxu_lock:
        return _mxu_flops, _mxu_tiles_skipped, _mxu_tiles_total


def reset_mxu_tiles() -> None:
    """Zero the mxu accumulators (callers bracket a measured span)."""
    global _mxu_flops, _mxu_tiles_skipped, _mxu_tiles_total
    with _mxu_lock:
        _mxu_flops = _mxu_tiles_skipped = _mxu_tiles_total = 0


# --- Unified snapshot (round 12) ----------------------------------------------
# One read of every process-global engine counter, for the telemetry
# layer (serve/observe.py metrics verb, engine span attributes).  All
# reads are the non-destructive peeks above, so snapshotting never
# perturbs the perf-smoke bracketing resets.

def counter_totals() -> dict:
    """All engine counters in one dict: dispatches, plane_pass_bytes,
    collective_bytes, collective_rounds,
    mxu_flops/mxu_tiles_skipped/mxu_tiles_total."""
    flops, skipped, total = mxu_tile_counts()
    return {
        "dispatches": dispatch_count(),
        "plane_pass_bytes": plane_pass_bytes(),
        "collective_bytes": collective_bytes(),
        "collective_rounds": collective_rounds(),
        "mxu_flops": flops,
        "mxu_tiles_skipped": skipped,
        "mxu_tiles_total": total,
    }
