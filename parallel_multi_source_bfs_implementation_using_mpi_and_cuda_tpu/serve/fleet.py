"""Fleet supervisor: N replica daemons, heartbeats, backoff restarts.

One ``msbfs serve`` process is a single point of failure; ROADMAP item 3
("serving at fleet scale") needs the loss of a whole replica to be a
routine, recoverable event.  This module is the process-level analogue
of PR 1's :class:`~..runtime.supervisor.ChunkSupervisor`: it spawns N
replica server processes (each a stock ``msbfs serve`` daemon with its
own unix socket and its own PR-3 state journal), watches them through
the ``health`` verb with heartbeat timeouts, and restarts the dead ones
on the same jittered-backoff :class:`RetryPolicy` schedule the engine
retries ride — one backoff story repo-wide.

Placement rides :class:`~.ring.PlacementRing`: a registered graph is
loaded on its ``replication`` ring owners only, so each replica journals
(and journal-replays) just the graphs it owns.  When a replica dies, the
supervisor *reconciles*: every graph whose live owner set lost a member
is registered on the next ring member (HRW guarantees that is the only
movement), and when the replica comes back its own journal replay plus
an idempotent re-load converge it — registration is load-once, so
reconciliation is safe to repeat forever.

The membership is **elastic** (docs/SERVING.md "Autoscaling &
overload"): :meth:`add_replica` spawns a new slot and splices it into
the ring (minimal movement — it steals only the keys it now wins), and
:meth:`remove_replica` retires one *safely*: the victim leaves the ring
first, reconcile re-registers its graphs on the promoted owners, and
only then does it get SIGTERM — the PR-3 drain path finishes every
accepted query before exit, so a scale-down loses zero acked work.
When an :class:`~.autoscale.AutoscalePolicy` is armed, the monitor loop
feeds it the queue signals each health probe already returns and
applies its deltas; a :class:`~.brownout.BrownoutLadder` rides the same
tick and pushes its rung to every replica via the ``posture`` verb.

Replicas may advertise a ``host`` label and listen on TCP
(``transport="tcp"``) so a fleet can span machines; the ring then
spreads each graph's owner set across distinct hosts.

Epoch fencing (docs/SERVING.md "Cross-machine transport & fencing"):
the supervisor owns a monotonic **membership epoch**, persisted and
fsync'd at ``base_dir/epoch`` and bumped on every topology change —
start, join, retire, quarantine, host kill.  The live value is mirrored
onto :attr:`PlacementRing.epoch` (routers stamp it on every frame) and
every replica is spawned with ``--epoch-file`` pointing at the same
file, so a frame carrying a stale view is refused with a typed
``FencedError`` (exit code 10) instead of being silently served by a
replica the sender no longer believes in.  Persistence makes the fence
survive supervisor resurrection: a new supervisor over an old
``base_dir`` resumes the counter, it never rewinds.

Chaos seams (docs/RESILIENCE.md): each monitor tick of replica ``i``
trips fault site ``replica<i>`` (``replica_kill`` -> real SIGKILL), and
each distinct host label trips its own site, where an armed
``host_down`` spec raises
:class:`~..utils.faults.SimulatedHostDown` — every replica advertising
that label is SIGKILLed in one tick, exercising cross-host failover.
``MSBFS_FAULTS`` is deliberately STRIPPED from replica environments:
the fleet plan belongs to the supervisor process, and a replica-level
plan is injected explicitly via ``replica_faults``.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..runtime.supervisor import (
    CorruptionError,
    RetryPolicy,
    StorageError,
    TransientError,
)
from ..utils import faults, knobs
from .autoscale import AutoscalePolicy, ReplicaSignal
from .brownout import BrownoutLadder
from .client import MsbfsClient, ServerError
from .journal import StateJournal
from .registry import content_hash
from .ring import PlacementRing
from .shards import ShardPlan, is_shard_name, plan_shards

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _env_float(name: str, default: float) -> float:
    return knobs.get_float(name, default)


def _alloc_port() -> int:
    """Grab an ephemeral TCP port for a replica slot.  The port is
    bound, read and released — a (tiny) race with other allocators is
    acceptable for tests/benches; production fleets pass explicit
    addresses per host."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


@dataclass
class ReplicaHandle:
    """One replica slot: a stable name + address whose process comes and
    goes.  The name (``r<i>``) is the ring member, so placement survives
    restarts; the journal path is per-slot, so a restarted process
    replays its own history.  ``host`` is the failure-domain label the
    ring spreads owners across (None = its own domain)."""

    index: int
    name: str
    address: str
    journal_path: str
    log_path: str
    host: Optional[str] = None
    weight: float = 1.0
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"  # starting | ready | down | failed | draining | removed
    draining: bool = False
    pid: Optional[int] = None
    restarts: int = 0
    injected_kills: int = 0
    last_exit: Optional[int] = None
    last_ok: float = 0.0  # monotonic time of last successful health probe
    spawned_at: float = 0.0
    restart_due: Optional[float] = None
    backoff: Optional[object] = None  # iterator over restart delays
    registered: Set[str] = field(default_factory=set)
    quarantines: int = 0
    # Last health probe's queue gauge (autoscaler signal).
    queue_depth: int = 0
    queue_capacity: int = 1
    queue_age_s: float = 0.0

    def describe(self) -> dict:
        return {
            "name": self.name,
            "address": self.address,
            "host": self.host,
            "weight": self.weight,
            "state": self.state,
            "pid": self.pid,
            "restarts": self.restarts,
            "injected_kills": self.injected_kills,
            "quarantines": self.quarantines,
            "last_exit": self.last_exit,
            "queue_depth": self.queue_depth,
            "queue_age_s": round(self.queue_age_s, 6),
            "graphs": sorted(self.registered),
        }


class FleetSupervisor:
    """Spawn, watch, heal — and now grow and shrink — a fleet of replica
    serving daemons.

    ``base_dir`` holds each replica's socket, journal and log.  The
    supervisor is intentionally stateless beyond the member list — kill
    the supervisor and a new one re-adopts nothing (replicas die with
    their spawning process group in tests via ``stop()``); durable graph
    state lives in the per-replica journals, exactly like PR 3.
    """

    def __init__(
        self,
        size: int,
        base_dir: str,
        replication: int = 2,
        heartbeat_s: float = 0.5,
        heartbeat_timeout_s: Optional[float] = None,
        boot_timeout_s: float = 240.0,
        restart_policy: Optional[RetryPolicy] = None,
        env: Optional[Dict[str, str]] = None,
        replica_faults: Optional[Dict[int, str]] = None,
        replica_env: Optional[Dict[int, Dict[str, str]]] = None,
        server_args: Optional[List[str]] = None,
        transport: str = "unix",
        hosts: Optional[Dict[int, str]] = None,
        host_pool: Optional[List[str]] = None,
        weights: Optional[Dict[int, float]] = None,
        autoscale: Optional[AutoscalePolicy] = None,
        brownout: Optional[BrownoutLadder] = None,
        shed_fn: Optional[Callable[[], int]] = None,
        drain_timeout_s: float = 60.0,
        shard_max_bytes: Optional[int] = None,
        shard_replicas: Optional[int] = None,
    ):
        if size < 1:
            raise ValueError(f"fleet size must be >= 1, got {size}")
        if transport not in ("unix", "tcp"):
            raise ValueError(
                f"transport must be 'unix' or 'tcp', got {transport!r}"
            )
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.transport = transport
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = (
            float(heartbeat_timeout_s)
            if heartbeat_timeout_s is not None
            else max(4 * self.heartbeat_s, 5.0)
        )
        self.boot_timeout_s = float(boot_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        # PR-1 backoff semantics for process restarts: bounded, jittered,
        # seeded — a crash-looping replica backs off to max_delay and a
        # replica that exhausts the schedule is marked failed (the fleet
        # degrades to survivors rather than thrashing forever).
        self.restart_policy = restart_policy or RetryPolicy(
            max_retries=6,
            base_delay=_env_float("MSBFS_FLEET_BACKOFF", 0.2),
            max_delay=5.0,
            seed=int(_env_float("MSBFS_FAULT_SEED", 0)),
        )
        self._env = dict(os.environ if env is None else env)
        # The fleet fault plan drives the SUPERVISOR's seams; replicas
        # get a clean slate unless a per-replica plan is injected.
        self._env.pop("MSBFS_FAULTS", None)
        self._replica_faults = dict(replica_faults or {})
        # Per-replica env overrides (e.g. MSBFS_AUDIT on one replica for
        # the chaos matrix' audit leg); applied on every (re)spawn.
        self._replica_env = {
            int(i): dict(v) for i, v in (replica_env or {}).items()
        }
        self._server_args = list(server_args or [])
        self._hosts_cfg = {int(i): str(h) for i, h in (hosts or {}).items()}
        self._host_pool = list(host_pool or [])
        self._weights_cfg = {
            int(i): float(w) for i, w in (weights or {}).items()
        }
        self.autoscale = autoscale
        self.brownout = brownout
        self.shed_fn = shed_fn
        self._shed_last = 0
        self._controllers_armed = False
        self._next_index = 0
        self.replicas: List[ReplicaHandle] = [
            self._make_handle(i) for i in range(size)
        ]
        self._next_index = size
        self.addresses: Dict[str, str] = {
            r.name: r.address for r in self.replicas
        }
        self.ring = PlacementRing(
            [r.name for r in self.replicas],
            replication=replication,
            weights={r.name: r.weight for r in self.replicas},
            hosts={r.name: r.host for r in self.replicas if r.host},
        )
        self.graphs: Dict[str, str] = {}  # name -> path
        self.digests: Dict[str, str] = {}  # name -> content digest
        self.refused_graphs: Dict[str, str] = {}  # name -> refusal reason
        # ---- cross-replica sharding (serve/shards.py) -------------------
        # Oversized graphs split into "<name>#shard<i>" entries that live
        # in the SAME graphs/digests tables — reconcile, digest gates and
        # journal replay apply to a shard exactly as to a whole graph.
        # Placement uses a second ring over the same members so a shard's
        # copy count (MSBFS_SHARD_REPLICAS) is independent of the whole-
        # graph replication factor.
        self.shard_max_bytes = (
            int(shard_max_bytes)
            if shard_max_bytes is not None
            else knobs.get_int("MSBFS_SHARD_MAX_BYTES", 0)
        )
        self.shard_replicas = (
            int(shard_replicas)
            if shard_replicas is not None
            else knobs.get_int("MSBFS_SHARD_REPLICAS", 2)
        )
        if self.shard_replicas < 1:
            raise ValueError(
                f"shard replicas must be >= 1, got {self.shard_replicas}"
            )
        self.shard_ring = PlacementRing(
            [r.name for r in self.replicas],
            replication=self.shard_replicas,
            weights={r.name: r.weight for r in self.replicas},
            hosts={r.name: r.host for r in self.replicas if r.host},
        )
        self.shard_plans: Dict[str, ShardPlan] = {}  # parent -> plan
        self.shard_reheals = 0  # shards re-replicated after owner loss
        # parent -> {shard name -> live-owner tuple}: last placement each
        # reconcile converged to; a diff against it IS the reheal event.
        self._shard_view: Dict[str, Dict[str, tuple]] = {}
        # Fleet manifest journal: shard topology must survive supervisor
        # resurrection (the per-replica journals only know shard NAMES,
        # not which parent they reassemble into).
        self.manifest = StateJournal(
            os.path.join(self.base_dir, "fleet.journal")
        )
        for parent, rec in sorted(self.manifest.replay().shards.items()):
            plan = ShardPlan.from_manifest(parent, rec)
            self.shard_plans[parent] = plan
            for s in plan.shards:
                self.graphs[s.name] = s.path
                self.digests[s.name] = s.digest
        # Membership epoch: durable at base_dir/epoch so a resurrected
        # supervisor resumes (never rewinds) the fence counter.
        self.epoch_path = os.path.join(self.base_dir, "epoch")
        self.epoch = self._load_epoch()
        self.ring.epoch = self.epoch
        self.shard_ring.epoch = self.epoch
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._log_files: List[object] = []
        self.started = False

    # ---- membership epoch -------------------------------------------------
    def _load_epoch(self) -> int:
        """Resume the persisted fence counter (0 on first boot).  An
        unreadable or corrupt file restarts at 0 — strictly worse than
        resuming, but a fence that refuses to boot is worse still."""
        try:
            with open(self.epoch_path, "r", encoding="utf-8") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _bump_epoch(self, reason: str) -> int:
        """Advance the membership epoch for one topology change and make
        it durable BEFORE it becomes visible: write + fsync + rename,
        then mirror onto the ring (what routers stamp on frames).  A
        crash between rename and mirror re-reads the higher value on
        resurrection — the fence is monotonic either way."""
        with self._lock:
            self.epoch += 1
            tmp = self.epoch_path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(f"{self.epoch}\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.epoch_path)
            except OSError as exc:
                print(
                    f"msbfs fleet: epoch persist to {self.epoch_path} "
                    f"failed at {reason}: {exc} (fence continues in "
                    "memory; resurrection may rewind)",
                    file=sys.stderr,
                )
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            self.ring.epoch = self.epoch
            self.shard_ring.epoch = self.epoch
            return self.epoch

    def _host_for(self, index: int) -> Optional[str]:
        if index in self._hosts_cfg:
            return self._hosts_cfg[index]
        if self._host_pool:
            return self._host_pool[index % len(self._host_pool)]
        return None

    def _make_handle(
        self,
        index: int,
        weight: Optional[float] = None,
        host: Optional[str] = None,
    ) -> ReplicaHandle:
        if self.transport == "tcp":
            address = f"127.0.0.1:{_alloc_port()}"
        else:
            address = f"unix:{os.path.join(self.base_dir, f'r{index}.sock')}"
        return ReplicaHandle(
            index=index,
            name=f"r{index}",
            address=address,
            journal_path=os.path.join(self.base_dir, f"r{index}.journal"),
            log_path=os.path.join(self.base_dir, f"r{index}.log"),
            host=host if host is not None else self._host_for(index),
            weight=(
                weight
                if weight is not None
                else self._weights_cfg.get(index, 1.0)
            ),
        )

    # ---- lifecycle --------------------------------------------------------
    def start(self, wait_ready_s: Optional[float] = None) -> None:
        with self._lock:
            if self.started:
                from ..runtime.supervisor import InputError

                raise InputError("fleet already started")
            self.started = True
            # The boot topology is itself a membership change: stamp it
            # so frames minted against a pre-start view are fenceable.
            self._bump_epoch("start")
            for r in self.replicas:
                self._spawn(r)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="msbfs-fleet-monitor", daemon=True
        )
        self._monitor.start()
        if wait_ready_s is not None:
            self.wait_ready(wait_ready_s)

    def stop(self, drain: bool = False) -> None:
        """Tear the fleet down: stop the monitor, then SIGTERM (drain) or
        SIGKILL each replica and reap it.  Idempotent."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=30.0)
            self._monitor = None
        with self._lock:
            procs = [(r, r.proc) for r in self.replicas]
        for r, proc in procs:
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.send_signal(signal.SIGTERM if drain else signal.SIGKILL)
            except OSError:
                pass
        for r, proc in procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=60.0 if drain else 30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30.0)
            r.last_exit = proc.returncode
            if r.state != "removed":
                r.state = "down"
            r.pid = None
        for f in self._log_files:
            try:
                f.close()
            except OSError:
                pass
        self._log_files = []

    def __enter__(self) -> "FleetSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def active_replicas(self) -> List[ReplicaHandle]:
        """Slots that count toward fleet size: not removed, not on the
        way out."""
        with self._lock:
            return [
                r
                for r in self.replicas
                if r.state != "removed" and not r.draining
            ]

    def wait_ready(self, timeout_s: float, quorum: Optional[int] = None) -> None:
        """Block until ``quorum`` replicas (default: all active) report
        ready."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            want = (
                len(self.active_replicas()) if quorum is None else int(quorum)
            )
            if len(self.ready_names()) >= want:
                return
            time.sleep(min(0.1, self.heartbeat_s))
        want = len(self.active_replicas()) if quorum is None else int(quorum)
        raise TransientError(
            f"fleet: {len(self.ready_names())}/{want} replicas ready "
            f"after {timeout_s:g}s (states: "
            f"{[r.state for r in self.replicas]})"
        )

    # ---- spawning ---------------------------------------------------------
    def _spawn(self, r: ReplicaHandle) -> None:
        if r.address.startswith("unix:"):
            sock_path = r.address[len("unix:"):]
            if os.path.exists(sock_path):
                try:
                    os.unlink(sock_path)
                except OSError:
                    pass
        env = dict(self._env)
        env.update(self._replica_env.get(r.index, {}))
        plan = self._replica_faults.get(r.index)
        if plan:
            env["MSBFS_FAULTS"] = plan
        cmd = [
            sys.executable,
            os.path.join(_REPO_ROOT, "main.py"),
            "serve",
            "--listen",
            r.address,
            "--journal",
            r.journal_path,
            "--epoch-file",
            self.epoch_path,
        ] + self._server_args
        log = open(r.log_path, "ab")
        self._log_files.append(log)
        r.proc = subprocess.Popen(
            cmd, cwd=_REPO_ROOT, env=env, stdout=log, stderr=log
        )
        r.pid = r.proc.pid
        r.state = "starting"
        r.spawned_at = time.monotonic()
        r.last_ok = 0.0
        r.restart_due = None
        r.registered = set()

    def _schedule_restart(self, r: ReplicaHandle) -> None:
        if r.backoff is None:
            r.backoff = iter(self.restart_policy.delays())
        delay = next(r.backoff, None)
        if delay is None:
            r.state = "failed"  # budget exhausted: degrade to survivors
            r.restart_due = None
            return
        r.state = "down"
        r.restart_due = time.monotonic() + delay

    # ---- elastic membership -----------------------------------------------
    def add_replica(
        self, weight: float = 1.0, host: Optional[str] = None
    ) -> ReplicaHandle:
        """Scale up by one slot: fresh index (slot names are never
        reused, so a removed replica's journal can't be replayed by an
        unrelated successor), spliced into the ring with minimal
        movement, spawned immediately when the fleet is running.
        Reconcile then loads onto it exactly the graphs it now owns."""
        with self._lock:
            i = self._next_index
            self._next_index += 1
            r = self._make_handle(
                i,
                weight=weight,
                host=host if host is not None else self._host_for(i),
            )
            self.replicas.append(r)
            self.addresses[r.name] = r.address
            self.ring.add_member(r.name, weight=r.weight, host=r.host)
            self.shard_ring.add_member(r.name, weight=r.weight, host=r.host)
            self._bump_epoch(f"join {r.name}")
            if self.started and not self._stop.is_set():
                self._spawn(r)
        return r

    def remove_replica(
        self,
        name: str,
        sync: bool = True,
        drain_timeout_s: Optional[float] = None,
    ) -> bool:
        """Scale down by one slot, safely.  Ordering is the contract:

        1. the victim leaves the ring — new queries route to the
           promoted owners, nothing new lands on it;
        2. reconcile re-registers its graphs on those owners NOW, while
           the victim still serves (no availability dip);
        3. SIGTERM — the PR-3 drain path finishes every accepted query
           (in flight AND queued) and exits 0; only a drain-timeout
           stalls to SIGKILL.

        ``sync=False`` runs step 3 on a background thread (the monitor
        loop uses this so a scale-down never blocks heartbeats).
        Returns False when ``name`` is unknown or already leaving."""
        timeout = (
            self.drain_timeout_s
            if drain_timeout_s is None
            else float(drain_timeout_s)
        )
        with self._lock:
            r = next((x for x in self.replicas if x.name == name), None)
            if r is None or r.draining or r.state == "removed":
                return False
            live = [
                x
                for x in self.replicas
                if x.state != "removed" and not x.draining
            ]
            if len(live) <= 1:
                raise ValueError("cannot remove the last live replica")
            r.draining = True
            r.state = "draining"
            if r.name in self.ring.members:
                self.ring.remove_member(r.name)
            if r.name in self.shard_ring.members:
                self.shard_ring.remove_member(r.name)
            self._bump_epoch(f"retire {name}")
        # Promoted owners pick the victim's graphs up while it still
        # answers — the walk order is ring order, so by the time the
        # victim stops accepting, its keys already have live homes.
        self._reconcile()
        if sync:
            self._drain_victim(r, timeout)
        else:
            threading.Thread(
                target=self._drain_victim,
                args=(r, timeout),
                name="msbfs-fleet-drain",
                daemon=True,
            ).start()
        return True

    def _drain_victim(self, r: ReplicaHandle, timeout: float) -> None:
        proc = r.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                    proc.wait(timeout=30.0)
                except OSError:
                    pass
        if proc is not None:
            r.last_exit = proc.returncode
        with self._lock:
            r.pid = None
            r.state = "removed"
            self.addresses.pop(r.name, None)

    # ---- monitoring -------------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._tick_hosts()
                with self._lock:
                    snapshot = list(self.replicas)
                changed = False
                for r in snapshot:
                    changed |= self._tick(r)
                if changed:
                    self._reconcile()
                self._control_tick()
            except Exception:  # noqa: BLE001 — the monitor must survive
                pass

    def _tick_hosts(self) -> None:
        """Trip each distinct host label as a fault site.  An armed
        ``host_down:<host>`` plan raises SimulatedHostDown here; the
        supervisor answers with a real SIGKILL of every replica on that
        host — a whole failure domain gone in one heartbeat."""
        with self._lock:
            labels: List[str] = []
            for r in self.replicas:
                if r.host and r.state != "removed" and r.host not in labels:
                    labels.append(r.host)
        for label in labels:
            try:
                faults.trip(label)
            except faults.SimulatedHostDown as down:
                self._kill_host(down.host)

    def _kill_host(self, host: str) -> None:
        with self._lock:
            victims = [r for r in self.replicas if r.host == host]
        for v in victims:
            if v.proc is not None and v.proc.poll() is None:
                v.injected_kills += 1
                try:
                    v.proc.kill()
                    v.proc.wait(timeout=30.0)
                except OSError:
                    pass
        # A whole failure domain went dark: one epoch bump for the event
        # (not one per victim) — routers re-learn the view once.
        self._bump_epoch(f"host_down {host}")

    def _tick(self, r: ReplicaHandle) -> bool:
        """One heartbeat of one replica; True when its readiness flipped
        (the reconcile trigger).  This is the fleet chaos seam."""
        if r.state in ("failed", "removed") or r.draining:
            return False
        try:
            faults.trip(f"replica{r.index}")
        except faults.SimulatedReplicaKill as kill:
            victim = self.replicas[kill.replica % len(self.replicas)]
            if victim.proc is not None and victim.proc.poll() is None:
                victim.injected_kills += 1
                try:
                    victim.proc.kill()
                    victim.proc.wait(timeout=30.0)
                except OSError:
                    pass
        now = time.monotonic()
        was_ready = r.state == "ready"
        if r.proc is None or r.proc.poll() is not None:
            if r.state not in ("down", "failed") or r.restart_due is None:
                if r.proc is not None:
                    r.last_exit = r.proc.returncode
                if r.state != "failed":
                    self._schedule_restart(r)
            if (
                r.state == "down"
                and r.restart_due is not None
                and now >= r.restart_due
            ):
                r.restarts += 1
                self._spawn(r)
            return was_ready
        # Process is alive: probe readiness.
        healthy = self._probe(r) is not None
        if healthy:
            r.last_ok = now
            if r.state != "ready":
                r.state = "ready"
                r.backoff = None  # a recovered replica regains full budget
                # A replica (re)joining mid-brownout must adopt the
                # current posture — transitions it missed don't re-fire.
                if self.brownout is not None and self.brownout.level > 0:
                    self._push_posture_one(r)
            return not was_ready
        if was_ready and now - r.last_ok > self.heartbeat_timeout_s:
            # Alive but unresponsive past the timeout: treat as dead —
            # kill hard so the journal-replay restart path takes over.
            try:
                r.proc.kill()
                r.proc.wait(timeout=30.0)
            except OSError:
                pass
            r.last_exit = r.proc.returncode
            self._schedule_restart(r)
            return True
        if r.state == "starting" and now - r.spawned_at > self.boot_timeout_s:
            try:
                r.proc.kill()
                r.proc.wait(timeout=30.0)
            except OSError:
                pass
            r.last_exit = r.proc.returncode
            self._schedule_restart(r)
        return False

    def _probe(self, r: ReplicaHandle) -> Optional[dict]:
        """One health round trip; no retries (the heartbeat IS the retry
        loop).  Ready means journal replay finished and the daemon is
        accepting work.  Returns the health payload (the autoscaler's
        queue signals ride it) or None when not ready."""
        try:
            with MsbfsClient(
                r.address,
                timeout=max(2.0, self.heartbeat_timeout_s),
                retry=RetryPolicy(max_retries=0),
            ) as c:
                h = c.health()
        except (ServerError, OSError, ValueError):
            return None
        if not h.get("ready") or h.get("draining"):
            return None
        q = h.get("queue") or {}
        r.queue_depth = int(q.get("depth", h.get("queue_depth", 0)) or 0)
        r.queue_capacity = max(1, int(q.get("capacity", 1) or 1))
        r.queue_age_s = float(q.get("oldest_age_s", 0.0) or 0.0)
        return h

    # ---- overload control loop --------------------------------------------
    def _control_tick(self) -> None:
        """Feed the autoscaler and the brownout ladder one heartbeat of
        fleet signal and apply what they decide.  Both are optional and
        both are pure controllers — this is the only place decisions
        turn into membership changes or posture pushes."""
        if self.autoscale is None and self.brownout is None:
            return
        shed_delta = 0
        if self.shed_fn is not None:
            try:
                shed_now = int(self.shed_fn())
            except Exception:  # noqa: BLE001 — signal, not control
                shed_now = self._shed_last
            shed_delta = max(0, shed_now - self._shed_last)
            self._shed_last = shed_now
        with self._lock:
            active = [
                r
                for r in self.replicas
                if r.state != "removed" and not r.draining
            ]
            signals = [
                ReplicaSignal(
                    utilization=r.queue_depth / max(1, r.queue_capacity),
                    oldest_age_s=r.queue_age_s,
                )
                for r in active
                if r.state == "ready"
            ]
            size = len(active)
        # An unready fleet is not a dead fleet: until the first replica
        # has ever reported ready, an empty signal list means "still
        # booting", and the policy's empty-is-hot rule (meant for a
        # fleet that LOST everything) would scale up against thin air.
        if signals:
            self._controllers_armed = True
        elif not self._controllers_armed:
            return
        if self.brownout is not None:
            high = (
                self.autoscale.config.high_watermark
                if self.autoscale is not None
                else 0.75
            )
            util = (
                sum(s.utilization for s in signals) / len(signals)
                if signals
                else 0.0
            )
            saturated = bool(signals) and (util >= high or shed_delta > 0)
            if self.brownout.tick(saturated) is not None:
                self._push_posture()
        if self.autoscale is None or self._stop.is_set():
            return
        delta = self.autoscale.tick(
            size=size, replicas=signals, shed_since_last=shed_delta
        )
        if delta > 0:
            for _ in range(delta):
                try:
                    self.add_replica()
                except Exception:  # noqa: BLE001
                    self.autoscale.cancel()
                    break
        elif delta < 0:
            # Retire the newest ready replicas first: they own the
            # fewest long-lived keys and their journals are smallest.
            victims = [r for r in reversed(active) if r.state == "ready"]
            victims = victims[: -delta]
            if not victims:
                self.autoscale.cancel()
            for v in victims:
                try:
                    self.remove_replica(v.name, sync=False)
                except ValueError:
                    self.autoscale.cancel()

    def _push_posture(self) -> None:
        with self._lock:
            targets = [r for r in self.replicas if r.state == "ready"]
        for r in targets:
            self._push_posture_one(r)

    def _push_posture_one(self, r: ReplicaHandle) -> None:
        """Best-effort posture push; a miss is healed on the replica's
        next ready flip or the ladder's next transition."""
        if self.brownout is None:
            return
        audit = 0.0 if self.brownout.audit_suppressed() else "restore"
        try:
            with MsbfsClient(
                r.address, timeout=10.0, retry=RetryPolicy(max_retries=0)
            ) as c:
                c.posture(
                    audit_sample=audit,
                    cache_only=self.brownout.cache_only(),
                )
        except (ServerError, OSError, ValueError):
            pass

    # ---- placement --------------------------------------------------------
    def register(self, name: str, path: str) -> List[str]:
        """Register ``path`` under ``name`` on the graph's ring owners.
        Returns the owner names.  Safe to call again (load-once).

        When ``shard_max_bytes`` is armed and the artifact exceeds it,
        the graph is planned into row-range shards instead (serve/
        shards.py): each shard registers under its derived name on the
        shard ring, the manifest journals the topology BEFORE placement
        (a supervisor crash mid-register resurrects the plan, and the
        shard artifacts it points at are already on disk), and the
        return value is the union of shard owners.  A manifest append
        that hits a full disk propagates the typed ``StorageError`` —
        the registration promise was durability, not a hint; nothing is
        placed, and re-registering after freeing disk re-plans
        deterministically onto the same artifact digests."""
        digest = content_hash(path)
        plan = None
        if self.shard_max_bytes > 0 and not is_shard_name(name):
            plan = plan_shards(
                name,
                path,
                out_dir=os.path.join(
                    self.base_dir, "shards", name.replace(os.sep, "_")
                ),
                max_bytes=self.shard_max_bytes,
                replicas=self.shard_replicas,
                digest=digest,
            )
        if plan is None:
            with self._lock:
                self.graphs[name] = path
                self.digests[name] = digest
            self._reconcile()
            return self.ring.owners(digest)
        self.manifest.append(plan.to_record())  # StorageError propagates
        with self._lock:
            self.shard_plans[name] = plan
            # A re-registration with a new split drops stale shard rows.
            for gname in [
                g
                for g in self.graphs
                if is_shard_name(g)
                and g.split("#", 1)[0] == name
                and g not in {s.name for s in plan.shards}
            ]:
                self.graphs.pop(gname, None)
                self.digests.pop(gname, None)
            for s in plan.shards:
                self.graphs[s.name] = s.path
                self.digests[s.name] = s.digest
        self._reconcile()
        owners: Set[str] = set()
        for s in plan.shards:
            owners.update(self.shard_ring.owners(s.digest))
        return sorted(owners)

    def _ring_for(self, name: str) -> PlacementRing:
        """Shard entries place on the shard ring (their own replication
        factor); whole graphs on the stock ring."""
        return self.shard_ring if is_shard_name(name) else self.ring

    def ready_names(self) -> Set[str]:
        return {r.name for r in self.replicas if r.state == "ready"}

    def _reconcile(self) -> None:
        """Converge placement: every graph loaded on its live owner set.
        Load-once makes this idempotent; a dead owner's key lands on the
        next ring member (stand-in), and a recovered owner picks its
        graphs back up on the next pass."""
        with self._lock:
            todo = list(self.graphs.items())
            digests = dict(self.digests)
            # Readiness snapshot under the same lock as the graph table:
            # a replica flipping state mid-snapshot must not let one
            # graph see a ring the next graph doesn't (the two would
            # converge to different stand-ins for the same outage).
            ready = {r.name: r for r in self.replicas if r.state == "ready"}
        for name, path in todo:
            owners = self._ring_for(name).owners(
                digests[name], alive=ready.keys()
            )
            pending = [
                ready[o] for o in owners if name not in ready[o].registered
            ]
            if not pending:
                continue
            # Re-registration integrity gate: re-hash the on-disk file
            # against the digest recorded at register() time.  A file
            # that changed underneath the fleet must not be silently
            # re-registered under the old name on a stand-in — record a
            # typed refusal in status() and keep the placement hole (a
            # background thread cannot usefully raise).
            try:
                digest_now = content_hash(path)
            except OSError as exc:
                digest_now, reason = None, f"unreadable: {exc}"
            if digest_now != digests[name]:
                if digest_now is not None:
                    reason = (
                        f"{CorruptionError.__name__}: on-disk content "
                        f"hash {digest_now} != registered "
                        f"{digests[name]} — refusing re-registration of "
                        "silently different content"
                    )
                with self._lock:
                    self.refused_graphs[name] = reason
                continue
            with self._lock:
                self.refused_graphs.pop(name, None)  # file recovered
            for r in pending:
                try:
                    with MsbfsClient(r.address, timeout=300.0) as c:
                        c.load(path, graph=name)
                    r.registered.add(name)
                except (ServerError, OSError, ValueError):
                    pass  # next reconcile pass retries
        self._note_shard_moves(ready.keys())

    def _note_shard_moves(self, alive) -> None:
        """Detect shard re-replication: the reconcile loop above already
        DID the copy (a shard is just a graph; a dead owner's key walks
        to the ring stand-in and gets the digest-verified load), so all
        that is left is to make the move durable and fenceable — append
        the manifest again (journal-recorded) and bump the membership
        epoch so frames minted against the old placement are refused.
        The trigger is a placement DIFF against the last converged view,
        not a death event: a reheal and a recovery are both topology
        changes, and counting diffs makes the chaos chain's
        ``shard_reheals`` assertion deterministic."""
        alive = set(alive)
        with self._lock:
            plans = dict(self.shard_plans)
        for parent, plan in plans.items():
            view = {
                s.name: tuple(self.shard_ring.owners(s.digest, alive=alive))
                for s in plan.shards
            }
            with self._lock:
                prev = self._shard_view.get(parent)
                self._shard_view[parent] = view
            if prev is None or view == prev:
                continue
            moved = sorted(sn for sn in view if view[sn] != prev.get(sn))
            with self._lock:
                self.shard_reheals += len(moved)
            try:
                self.manifest.append(plan.to_record())
            except StorageError as exc:
                # The copies themselves landed; only the manifest
                # re-append is lost.  Resurrection re-plans from the
                # parent artifact, so degrade loudly, don't crash the
                # monitor thread (docs/RESILIENCE.md "Disk exhaustion").
                print(
                    f"msbfs fleet: shard reheal for {parent!r} not "
                    f"journaled: {exc}",
                    file=sys.stderr,
                )
            self._bump_epoch(
                f"shard-reheal {parent}: {','.join(moved)}"
            )

    # ---- corruption response ----------------------------------------------
    def quarantine(self, name_or_index) -> bool:
        """Take a replica that served a corrupt answer out of rotation:
        SIGKILL its process so the stock heartbeat machinery does the
        rest — restart on the jittered backoff schedule, journal replay,
        reconcile moves its keys to a stand-in meanwhile.  Deliberately
        NOT a new lifecycle state: a quarantined replica is just a dead
        one, and dead is the one condition the fleet already heals from
        end to end.  Returns True when a live process was killed."""
        with self._lock:
            for r in self.replicas:
                if r.name == name_or_index or r.index == name_or_index:
                    victim = r
                    break
            else:
                return False
            victim.quarantines += 1
            proc = victim.proc
        if proc is None or proc.poll() is not None:
            return False
        try:
            proc.kill()
            proc.wait(timeout=30.0)
        except OSError:
            return False
        # A quarantine is a forced view change: in-flight frames minted
        # against the pre-quarantine view must be refusable.
        self._bump_epoch(f"quarantine {victim.name}")
        return True

    # ---- observability ----------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            digests = dict(self.digests)
            refused = dict(self.refused_graphs)
            replicas = list(self.replicas)
            plans = dict(self.shard_plans)
            reheals = self.shard_reheals
        ready = self.ready_names()
        shards = {}
        for parent, plan in plans.items():
            rows = []
            under = 0
            for s in plan.shards:
                live = self.shard_ring.owners(s.digest, alive=ready)
                if len(live) < min(plan.replicas, len(ready) or 1):
                    under += 1
                rows.append(
                    {
                        "name": s.name,
                        "digest": s.digest,
                        "rows": [s.lo, s.hi],
                        "owners": self.shard_ring.owners(s.digest),
                        "live_owners": live,
                    }
                )
            shards[parent] = {
                "digest": plan.digest,
                "n": plan.n,
                "replicas": plan.replicas,
                "under_replicated": under,
                "shards": rows,
            }
        out = {
            "size": len([r for r in replicas if r.state != "removed"]),
            "slots": self._next_index,
            "epoch": self.epoch,
            "transport": self.transport,
            "replication": self.ring.replication,
            "shard_replicas": self.shard_replicas,
            "shard_reheals": reheals,
            "refused_graphs": refused,
            "ready": sorted(ready),
            "replicas": [r.describe() for r in replicas],
            "graphs": {
                name: {
                    "digest": digest,
                    "owners": self._ring_for(name).owners(digest),
                    "live_owners": self._ring_for(name).owners(
                        digest, alive=ready
                    ),
                }
                for name, digest in digests.items()
            },
            "shards": shards,
        }
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.describe()
        if self.brownout is not None:
            out["brownout"] = self.brownout.describe()
        return out
