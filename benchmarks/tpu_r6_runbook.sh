#!/bin/bash
# Round-10 multi-chip measurement runbook — the commands that turn the
# simulated-mesh numbers (bench configs 7/7t/7l, the perf-smoke multichip
# guard) into REAL pod numbers the day multi-chip hardware exists.  Every
# step is re-runnable; artifacts land under benchmarks/raw_r6/.
#
# What is already measured WITHOUT a pod (forced 8/16-virtual-device CPU
# mesh — collective BYTES are analytic and platform-independent, wall
# clock is not):
#   * bench configs 7 (2x4), 7t (4x2), 7l (1x8): mesh2d TEPS +
#     detail.multichip (collective_bytes, merge_tree, scaling efficiency
#     vs the same engine on 1x1) — `python bench.py` default sweep.
#   * perf-smoke multichip-frontier-bytes-ratio: 4x4 2D moves 0.4x the
#     1x16 1D dense-halo wire bytes (147,456 vs 368,640 on RMAT-10/K=16).
#   * engines-agree mesh2d arms + tests/test_partition2d.py: bit-identical
#     results across mesh shapes, merge trees, and mid-drive chip loss.
#
# What NEEDS a pod (this file): real ICI wall-clock — whether the 2.5x
# wire-byte diet turns into wall-clock TEPS at real mesh sizes, which
# merge tree wins per axis size on real links, and the reshard pause.
#
# NOTE (hard-won, r5): never OVERWRITE PYTHONPATH on a TPU run — the axon
# plugin registers via PYTHONPATH=/root/.axon_site; append instead.
set -uo pipefail
cd "$(dirname "$0")/.."
RAW=benchmarks/raw_r6
mkdir -p "$RAW"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
echo "runbook start $(stamp)" | tee -a "$RAW/runbook_meta.txt"
python -c "import jax; print('jax', jax.__version__, len(jax.devices()), 'devices')" \
    2>/dev/null | tee -a "$RAW/runbook_meta.txt"

echo "== 1. mesh-shape sweep on real chips: RMAT-22 x K=64 per shape"
# Unset BENCH_VIRTUAL_CPU semantics: single-config mode runs on the
# AMBIENT backend, so on a pod these rows measure real ICI.  Shapes
# must factor the chip count (4 chips: 2x2/1x4; 8: 2x4/4x2/1x8).
for MESH in 2x2 1x4 2x4 4x2 1x8; do
  BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=$MESH BENCH_SCALE=22 \
      BENCH_K=64 BENCH_REPEATS=3 BENCH_EXTRA_KS= BENCH_RUN_S=3600 \
      python bench.py 2> "$RAW/mesh_${MESH}.stderr" \
      | tee "$RAW/mesh_${MESH}.json" || true
done

echo "== 2. merge-tree shootout per mesh shape (ring vs halving vs oneshot)"
# detail.multichip.collective_bytes separates wire bytes from wall clock:
# oneshot trades (C-1)x more bytes for one fewer hop — only real links
# can say where the crossover sits (docs/MULTIHOST.md 'Reduction trees').
for TREE in ring halving oneshot; do
  BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=2x4 BENCH_MERGE_TREE=$TREE \
      BENCH_SCALE=22 BENCH_K=64 BENCH_REPEATS=3 BENCH_EXTRA_KS= \
      BENCH_RUN_S=3600 python bench.py \
      2> "$RAW/tree_${TREE}.stderr" | tee "$RAW/tree_${TREE}.json" || true
done

echo "== 2b. sparse-vs-dense wire on real ICI (round 15 density-adaptive wire)"
# The road workload is the sparse wire's home regime (thin deep-BFS
# wavefront).  Dense leg pins BENCH_WIRE_SPARSE=0; sparse leg runs the
# auto budget.  detail.multichip.wire carries the per-level encoding
# ledger + measured-vs-dense-model bytes, so this pair says whether the
# <= 0.5x byte diet (pinned on CPU by the perf-smoke sparse-wire-bytes
# row) turns into wall clock on real links.
for WIRE in 0 auto; do
  BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=2x4 BENCH_GRAPH=road \
      BENCH_SCALE=20 BENCH_K=32 BENCH_MAX_S=8 BENCH_WIRE_SPARSE=$WIRE \
      BENCH_REPEATS=2 BENCH_EXTRA_KS= BENCH_RUN_S=3600 python bench.py \
      2> "$RAW/wire_${WIRE}.stderr" | tee "$RAW/wire_${WIRE}.json" || true
done

echo "== 2c. pipelined-vs-oneshot exchange overlap (round 15 striped ring)"
# The pipelined tree moves ring bytes but overlaps each stripe's
# ppermute with the previous stripe's tile pass — only real links can
# price the overlap (on the simulated CPU mesh transfer is a memcpy, so
# the CPU rows say bytes only).  Stripe count sweep: 1 degenerates to
# plain ring (the control), 8 halves the per-hop payload twice more.
for CHUNKS in 1 2 4 8; do
  BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=2x4 \
      BENCH_MERGE_TREE=pipelined BENCH_WIRE_CHUNKS=$CHUNKS \
      BENCH_WIRE_SPARSE=0 BENCH_SCALE=22 BENCH_K=64 BENCH_REPEATS=3 \
      BENCH_EXTRA_KS= BENCH_RUN_S=3600 python bench.py \
      2> "$RAW/pipe_${CHUNKS}.stderr" | tee "$RAW/pipe_${CHUNKS}.json" || true
done

echo "== 2d. bounded-staleness async rounds on real ICI (round 19)"
# The road workload again — hundreds of levels means hundreds of
# synchronous barriers, the async drive's home regime.  k=1 is the
# level-synchronous control; k in {2,4,8} trades per-round wire bytes
# (int32 neg planes vs bit planes) for a 1/k-ish barrier count
# (detail.multichip.async.collective_rounds, pinned <= 0.5x at k=4 on
# CPU by the perf-smoke async-collective-rounds row).  Only real links
# can say where the byte-vs-barrier tradeoff nets out in wall clock.
for ALEVELS in 1 2 4 8; do
  BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=2x4 BENCH_GRAPH=road \
      BENCH_SCALE=20 BENCH_K=32 BENCH_MAX_S=8 BENCH_ASYNC_LEVELS=$ALEVELS \
      BENCH_REPEATS=2 BENCH_EXTRA_KS= BENCH_RUN_S=3600 python bench.py \
      2> "$RAW/async_${ALEVELS}.stderr" \
      | tee "$RAW/async_${ALEVELS}.json" || true
done

echo "== 3. 2D-vs-1D wall clock on real ICI (the headline scale-out claim)"
# The 1D row: the same workload through the vertex-sharded dense-halo
# engine (MSBFS_VSHARD) via the CLI for an apples-to-apples product path.
BENCH_CONFIGS= BENCH_ENGINE=mesh2d BENCH_MESH=1x8 BENCH_SCALE=22 BENCH_K=64 \
    BENCH_REPEATS=3 BENCH_EXTRA_KS= BENCH_RUN_S=3600 python bench.py \
    2> "$RAW/oned_1x8.stderr" | tee "$RAW/oned_1x8.json" || true

echo "== 4. live-reshard pause on real chips (chip-kill chaos via fault plan)"
# MSBFS_FAULT=chip:rank0:2 + the supervisor: time-to-first-result after a
# mid-drive device loss = reshard (retile on survivors) + recompile.
MSBFS_MESH=2x4 MSBFS_FAULT=chip:rank0:2 MSBFS_FAULT_SEED=0 MSBFS_STATS=1 \
    timeout 1800 python main.py -g data/rmat20.bin -q data/q64.bin -gn 8 \
    2>&1 | tee "$RAW/reshard_pause.txt" || true

echo "== 4b. weighted delta-stepping on real chips (round 17, bench config 9)"
# The weighted road workload (bucketed delta-stepping vs the host
# Bellman-Ford recompute).  On CPU the speedup column is dominated by
# dispatch overhead; real HBM bandwidth is what the bucket-plane diet
# (detail.weighted.bucket_plane_bytes, pinned by the perf-smoke
# weighted-bucket-bytes row) was designed for.  Flavor sweep: the
# negotiated default (bitbell), the hot-band stencil, and the 2D mesh.
for WENG in bitbell stencil mesh2d; do
  BENCH_CONFIGS= BENCH_WEIGHTED=1 BENCH_GRAPH=road BENCH_SCALE=18 \
      BENCH_K=8 BENCH_MAX_S=8 BENCH_WEIGHTED_ENGINE=$WENG \
      BENCH_REPEATS=3 BENCH_EXTRA_KS= BENCH_RUN_S=3600 python bench.py \
      2> "$RAW/weighted_${WENG}.stderr" \
      | tee "$RAW/weighted_${WENG}.json" || true
done

echo "== 5. simulated-mesh twin for the archive (byte-exact, any host)"
BENCH_CONFIGS=7,7t,7l,7s,7a BENCH_RUN_S=3600 \
    BENCH_DETAIL_PATH="$RAW/multichip_sim_detail.json" python bench.py \
    2> "$RAW/multichip_sim.stderr" | tee "$RAW/multichip_sim.json" || true

echo "runbook end $(stamp)" | tee -a "$RAW/runbook_meta.txt"
