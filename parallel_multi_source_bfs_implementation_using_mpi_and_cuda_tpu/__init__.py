"""TPU-native multi-source BFS / distance-to-set framework.

A ground-up JAX/XLA re-design of the capabilities of
``irmakerkol/Parallel-Multi-Source-BFS-Implementation-Using-MPI-and-CUDA``
(reference: ``/root/reference/main.cu``): given an undirected graph G and K
query groups of source vertices, run a multi-source BFS per group, compute
F(U_k) = sum of distances over reached vertices, and report the group with the
minimum F (ties -> lowest query index, 1-based in the report).

Layer map (mirrors SURVEY.md section 1):

==========================  =====================================================
Reference layer             This package
==========================  =====================================================
CLI / driver                :mod:`.cli`
Data I/O (binary loaders)   :mod:`.utils.io` (+ native C++ fast path
                            in ``runtime/loader.cpp`` via :mod:`.runtime`)
Distributed runtime / MPI   :mod:`.parallel` (mesh + shard_map + XLA collectives)
Scheduler (query distrib.)  :mod:`.parallel.scheduler` (cyclic, reference-exact)
Device compute (BFS)        :mod:`.ops` (lax.while_loop BFS, vmap batching,
                            dense-MXU + Pallas frontier kernels)
==========================  =====================================================

Design stance: BFS is a pure-functional level-synchronous iteration inside
``jax.lax.while_loop`` — the per-level host<->device flag round-trip of the
reference (main.cu:61-71) disappears entirely; the convergence test is an
on-device ``jnp.any``.  Queries are vmap-batched per chip and shard_map-sharded
across chips on a ``('q',)`` mesh axis with the reference's exact cyclic
assignment (main.cu:303-307).
"""

from jax import config as _jax_config

# F(U) sums can exceed int32 (n * diameter), matching the reference's
# `long long` accumulator (main.cu:75-89).  All other arrays in this package
# carry explicit int32 dtypes, so enabling x64 only affects the objective
# accumulator (int64 is software-emulated on TPU; it is used only for the
# final O(n) reduction).
_jax_config.update("jax_enable_x64", True)

import jax as _jax  # noqa: E402

if not hasattr(_jax, "shard_map"):
    # jax < 0.4.35 ships shard_map under jax.experimental only; every
    # mesh engine in .parallel calls the stable-namespace spelling
    # (f positional + mesh/in_specs/out_specs keywords, valid for both).
    # Alias it so the runtime comes up on whatever jax the host bakes in.
    # Replication checking is disabled: the engines annotate varying axes
    # with lax.pcast, which the old checker doesn't understand.
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def _shard_map(f, **kwargs):
        kwargs.setdefault("check_rep", False)
        return _experimental_shard_map(f, **kwargs)

    _jax.shard_map = _shard_map

if not hasattr(_jax.lax, "pcast"):
    # Pre-VMA jax has no varying-axes type system; with replication
    # checking off (above) the cast is semantically a no-op.
    _jax.lax.pcast = lambda x, axes, to: x

if not hasattr(_jax.distributed, "is_initialized"):
    # jax < 0.4.39 has no public initialization probe; the internal
    # global state's client handle is the same signal the newer public
    # API reads.
    from jax._src import distributed as _internal_distributed

    _jax.distributed.is_initialized = (
        lambda: _internal_distributed.global_state.client is not None
    )

from .models.csr import CSRGraph, DeviceCSR  # noqa: E402
from .models.bell import BellGraph  # noqa: E402
from .ops.bfs import multi_source_bfs, batched_multi_source_bfs  # noqa: E402
from .ops.objective import f_of_u, select_best  # noqa: E402
from .ops.engine import Engine  # noqa: E402
from .ops.bitbell import BitBellEngine  # noqa: E402
from .utils.io import (  # noqa: E402
    load_graph_bin,
    load_query_bin,
    save_graph_bin,
    save_query_bin,
    pad_queries,
)

__all__ = [
    "CSRGraph",
    "DeviceCSR",
    "BellGraph",
    "BitBellEngine",
    "multi_source_bfs",
    "batched_multi_source_bfs",
    "f_of_u",
    "select_best",
    "Engine",
    "load_graph_bin",
    "load_query_bin",
    "save_graph_bin",
    "save_query_bin",
    "pad_queries",
]

__version__ = "0.1.0"
