"""Graph registry: load-once, device-resident, versioned (docs/SERVING.md).

The reference re-reads and re-uploads the graph on every process run
(main.cu:235-298); a serving daemon must pay that once.  Each registered
graph is keyed by *name + content hash*: registering the same file under
the same name is a no-op (load-once), registering different bytes under
an existing name is refused (an operator must say ``reload`` to mean
replacement — silent content swaps under a live name would poison the
result cache's mental model).  ``reload`` re-reads the file, rebuilds
the engine and bumps the integer *version*; every cache key downstream
includes the version, so stale results are unreachable by construction.

Engines are built through the CLI's own single-chip routing policy
(level-chunk bound, bitbell default with the capacity-degradation
ladder) and wrapped in the PR-1 :class:`ChunkSupervisor` — a fault
during a served request degrades or fails that request, not the daemon.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..runtime.supervisor import (
    ChunkSupervisor,
    CorruptionError,
    InputError,
    RetryPolicy,
)
from ..utils import knobs
from ..utils.io import load_graph_bin


def content_hash(path: str) -> str:
    """Streaming sha256 of the graph file (hex, 12 chars — enough to
    distinguish operator mistakes; this is an identity label, not a
    security boundary)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()[:12]


def _env_int(name: str, default: int) -> int:
    return knobs.get_int(name, default)


def _env_float(name: str, default: float) -> float:
    return knobs.get_float(name, default)


def audit_sample_rate() -> float:
    """``MSBFS_AUDIT`` (docs/RESILIENCE.md "Silent data corruption"):
    ``off``/unset/``0`` disables, ``full``/``1`` audits every served
    f_values call, a float in (0, 1) audits that sampled fraction.
    Malformed values fall back to off (the repo-wide knob convention)."""
    raw = knobs.raw("MSBFS_AUDIT", "").strip().lower()
    if raw in ("", "off", "0"):
        return 0.0
    if raw in ("full", "1"):
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


# --- MXU tile-index cache (round 8, bounded round 9) -------------------------
# Densifying CSR adjacency into per-tile blocks is the mxu route's only
# host-side preprocessing cost (O(E) scatter + unique per graph).  The
# serve daemon keys graphs by content hash already, so the packed
# MxuGraph is cached under (content digest, tile size): a warm reload of
# unchanged bytes — and every identical-content register — reuses the
# device-resident tiles instead of re-packing.  Round 9 bounds it: a
# long-lived fleet replica sees an unbounded stream of distinct digests
# over its lifetime (reloads, many named graphs), and each entry pins
# device-resident tile arrays — so the cache is LRU with a BYTE cap
# (``MSBFS_MXU_CACHE_BYTES``, default 256 MiB; <= 0 disables caching,
# the repo-wide capacity convention of serve/caches.py), sized by the
# packed arrays' nbytes, with an eviction counter in the stats hook.

_MXU_CACHE_DEFAULT_BYTES = 256 << 20

_mxu_tile_cache: "OrderedDict[tuple, Tuple[object, int]]" = OrderedDict()
_mxu_tile_cache_lock = threading.Lock()
_mxu_tile_cache_hits = 0
_mxu_tile_cache_evictions = 0
_mxu_tile_cache_bytes = 0


def _mxu_cache_cap_bytes() -> int:
    return _env_int("MSBFS_MXU_CACHE_BYTES", _MXU_CACHE_DEFAULT_BYTES)


def _mxu_graph_nbytes(mg) -> int:
    """Footprint of one packed tile index: the sum of its array members'
    nbytes (device arrays report the device allocation)."""
    total = 0
    for name in ("tiles", "tile_row", "tile_col", "start", "count", "vals"):
        nb = getattr(getattr(mg, name, None), "nbytes", 0)
        total += int(nb or 0)
    return max(total, 1)  # never let an entry count as free


def _cached_mxu_graph(graph, content_digest: Optional[str]):
    """MxuGraph for ``graph``, reusing the packed tile index when the
    serving content digest (and MSBFS_MXU_TILE) match a prior build."""
    global _mxu_tile_cache_hits, _mxu_tile_cache_evictions
    global _mxu_tile_cache_bytes
    from ..ops.mxu import MxuGraph, resolve_tile

    if content_digest is None:
        return MxuGraph.from_host(graph)
    key = (content_digest, resolve_tile())
    with _mxu_tile_cache_lock:
        have = _mxu_tile_cache.get(key)
        if have is not None:
            _mxu_tile_cache.move_to_end(key)  # LRU: refresh recency
            _mxu_tile_cache_hits += 1
            return have[0]
    mg = MxuGraph.from_host(graph)
    cap = _mxu_cache_cap_bytes()
    if cap <= 0:
        return mg
    size = _mxu_graph_nbytes(mg)
    with _mxu_tile_cache_lock:
        have = _mxu_tile_cache.get(key)
        if have is not None:  # lost the build race: reuse the winner
            _mxu_tile_cache.move_to_end(key)
            _mxu_tile_cache_hits += 1
            return have[0]
        _mxu_tile_cache[key] = (mg, size)
        _mxu_tile_cache_bytes += size
        # Evict oldest-first down to the cap.  An entry larger than the
        # whole cap evicts itself immediately: the build still returns
        # (capacity bounds the CACHE, not the workload), it just never
        # parks in it — caches.py's capacity-vs-capability rule.
        while _mxu_tile_cache_bytes > cap and _mxu_tile_cache:
            _, (_, old_size) = _mxu_tile_cache.popitem(last=False)
            _mxu_tile_cache_bytes -= old_size
            _mxu_tile_cache_evictions += 1
    return mg


def mxu_tile_cache_stats() -> dict:
    """Observability hook for tests and the daemon: entry count, hits,
    evictions, resident bytes and the active byte cap."""
    with _mxu_tile_cache_lock:
        return {
            "entries": len(_mxu_tile_cache),
            "hits": _mxu_tile_cache_hits,
            "evictions": _mxu_tile_cache_evictions,
            "bytes": _mxu_tile_cache_bytes,
            "cap_bytes": _mxu_cache_cap_bytes(),
        }


def build_supervised_engine(graph, content_digest: Optional[str] = None) -> ChunkSupervisor:
    """The serving engine route: the CLI's single-chip policy (bounded
    level loop, bitbell default + degradation ladder, MSBFS_BACKEND=
    "vmap"/"csr" honored for the per-query CSR pull) under the
    supervisor with the same env knobs as the batch path
    (docs/RESILIENCE.md).  The daemon serves one process's devices; the
    multi-chip mesh routes stay with the batch CLI for now
    (docs/SERVING.md scopes this)."""
    from ..cli import (
        _bitbell_ladder,
        _explicit_level_chunk,
        _level_chunk_policy,
        _road_class,
    )

    explicit_chunk = _explicit_level_chunk()
    level_chunk = _level_chunk_policy(graph, explicit_chunk)
    # Same megachunk policy as the batch CLI (round 6): a deliberate
    # MSBFS_LEVEL_CHUNK bound is honored exactly; the auto bound may be
    # megachunk-fused per dispatch (ops.bitbell.resolve_megachunk).
    megachunk = (
        1 if (explicit_chunk is not None and explicit_chunk > 0) else None
    )
    backend = knobs.raw("MSBFS_BACKEND", "auto")
    ladder = []
    engine = None
    label = "stencil"
    if backend == "stencil" or (
        backend == "auto"
        and _road_class(graph)
        and knobs.raw("MSBFS_STENCIL", "") != "0"
    ):
        # Round 7: the served route mirrors the batch CLI's stencil
        # probe, so a registered road/grid graph serves through the
        # banded masked-shift engine (with the round-7 window/wavefront/
        # kernel knobs riding StencilEngine's own env parsing) instead of
        # silently falling back to gathers.  Auto probe failures keep the
        # gather engines; a forced backend=stencil failure is the
        # operator's routing error and raises.
        from ..ops.stencil import (
            AUTO_STENCIL_LEVEL_CHUNK,
            StencilEngine,
            StencilGraph,
        )

        try:
            sg = StencilGraph.from_host(graph)
        except ValueError:
            if backend == "stencil":
                raise
            sg = None
        if sg is not None:
            stencil_chunk = (
                level_chunk
                if explicit_chunk is not None and explicit_chunk >= 0
                else (AUTO_STENCIL_LEVEL_CHUNK if level_chunk else None)
            )
            engine = StencilEngine(
                sg, level_chunk=stencil_chunk, megachunk=megachunk
            )
    if engine is not None:
        pass
    else:
        # The non-stencil routes go through the engine lattice
        # (ops.engine.resolve_axes): the backend name resolves to axis
        # tokens and negotiate_engine picks the first candidate class
        # declaring them, so the served route label comes out of the
        # negotiation — never hand-assigned per branch.  Candidate notes:
        #   * mxu — adjacency densified into per-tile blocks (all-zero
        #     tiles skipped), direction-switched back to the gather push
        #     on thin frontiers.  The packed tile index rides the
        #     content-digest cache above, so a warm reload of unchanged
        #     bytes re-registers without re-packing; a forced
        #     backend=mxu tile-cap failure is the operator's routing
        #     error and raises (the stencil precedent).
        #   * lowk — serving buckets queries by shape, so an operator
        #     pinning a K <= 4 workload can serve the byte-flag planes;
        #     the auto route stays with bitbell because a served graph
        #     sees arbitrary K over its lifetime.
        #   * vmap/csr — the generic word-plane per-query pull.
        # Backends with no served variant (push/packed/dense/streamed/
        # pallas) keep the historical bitbell fallback.
        from ..models.bell import BellGraph
        from ..ops.bitbell import BitBellEngine
        from ..ops.engine import Engine, negotiate_engine, resolve_axes
        from ..ops.lowk import LowKEngine
        from ..ops.mxu import MxuEngine

        routed = backend if backend in ("vmap", "mxu", "lowk") else (
            "vmap" if backend == "csr" else "bitbell"
        )
        _, required = resolve_axes(routed)
        label, engine = negotiate_engine(
            required,
            [
                (
                    "bitbell",
                    BitBellEngine,
                    lambda: BitBellEngine(
                        BellGraph.from_host(graph),
                        level_chunk=level_chunk,
                        megachunk=megachunk,
                    ),
                ),
                (
                    "lowk",
                    LowKEngine,
                    lambda: LowKEngine(
                        BellGraph.from_host(graph),
                        level_chunk=level_chunk,
                        megachunk=megachunk,
                    ),
                ),
                (
                    "mxu",
                    MxuEngine,
                    lambda: MxuEngine(
                        _cached_mxu_graph(graph, content_digest),
                        level_chunk=level_chunk,
                        megachunk=megachunk,
                    ),
                ),
                (
                    "vmap",
                    Engine,
                    lambda: Engine(
                        graph.to_device(), level_chunk=level_chunk
                    ),
                ),
            ],
        )
        if label == "bitbell":
            ladder = _bitbell_ladder(graph, level_chunk)
    # Output certification (MSBFS_AUDIT): the supervisor audits served
    # f_values against the host-CSR distance certificate and escalates —
    # retry, alternate rung, typed CorruptionError — before any
    # uncertified answer can reach the wire (ops/certify.py).
    sample = audit_sample_rate()
    auditor = None
    if sample > 0.0:
        from ..ops.certify import make_auditor

        auditor = make_auditor(graph)
    sup = ChunkSupervisor(
        engine,
        policy=RetryPolicy(
            max_retries=_env_int("MSBFS_RETRIES", 2),
            base_delay=_env_float("MSBFS_BACKOFF", 0.1),
            seed=_env_int("MSBFS_FAULT_SEED", 0),
        ),
        watchdog=_env_float("MSBFS_WATCHDOG", 0.0) or None,
        ladder=ladder,
        auditor=auditor,
        audit_sample=sample,
    )
    # Observability: the negotiated route label rides the supervisor so
    # the registry's describe() can report WHICH lattice point serves
    # each graph (entries built before this attribute report None).
    sup.engine_label = label
    return sup


def build_supervised_weighted_engine(graph) -> ChunkSupervisor:
    """The weighted serving route (``weighted: true`` queries): a
    delta-stepping engine negotiated by capability token
    (``MSBFS_WEIGHTED_ENGINE``), supervised with the same retry/
    watchdog knobs as the unit-cost route, audited — when
    ``MSBFS_AUDIT`` is armed — against the weighted five-invariant
    certificate (``ops.certify.WEIGHTED_INVARIANTS``).  Raises
    InputError on a weightless graph (the caller surfaces it as the
    typed query refusal)."""
    from ..weighted import negotiate_weighted_engine

    _, engine = negotiate_weighted_engine(graph)
    sample = audit_sample_rate()
    auditor = None
    if sample > 0.0:
        from ..ops.certify import make_weighted_auditor

        auditor = make_weighted_auditor(graph)
    return ChunkSupervisor(
        engine,
        policy=RetryPolicy(
            max_retries=_env_int("MSBFS_RETRIES", 2),
            base_delay=_env_float("MSBFS_BACKOFF", 0.1),
            seed=_env_int("MSBFS_FAULT_SEED", 0),
        ),
        watchdog=_env_float("MSBFS_WATCHDOG", 0.0) or None,
        auditor=auditor,
        audit_sample=sample,
    )


@dataclass
class GraphEntry:
    """One registered graph: host CSR + supervised device engine.

    ``deltas``/``delta_version`` carry the dynamic-graph version chain
    (docs/SERVING.md "Mutations & versions"): a ``mutate`` appends to
    the :class:`..dynamic.delta.DeltaLog` and swaps in a new entry
    serving the patched CSR, with the chained content digest riding
    every cache key — the same stale-answers-are-unreachable mechanism
    reload's version bump uses, one axis deeper.
    """

    name: str
    path: str
    hash: str
    version: int
    graph: object
    supervisor: ChunkSupervisor
    loaded_at: float = field(default_factory=time.time)
    lock: threading.Lock = field(default_factory=threading.Lock)
    deltas: Optional[object] = None  # dynamic.delta.DeltaLog
    delta_version: int = 0
    # Lazily-built weighted supervisor (weighted: true queries): most
    # registered graphs never see a weighted query, so the
    # delta-stepping engine build is deferred to first use.
    weighted_supervisor: Optional[ChunkSupervisor] = None

    def get_weighted_supervisor(self) -> ChunkSupervisor:
        """The entry's weighted serving engine, built on first use
        under the entry lock.  Raises InputError (via the negotiation)
        when the graph carries no cost section — a ``weighted: true``
        query against a weightless graph is the caller's typed
        refusal."""
        sup = self.weighted_supervisor
        if sup is not None:
            return sup
        with self.lock:
            if self.weighted_supervisor is None:
                self.weighted_supervisor = build_supervised_weighted_engine(
                    self.graph
                )
            return self.weighted_supervisor

    @property
    def digest(self) -> str:
        """Content-derived identity of what is actually served: the
        file hash at delta-version 0, the chained delta digest after a
        mutate — ``(base_digest, version)`` collapsed to one label."""
        if self.deltas is None or self.delta_version == 0:
            return self.hash
        return self.deltas.digest(self.delta_version)

    @property
    def key(self) -> str:
        """Cache-key stem: name, content hash AND version — reload (same
        name, new bytes, bumped version) can never collide with entries
        cached before it; a mutate appends its chained delta digest so
        pre-mutation results are unreachable the same way."""
        stem = f"{self.name}@{self.hash}/v{self.version}"
        if self.delta_version:
            stem += f"+m{self.delta_version}.{self.digest}"
        return stem

    def version_chain(self) -> list:
        """The ``versions`` verb payload: one row per delta version,
        digests chained from the base content hash."""
        out = [
            {
                "version": 0,
                "digest": self.hash,
                "inserts": 0,
                "deletes": 0,
            }
        ]
        if self.deltas is not None:
            out.extend(
                {
                    "version": int(b.version),
                    "digest": b.digest,
                    "inserts": int(b.inserts.shape[0]),
                    "deletes": int(b.deletes.shape[0]),
                }
                for b in self.deltas.batches
            )
        return out

    def describe(self) -> dict:
        return {
            "name": self.name,
            "path": self.path,
            "hash": self.hash,
            "version": self.version,
            "delta_version": self.delta_version,
            "digest": self.digest,
            "n": int(self.graph.n),
            "directed_edges": int(self.graph.num_directed_edges),
            "weighted": bool(getattr(self.graph, "has_weights", False)),
            "engine": getattr(self.supervisor, "engine_label", None),
            "loaded_at": round(self.loaded_at, 3),
        }


class GraphRegistry:
    """Named, versioned graph store behind the daemon's verbs."""

    def __init__(self):
        self._entries: Dict[str, GraphEntry] = {}
        self._lock = threading.Lock()

    def load(
        self, name: str, path: str, expected_hash: Optional[str] = None
    ) -> GraphEntry:
        """Register ``path`` under ``name`` (load-once).  Same name +
        same bytes: returns the existing device-resident entry without
        touching the device.  Same name + different bytes: InputError
        (use :meth:`reload`).

        ``expected_hash`` is the integrity contract for re-registration
        paths that REMEMBER what the bytes used to be — journal replay
        and fleet reconcile: when the on-disk file no longer hashes to
        it, registration is refused with a typed
        :class:`CorruptionError` (the file changed underneath the
        journal; serving it would silently answer from different data
        than the journal promised)."""
        digest = content_hash(path)
        if expected_hash is not None and digest != expected_hash:
            raise CorruptionError(
                f"graph {name!r} at {path} hashes to {digest}, but its "
                f"registration records {expected_hash}: the file changed "
                "underneath the journal — refusing to re-register "
                "silently different content",
                invariants=("content-digest",),
            )
        with self._lock:
            have = self._entries.get(name)
            if have is not None:
                if have.hash == digest:
                    return have
                raise InputError(
                    f"graph {name!r} is already registered with different "
                    f"content (have {have.hash}, file is {digest}); use "
                    "reload to replace it"
                )
        graph = load_graph_bin(path)
        entry = GraphEntry(
            name=name,
            path=path,
            hash=digest,
            version=1,
            graph=graph,
            supervisor=build_supervised_engine(graph, content_digest=digest),
        )
        with self._lock:
            # Lost-race rule: first registration wins, identical content
            # from the racer is a benign no-op hit.
            have = self._entries.get(name)
            if have is not None and have.hash == digest:
                return have
            if have is not None:
                raise InputError(
                    f"graph {name!r} was concurrently registered with "
                    "different content"
                )
            self._entries[name] = entry
        return entry

    def reload(self, name: str) -> GraphEntry:
        """Re-read the entry's path, rebuild the engine, bump version.
        The new entry replaces the old atomically; in-flight requests
        against the old entry finish on the old engine (its arrays stay
        alive until the last reference drops)."""
        with self._lock:
            have = self._entries.get(name)
        if have is None:
            raise InputError(f"no graph registered as {name!r}")
        digest = content_hash(have.path)
        graph = load_graph_bin(have.path)
        entry = GraphEntry(
            name=name,
            path=have.path,
            hash=digest,
            version=have.version + 1,
            graph=graph,
            supervisor=build_supervised_engine(graph, content_digest=digest),
        )
        with self._lock:
            self._entries[name] = entry
        return entry

    def mutate(self, name: str, inserts, deletes) -> Tuple[GraphEntry, object]:
        """Append one edge-delta batch to ``name``'s version chain and
        atomically swap in an entry serving the patched dedup CSR
        (``dynamic.delta.DeltaLog.apply`` — bit-identical to a from-
        scratch rebuild on the mutated edge list).  Returns (new entry,
        appended batch).  In-flight requests against the old entry
        finish on the old engine, exactly like reload; their results are
        keyed to the old entry key, so they can never be served against
        a post-delta question.

        Callers serialize mutations per name (the daemon funnels the
        ``mutate`` verb through one lock); a concurrent reload loses the
        swap race loudly rather than silently dropping the chain."""
        from ..dynamic.delta import DeltaLog  # lazy: registry loads fast

        with self._lock:
            have = self._entries.get(name)
        if have is None:
            raise InputError(f"no graph registered as {name!r}")
        with have.lock:
            log = have.deltas
            if log is None:
                log = DeltaLog.from_graph(have.graph, have.hash)
            try:
                batch = log.append(inserts, deletes)
            except ValueError as exc:
                raise InputError(f"mutate {name!r}: {exc}")
            graph, _ = log.apply()
            entry = GraphEntry(
                name=name,
                path=have.path,
                hash=have.hash,
                version=have.version,
                graph=graph,
                supervisor=build_supervised_engine(
                    graph, content_digest=batch.digest
                ),
                deltas=log,
                delta_version=log.version,
            )
        with self._lock:
            cur = self._entries.get(name)
            if cur is not have:
                raise InputError(
                    f"graph {name!r} was replaced while mutating; "
                    "re-issue the mutation against the new registration"
                )
            self._entries[name] = entry
        return entry, batch

    def get(self, name: str) -> GraphEntry:
        with self._lock:
            entry = self._entries.get(name)
            have = sorted(self._entries)
        if entry is None:
            raise InputError(
                f"no graph registered as {name!r} "
                f"(have: {', '.join(have) or 'none'})"
            )
        return entry

    def maybe_get(self, name: str) -> Optional[GraphEntry]:
        with self._lock:
            return self._entries.get(name)

    def evict(self, name: str) -> Optional[GraphEntry]:
        """Drop a registration (journal replay refusing a delta chain
        that no longer verifies: serving the base content would silently
        answer from pre-mutation data the journal promised was mutated).
        Returns the removed entry, or None when nothing was registered;
        in-flight requests against the removed entry finish on its
        engine — the arrays live until the last reference drops."""
        with self._lock:
            return self._entries.pop(name, None)

    def describe(self) -> dict:
        with self._lock:
            entries = list(self._entries.values())
        return {e.name: e.describe() for e in entries}
