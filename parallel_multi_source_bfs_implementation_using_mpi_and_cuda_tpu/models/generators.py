"""Graph and query generators for tests/benchmarks (no reference analog —
the reference ships no generators or fixtures; SURVEY.md section 4 calls for
creating them from scratch).

Covers the BASELINE.json config families: RMAT (power-law, low diameter),
2-D grid (road-like, high diameter), and uniform G(n, m).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    native: bool = None,
) -> Tuple[int, np.ndarray]:
    """Graph500-style R-MAT: n = 2^scale vertices, m = edge_factor * n records.

    Vectorized quadrant sampling (one (m, scale) draw), no per-edge Python.
    Returns (n, edges[m, 2] int32); duplicates/self-loops are kept, matching
    the reference loader's no-dedup behavior (main.cu:106-116).

    ``native`` (default: env MSBFS_NATIVE_RMAT=1) samples via the C++
    generator (runtime/loader.cpp msbfs_rmat_edges) — same construction,
    ~20x faster at RMAT-25 scale, but a DIFFERENT RNG stream, so a given
    seed yields a different (identically distributed) graph; existing
    BASELINE rows keep the NumPy stream for comparability.
    """
    n = 1 << scale
    m = edge_factor * n
    d = 1.0 - a - b - c
    if native is None:
        from ..utils import knobs

        native = knobs.raw("MSBFS_NATIVE_RMAT") == "1"
    if native:
        from ..runtime import native_loader

        edges = native_loader.rmat_edges(scale, m, a, b, c, seed)
        if edges is None:
            # Explicitly requested stream must not silently substitute the
            # NumPy one (same seed, DIFFERENT graph -> irreproducible
            # benchmark rows); same contract as utils/io.py's native flag.
            from ..runtime.supervisor import InputError

            raise InputError(
                "native R-MAT requested (MSBFS_NATIVE_RMAT/native=True) "
                "but librt_loader.so is not built (run `make native`)"
            )
        return n, edges
    rng = np.random.default_rng(seed)
    # Level-by-level quadrant sampling (keeps peak memory at O(m), not
    # O(m * scale)): P(u_bit=1) = c+d; P(v_bit=1 | u_bit) = b/(a+b) or
    # d/(c+d) — the same joint distribution as drawing the quadrant.
    u = np.zeros(m, dtype=np.int64)
    v = np.zeros(m, dtype=np.int64)
    p_u1 = c + d
    p_v1_given_u0 = b / (a + b)
    p_v1_given_u1 = d / (c + d)
    for _ in range(scale):
        u_bit = rng.random(m) < p_u1
        p_v1 = np.where(u_bit, p_v1_given_u1, p_v1_given_u0)
        v_bit = rng.random(m) < p_v1
        u = (u << 1) | u_bit
        v = (v << 1) | v_bit
    # Permute vertex ids so degree is not correlated with id (standard
    # Graph500 step, keeps the power-law but randomizes layout).
    perm = rng.permutation(n).astype(np.int64)
    edges = np.stack([perm[u.astype(np.int64)], perm[v.astype(np.int64)]], axis=1)
    return n, edges.astype(np.int32)


def grid_edges(rows: int, cols: int) -> Tuple[int, np.ndarray]:
    """4-neighbor grid: n = rows*cols, high diameter (road-network stand-in
    for the USA-road-d config in BASELINE.json)."""
    idx = np.arange(rows * cols, dtype=np.int32).reshape(rows, cols)
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    edges = np.concatenate([right, down], axis=0).astype(np.int32)
    return rows * cols, edges


def road_edges(
    rows: int,
    cols: int,
    seed: int = 0,
    keep: float = 0.55,
    diag: float = 0.06,
    shortcut_frac: float = 0.0005,
    shortcut_reach: int = 0,
) -> Tuple[int, np.ndarray]:
    """Synthetic road network calibrated to the DIMACS USA-road-d family
    (the real dataset is unavailable in this sandbox — zero egress; this is
    the documented stand-in BASELINE.md config 4 uses).

    Construction and calibration targets:

    * 4-neighbor grid with each edge kept with probability ``keep`` —
      irregular connectivity and dead ends like a real street network;
    * diagonal (down-right / down-left) links with probability ``diag`` —
      non-gridlike junctions;
    * ``shortcut_frac * n`` medium-range links (highway segments), each
      connecting a node to one <= ``shortcut_reach`` (default side/8) grid
      steps away in each axis: shortens paths regionally WITHOUT the
      global small-world collapse uniform random pairs would cause;
    * defaults give mean undirected degree 2 * (2*keep + 2*diag) ~ 2.44 —
      USA-road-d's 58.3M arcs / 23.9M nodes — and diameter Theta(rows+cols)
      like the real network's ~8000-hop diameter at its scale.

    Returns (n, edges) in the reference loader's convention (each line one
    undirected edge, doubled by the CSR build, main.cu:106-116).
    """
    rng = np.random.default_rng(seed)
    n = rows * cols
    idx = np.arange(n, dtype=np.int32).reshape(rows, cols)
    parts = []
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    parts.append(right[rng.random(len(right)) < keep])
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    parts.append(down[rng.random(len(down)) < keep])
    dr = np.stack([idx[:-1, :-1].ravel(), idx[1:, 1:].ravel()], axis=1)
    parts.append(dr[rng.random(len(dr)) < diag])
    dl = np.stack([idx[:-1, 1:].ravel(), idx[1:, :-1].ravel()], axis=1)
    parts.append(dl[rng.random(len(dl)) < diag])
    k = int(n * shortcut_frac)
    if k:
        reach = shortcut_reach or max(2, min(rows, cols) // 8)
        r0 = rng.integers(0, rows, size=k)
        c0 = rng.integers(0, cols, size=k)
        r1 = np.clip(r0 + rng.integers(-reach, reach + 1, size=k), 0, rows - 1)
        c1 = np.clip(c0 + rng.integers(-reach, reach + 1, size=k), 0, cols - 1)
        parts.append(
            np.stack([idx[r0, c0], idx[r1, c1]], axis=1).astype(np.int32)
        )
    edges = np.concatenate(parts, axis=0).astype(np.int32)
    return n, edges


def hub_tail_edges(
    tail: int = 2500, hub_fan: int = 100
) -> Tuple[int, np.ndarray]:
    """Adversarial degree profile: a ``tail``-vertex path (deep BFS) with
    one ``hub_fan``-degree hub grafted onto vertex 0 — high max degree on
    a high-diameter graph.  This is the shape that fooled the round-3
    road-class heuristic (max_degree <= 64) into the unbounded dispatch
    path (VERDICT r3); the bounded level loop must engage on it.  Layout:
    path 0..tail-1, hub = ``tail``, leaves ``tail+1..n-1``."""
    n = tail + 1 + hub_fan
    path = np.stack([np.arange(tail - 1), np.arange(1, tail)], axis=1)
    hub = tail
    leaves = np.stack(
        [np.full(hub_fan, hub), np.arange(tail + 1, n)], axis=1
    )
    bridge = np.array([[0, hub]])
    return n, np.concatenate([path, bridge, leaves]).astype(np.int64)


def gnm_edges(n: int, m: int, seed: int = 0) -> Tuple[int, np.ndarray]:
    """Uniform G(n, m) multigraph (duplicates and self-loops possible)."""
    rng = np.random.default_rng(seed)
    edges = rng.integers(0, n, size=(m, 2), dtype=np.int64).astype(np.int32)
    return n, edges


def edge_costs(
    m: int,
    dist: str = "uniform",
    max_cost: int = 16,
    seed: int = 0,
    zipf_a: float = 1.6,
) -> np.ndarray:
    """Deterministic positive integer edge costs for the weighted/
    subsystem: (m,) int32 in [1, max_cost].

    ``uniform`` draws each cost uniformly (road-style travel costs);
    ``zipf`` draws a heavy-tailed Zipf(``zipf_a``) clipped to
    ``max_cost`` (latency-graph style: most links cheap, a few
    expensive).  Same seed -> same costs, independent of the platform's
    BLAS/thread count (pure ``default_rng`` streams).
    """
    if m < 0:
        raise ValueError(f"m must be >= 0, got {m}")
    if max_cost < 1:
        raise ValueError(f"max_cost must be >= 1, got {max_cost}")
    rng = np.random.default_rng(seed)
    if dist == "uniform":
        w = rng.integers(1, max_cost + 1, size=m, dtype=np.int64)
    elif dist == "zipf":
        w = np.minimum(rng.zipf(zipf_a, size=m), max_cost)
    else:
        raise ValueError(f"unknown cost distribution {dist!r}")
    return w.astype(np.int32)


def delta_batches(
    n: int,
    edges: np.ndarray,
    batches: int = 1,
    batch_size: int = 16,
    locality: float = 0.9,
    insert_frac: float = 0.5,
    seed: int = 0,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Seeded edge-delta batches against (n, edges) for the dynamic-graph
    subsystem (``dynamic.delta``): each batch is (inserts, deletes) pair
    arrays, ``batch_size`` mutations split ``insert_frac``/rest.

    ``locality`` in [0, 1] is the knob bench config 8 sweeps: each batch
    draws every endpoint from one contiguous vertex-id window of
    ``max(8, round(n * (1 - locality)))`` ids — 1.0 is a street-closure-
    sized patch (grid/road layouts are row-major, so an id window IS a
    spatial patch), 0.0 is whole-graph churn.  Deletes are drawn from
    the LIVE canonical edge set (batches compose: an edge deleted in
    batch i is not re-deleted in batch j), entirely inside the window;
    inserts are fresh window-local pairs.  Deterministic per seed.
    """
    from ..dynamic.delta import canonical_edge_keys, keys_to_pairs  # lazy:
    # models must stay importable without the dynamic subsystem loaded

    if not 0.0 <= locality <= 1.0:
        raise ValueError(f"locality must be in [0, 1], got {locality}")
    rng = np.random.default_rng(seed)
    live = canonical_edge_keys(np.asarray(edges))
    span = max(8, int(round(n * (1.0 - locality))))
    span = min(span, n)
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for _ in range(batches):
        lo = int(rng.integers(0, max(1, n - span + 1)))
        hi = lo + span
        n_ins = int(round(batch_size * insert_frac))
        n_del = batch_size - n_ins
        ins = rng.integers(lo, hi, size=(n_ins, 2), dtype=np.int64).astype(
            np.int32
        )
        pairs = keys_to_pairs(live)
        in_window = (pairs[:, 0] >= lo) & (pairs[:, 1] < hi)
        candidates = live[in_window]
        take = min(n_del, candidates.size)
        dels_keys = (
            rng.choice(candidates, size=take, replace=False)
            if take
            else np.zeros(0, dtype=np.int64)
        )
        dels = keys_to_pairs(np.sort(dels_keys))
        out.append((ins, dels))
        live = np.union1d(
            np.setdiff1d(live, dels_keys, assume_unique=False),
            canonical_edge_keys(ins),
        )
    return out


def random_queries(
    n: int, k: int, max_group: int = 128, seed: int = 0
) -> List[np.ndarray]:
    """K ragged source groups with sizes in [1, max_group] (query format
    limits: K <= 255, group size <= 255; reference comments say 64/128,
    main.cu:145,152)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(k):
        size = int(rng.integers(1, max_group + 1))
        out.append(rng.integers(0, n, size=size, dtype=np.int64).astype(np.int32))
    return out


def component_labels(
    n: int, edges: np.ndarray, sample_cap: int = 1 << 24, seed: int = 0
) -> np.ndarray:
    """Per-vertex connected-component label (= min vertex id in the
    component), Shiloach-Vishkin style hooking + pointer jumping on NumPy.

    For edge lists beyond ``sample_cap`` rows a uniform edge SAMPLE is
    labeled instead of the full list.  That under-merges — sampled labels
    refine the true components — which is exactly the safe direction for
    the only consumer (:func:`ensure_giant_sources`): any two vertices
    sharing a SAMPLED label share a true component, so membership in the
    sampled giant certifies membership in the true giant; the sweep can
    only be more conservative, never wrong (round 7; fixture rule for
    BASELINE "minF > 0" headline groups)."""
    label = np.arange(n, dtype=np.int64)
    e = np.asarray(edges, dtype=np.int64)
    e = e[(e[:, 0] >= 0) & (e[:, 0] < n) & (e[:, 1] >= 0) & (e[:, 1] < n)]
    if len(e) > sample_cap:
        # With-replacement draw: duplicate edges are harmless to labeling,
        # and a without-replacement pick would materialize an O(len(e))
        # permutation on RMAT-25-class lists.
        rng = np.random.default_rng(seed)
        e = e[rng.integers(0, len(e), size=sample_cap)]
    u, v = e[:, 0], e[:, 1]
    while True:
        lu, lv = label[u], label[v]
        # Hook: every edge pulls both endpoints' labels down to the
        # smaller one; np.minimum.at resolves colliding writes by min.
        m = np.minimum(lu, lv)
        before = label.copy()
        np.minimum.at(label, u, m)
        np.minimum.at(label, v, m)
        # Pointer-jump to the fixed point so labels stay canonical
        # (label[i] == label[label[i]]) before the convergence test.
        while True:
            nxt = label[label]
            if np.array_equal(nxt, label):
                break
            label = nxt
        if np.array_equal(label, before):
            return label


def ensure_giant_sources(
    queries: List[np.ndarray],
    n: int,
    edges: np.ndarray,
    seed: int = 0,
) -> List[np.ndarray]:
    """Fixture rule (round 7): every query group gets >= 1 source in the
    largest connected component, by replacing source 0 of offending
    groups with a seeded draw from the giant.

    Why: a group whose sources all land in dust components reaches only
    that dust, F(U) collapses to near zero, and ``best()`` degenerates to
    "whichever group saw the fewest vertices" — the headline benchmark
    then reports a minF == 0 argmin race instead of distance-to-set work
    (BASELINE round-6 config-2/3 rows did exactly this).  Anchoring one
    source per group in the giant makes every headline row satisfy
    minF > 0 while keeping the other sources' dust-vs-giant mix intact.
    Groups are modified copies; the input list is not mutated."""
    labels = component_labels(n, edges, seed=seed)
    ids, counts = np.unique(labels, return_counts=True)
    giant_label = ids[np.argmax(counts)]
    giant = np.flatnonzero(labels == giant_label).astype(np.int32)
    rng = np.random.default_rng(seed)
    out = []
    for g in queries:
        g = np.asarray(g, dtype=np.int32)
        valid = g[(g >= 0) & (g < n)]
        if valid.size and np.any(labels[valid] == giant_label):
            out.append(g)
            continue
        fixed = g.copy()
        if fixed.size == 0:
            fixed = np.empty(1, dtype=np.int32)
        fixed[0] = giant[int(rng.integers(0, len(giant)))]
        out.append(fixed)
    return out
