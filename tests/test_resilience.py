"""Fault-tolerant execution tests (docs/RESILIENCE.md): deterministic
fault injection (utils.faults), the typed error taxonomy + exit codes,
retry with backoff, dispatch watchdog, capacity degradation down the
routing ladder, and degrade-to-survivors resharding on the 8-device
virtual CPU mesh — every recovery path the runtime promises, rehearsed
with injected faults, ending in bit-identical (F, argmin) results.
"""

import re
import time

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
    main,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.scheduler import (
    cyclic_assignment,
    reassign,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (
    CapacityError,
    ChunkSupervisor,
    DeviceError,
    InputError,
    MsbfsError,
    RetryPolicy,
    TransientError,
    call_with_watchdog,
    classify,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils import (
    faults,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.faults import (
    FaultPlan,
    SimulatedChipLoss,
    SimulatedResourceExhausted,
    SimulatedUnavailable,
    injected,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    save_graph_bin,
    save_query_bin,
)

from oracle import oracle_best, oracle_bfs, oracle_f

FAST = RetryPolicy(max_retries=2, base_delay=0.001, max_delay=0.01)

REPORT_TAIL_RE = re.compile(
    r"Query number \(k\) with minimum F value: (?P<mink>-?\d+)\n"
    r"Minimum F value: (?P<minf>-?\d+)\n"
)


# ---------------------------------------------------------------------------
# Fault-plan grammar and replay
# ---------------------------------------------------------------------------


def test_plan_parse_grammar():
    plan = FaultPlan.parse(
        "io:load_graph:1, oom:dispatch:2 ,hang:dispatch:3,chip:rank1:1"
    )
    kinds = [s.kind for s in plan.specs]
    assert kinds == ["io", "oom", "hang", "chip"]
    chip = plan.specs[-1]
    assert chip.rank == 1 and chip.site == "rank1"
    # Chips die during dispatches: the spec's counter is the dispatch one.
    assert chip.trip_site == "dispatch"
    assert plan.specs[0].trip_site == "load_graph"


@pytest.mark.parametrize(
    "bad",
    [
        "io:load_graph",  # missing count
        "nope:dispatch:1",  # unknown kind
        "io:load_graph:zero",  # non-integer count
        "io:load_graph:0",  # counts are 1-based
        "chip:dispatch:1",  # chip faults need rank<r>
    ],
)
def test_plan_malformed_fails_loud(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_plan_fires_once_on_nth_trip_and_replays():
    plan = FaultPlan.parse("transient:dispatch:2")
    plan.trip("dispatch")  # 1st: not due yet
    with pytest.raises(SimulatedUnavailable):
        plan.trip("dispatch")  # 2nd: fires
    plan.trip("dispatch")  # 3rd: spent, no-op
    assert plan.pending() == []
    plan.reset()  # replay: identical trace
    plan.trip("dispatch")
    with pytest.raises(SimulatedUnavailable):
        plan.trip("dispatch")


def test_plan_sites_are_independent():
    plan = FaultPlan.parse("io:load_graph:1")
    plan.trip("dispatch")  # other sites never advance this spec
    plan.trip("load_query")
    with pytest.raises(IOError):
        plan.trip("load_graph")


def test_active_plan_seam():
    plan = FaultPlan.parse("corrupt:load_query:1")
    with injected(plan):
        assert faults.active_plan() is plan
        with pytest.raises(ValueError):
            faults.trip("load_query")
    assert faults.active_plan() is None
    faults.trip("load_query")  # no active plan: free no-op


# ---------------------------------------------------------------------------
# Taxonomy and exit codes
# ---------------------------------------------------------------------------


def test_classify_taxonomy_and_exit_codes():
    oom = classify(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert isinstance(oom, CapacityError) and oom.exit_code == 3
    gone = classify(RuntimeError("UNAVAILABLE: socket closed"))
    assert isinstance(gone, TransientError) and gone.exit_code == 5
    assert isinstance(classify(TimeoutError("deadline")), TransientError)
    chip = classify(SimulatedChipLoss("rank down", {3}))
    assert isinstance(chip, DeviceError) and chip.exit_code == 4
    assert chip.failed_ranks == frozenset({3})
    bad = classify(ValueError("truncated file"))
    assert isinstance(bad, InputError) and bad.exit_code == 1
    other = classify(RuntimeError("weird"))
    assert type(other) is MsbfsError and other.exit_code == 6
    # Idempotent on taxonomy instances (exception chains re-classify).
    assert classify(oom) is oom


def test_exit_codes_are_distinct():
    codes = [
        e.exit_code
        for e in (MsbfsError, InputError, CapacityError, DeviceError,
                  TransientError)
    ]
    assert len(set(codes)) == len(codes)
    assert 0 not in codes and -1 not in codes  # success/usage stay theirs


# ---------------------------------------------------------------------------
# Retry policy and watchdog
# ---------------------------------------------------------------------------


def test_retry_backoff_deterministic_and_bounded():
    a = list(RetryPolicy(max_retries=4, base_delay=0.1, seed=7).delays())
    b = list(RetryPolicy(max_retries=4, base_delay=0.1, seed=7).delays())
    c = list(RetryPolicy(max_retries=4, base_delay=0.1, seed=8).delays())
    assert a == b  # replayable for a given MSBFS_FAULT_SEED
    assert a != c  # jitter decorrelates differently-seeded workers
    assert len(a) == 4
    assert all(d <= 30.0 for d in a)
    # Exponential growth survives the +/-50% jitter between steps of 2x.
    assert a[2] > a[0] and a[3] > a[1]


def test_watchdog_passes_results_and_errors_through():
    assert call_with_watchdog(lambda: 41 + 1, None) == 42
    assert call_with_watchdog(lambda: "ok", 5.0) == "ok"
    with pytest.raises(KeyError):
        call_with_watchdog(lambda: {}["x"], 5.0)


def test_watchdog_kills_hung_dispatch():
    t0 = time.perf_counter()
    with pytest.raises(TransientError, match="watchdog"):
        call_with_watchdog(lambda: time.sleep(5.0), 0.2)
    assert time.perf_counter() - t0 < 2.0  # did not wait out the hang


# ---------------------------------------------------------------------------
# Degrade-to-survivors rescheduling
# ---------------------------------------------------------------------------


def test_reassign_redistributes_orphans_cyclically():
    w, k = 4, 11
    out = reassign(k, w, failed_ranks={1})
    assert out[1] == []  # the dead rank owns nothing
    base = cyclic_assignment(k, w)
    orphans = base[1]  # [1, 5, 9]
    survivors = [0, 2, 3]
    for i, gid in enumerate(orphans):
        assert gid in out[survivors[i % 3]]
    # Exact cover: every query id exactly once across all ranks.
    flat = sorted(g for row in out for g in row)
    assert flat == list(range(k))


def test_reassign_multi_failure_and_no_survivors():
    out = reassign(8, 4, failed_ranks={0, 2})
    assert out[0] == [] and out[2] == []
    assert sorted(g for row in out for g in row) == list(range(8))
    with pytest.raises(ValueError):
        reassign(8, 4, failed_ranks={0, 1, 2, 3})


# ---------------------------------------------------------------------------
# ChunkSupervisor recovery loop (toy engine: no jax dispatch needed)
# ---------------------------------------------------------------------------


class ToyEngine:
    """Minimal engine: f_values is base + queries' row sums."""

    def __init__(self, tag=0):
        self.tag = tag
        self.calls = 0

    def f_values(self, queries):
        self.calls += 1
        return np.asarray(queries).sum(axis=1)

    def best(self, queries):
        f = self.f_values(queries)
        return int(f.min()), int(f.argmin())


def test_supervisor_transient_retry_bit_identical():
    q = np.arange(12, dtype=np.int32).reshape(4, 3)
    want = ToyEngine().f_values(q)
    plan = FaultPlan.parse("transient:dispatch:1")
    sup = ChunkSupervisor(ToyEngine(), policy=FAST, plan=plan)
    got = sup.f_values(q)
    assert np.array_equal(got, want)
    assert [e["action"] for e in sup.events] == ["retry"]
    assert sup.engine.calls == 1  # attempt 1 died before the engine ran


def test_supervisor_retry_budget_exhausts_to_transient_error():
    plan = FaultPlan.parse(
        "transient:dispatch:1,transient:dispatch:2,transient:dispatch:3"
    )
    sup = ChunkSupervisor(
        ToyEngine(),
        policy=RetryPolicy(max_retries=2, base_delay=0.001),
        plan=plan,
    )
    with pytest.raises(TransientError):
        sup.f_values(np.zeros((2, 2), dtype=np.int32))
    assert len(sup.events) == 2  # both retries recorded before giving up


def test_supervisor_capacity_degrades_down_ladder():
    class OomAlways:
        def f_values(self, queries):
            raise SimulatedResourceExhausted("RESOURCE_EXHAUSTED: injected")

    q = np.ones((3, 2), dtype=np.int32)
    sup = ChunkSupervisor(
        OomAlways(),
        policy=FAST,
        ladder=[("level-chunked", OomAlways), ("streamed", ToyEngine)],
    )
    got = sup.f_values(q)
    assert np.array_equal(got, ToyEngine().f_values(q))
    assert [e["action"] for e in sup.events] == ["degrade", "degrade"]
    assert [e["to"] for e in sup.events] == ["level-chunked", "streamed"]
    # Ladder exhausted: the next capacity fault is terminal.
    sup2 = ChunkSupervisor(OomAlways(), policy=FAST, ladder=[])
    with pytest.raises(CapacityError):
        sup2.f_values(q)


def test_supervisor_watchdog_retry_recovers():
    plan = FaultPlan.parse("hang:dispatch:1")
    plan.hang_seconds = 1.0
    q = np.arange(6, dtype=np.int32).reshape(2, 3)
    sup = ChunkSupervisor(ToyEngine(), policy=FAST, watchdog=0.2, plan=plan)
    t0 = time.perf_counter()
    got = sup.f_values(q)
    assert np.array_equal(got, ToyEngine().f_values(q))
    assert time.perf_counter() - t0 < 3.0
    assert sup.events[0]["action"] == "retry"
    assert "watchdog" in sup.events[0]["error"]


def test_supervisor_unrecoverable_device_error():
    class Doomed:
        def f_values(self, queries):
            raise SimulatedChipLoss("rank 1 gone", {1})

    sup = ChunkSupervisor(Doomed(), policy=FAST)  # no without_ranks
    with pytest.raises(DeviceError) as ei:
        sup.f_values(np.zeros((1, 1), dtype=np.int32))
    assert ei.value.failed_ranks == frozenset({1})


def test_supervisor_delegates_unknown_attributes():
    toy = ToyEngine(tag=9)
    sup = ChunkSupervisor(toy, policy=FAST)
    assert sup.tag == 9
    with pytest.raises(AttributeError):
        sup.nonexistent_attr


# ---------------------------------------------------------------------------
# Chip loss on the 8-device virtual mesh: reshard, bit-identical results
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh_engine():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.distributed import (
        DistributedEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    n, edges = generators.gnm_edges(72, 210, seed=11)
    graph = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 10, max_group=4, seed=12)
    padded = pad_queries(queries)
    mesh = make_mesh(num_query_shards=8, devices=jax.devices()[:8])
    engine = DistributedEngine(mesh, graph)
    return engine, np.asarray(padded)


def test_chip_loss_resharding_is_bit_identical(mesh_engine):
    engine, padded = mesh_engine
    want = np.asarray(engine.f_values(padded))
    plan = FaultPlan.parse("chip:rank2:1")
    sup = ChunkSupervisor(engine, policy=FAST, plan=plan)
    got = np.asarray(sup.f_values(padded))
    assert np.array_equal(got, want)  # bit-identical F after resharding
    assert [e["action"] for e in sup.events] == ["reshard"]
    assert sup.events[0]["failed_ranks"] == [2]
    assert sup.events[0]["survivor_shards"] == 7
    assert sup.engine is not engine and sup.engine.w == 7


def test_repeated_chip_loss_until_no_survivors(mesh_engine):
    engine, padded = mesh_engine
    want_best = engine.best(padded)
    plan = FaultPlan.parse("chip:rank0:1,chip:rank1:2,chip:rank2:3")
    sup = ChunkSupervisor(engine, policy=FAST, plan=plan)
    assert sup.best(padded) == want_best
    assert [e["action"] for e in sup.events] == ["reshard"] * 3
    assert sup.engine.w == 5


def test_without_ranks_rejects_total_loss(mesh_engine):
    engine, _ = mesh_engine
    with pytest.raises(DeviceError):
        engine.without_ranks(set(range(engine.w)))


def test_device_put_fault_seam_retried(mesh_engine):
    """The query-upload seam (parallel.scheduler.shard_queries) consults
    the process-wide plan; an injected transient there is retried like
    any dispatch fault and the batch still lands bit-identical."""
    engine, padded = mesh_engine
    want = np.asarray(engine.f_values(padded))
    plan = FaultPlan.parse("transient:device_put:1")
    sup = ChunkSupervisor(engine, policy=FAST, plan=plan)
    with injected(plan):
        got = np.asarray(sup.f_values(padded))
    assert np.array_equal(got, want)
    assert [e["action"] for e in sup.events] == ["retry"]


# ---------------------------------------------------------------------------
# CLI end-to-end: fault plans through main(), documented exit codes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cli_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("resilience_cli")
    n, edges = generators.gnm_edges(80, 240, seed=31)
    queries = generators.random_queries(n, 8, max_group=4, seed=32)
    gpath, qpath = str(d / "g.bin"), str(d / "q.bin")
    save_graph_bin(gpath, n, edges)
    save_query_bin(qpath, queries)
    want = oracle_best([oracle_f(oracle_bfs(n, edges, q)) for q in queries])
    return gpath, qpath, want


def run_cli(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


def _check_report(out, want):
    min_f, min_k = want
    m = REPORT_TAIL_RE.search(out)
    assert m, f"no report in {out!r}"
    assert int(m["mink"]) == min_k + 1 and int(m["minf"]) == min_f


def test_cli_transient_fault_retried_to_success(cli_files, capsys, monkeypatch):
    gpath, qpath, want = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "transient:dispatch:1")
    monkeypatch.setenv("MSBFS_BACKOFF", "0.001")
    rc, out, _ = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"],
                         capsys)
    assert rc == 0  # retried behind the scenes, batch finished
    _check_report(out, want)


def test_cli_oom_degrades_without_dying(cli_files, capsys, monkeypatch):
    gpath, qpath, want = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "oom:dispatch:1")
    monkeypatch.setenv("MSBFS_BACKOFF", "0.001")
    rc, out, _ = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"],
                         capsys)
    assert rc == 0  # stepped down the ladder, same answer
    _check_report(out, want)


def test_cli_chip_loss_recovers_on_survivors(cli_files, capsys, monkeypatch):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    gpath, qpath, want = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "chip:rank1:1")
    monkeypatch.setenv("MSBFS_BACKOFF", "0.001")
    rc, out, _ = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "8"],
                         capsys)
    assert rc == 0
    _check_report(out, want)


def test_cli_hung_dispatch_watchdog_exit_code(cli_files, capsys, monkeypatch):
    gpath, qpath, _ = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "hang:dispatch:1")
    monkeypatch.setenv("MSBFS_FAULT_HANG", "2.0")
    monkeypatch.setenv("MSBFS_WATCHDOG", "0.2")
    monkeypatch.setenv("MSBFS_RETRIES", "0")
    rc, out, err = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"],
                           capsys)
    assert rc == TransientError.exit_code == 5
    assert "msbfs: TransientError" in err and "watchdog" in err
    assert "Minimum F value" not in out  # stdout contract: no half-report


def test_cli_io_fault_keeps_reference_exit(cli_files, capsys, monkeypatch):
    gpath, qpath, _ = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "io:load_graph:1")
    rc, _, err = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"],
                         capsys)
    assert rc == InputError.exit_code == 1  # reference EXIT_FAILURE
    assert "Could not open graph file" in err
    assert "msbfs: InputError" in err


def test_cli_malformed_fault_plan_fails_loud(cli_files, capsys, monkeypatch):
    gpath, qpath, _ = cli_files
    monkeypatch.setenv("MSBFS_FAULTS", "bogus")
    rc, _, err = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"],
                         capsys)
    assert rc == 1
    assert "msbfs: InputError" in err


# ---------------------------------------------------------------------------
# Checkpoint integration: supervised chunks land in the journal
# ---------------------------------------------------------------------------


def test_checkpoint_journals_supervised_chunks(tmp_path):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        BitBellEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.checkpoint import (
        CheckpointedRunner,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )

    n, edges = generators.gnm_edges(60, 150, seed=41)
    graph = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 6, max_group=3, seed=42)
    padded = np.asarray(pad_queries(queries))
    engine = BitBellEngine(BellGraph.from_host(graph))
    want = np.asarray(engine.f_values(padded))

    path = str(tmp_path / "journal.bin")
    plan = FaultPlan.parse("transient:dispatch:2")
    sup = ChunkSupervisor(engine, policy=FAST, plan=plan)
    runner = CheckpointedRunner(sup, path, chunk=2)
    f_arr, computed = runner.run(n, graph.num_directed_edges, padded)
    assert np.array_equal(np.asarray(f_arr), want)
    assert computed == padded.shape[0]
    assert any(e["action"] == "retry" for e in sup.events)

    # The retried chunk is in the journal like any other: a resumed run
    # recomputes nothing.
    runner2 = CheckpointedRunner(engine, path, chunk=2)
    f_arr2, computed2 = runner2.run(n, graph.num_directed_edges, padded)
    assert computed2 == 0
    assert np.array_equal(np.asarray(f_arr2), want)
