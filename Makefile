# Build the native (C++) runtime components.
PKG := parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu
CXX ?= g++
CXXFLAGS ?= -O3 -march=native -std=c++17 -fPIC -Wall -Wextra -pthread

.PHONY: native clean test resilience serve lifecycle perf-smoke mxu fleet audit stampede multichip dynamic observe analyze lockwatch netchaos weighted shards

native: $(PKG)/runtime/librt_loader.so

$(PKG)/runtime/librt_loader.so: $(PKG)/runtime/loader.cpp
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

clean:
	rm -f $(PKG)/runtime/librt_loader.so

# Fault-injection rehearsal on the virtual CPU mesh (docs/RESILIENCE.md):
# every recovery path — retry, watchdog, ladder, survivor resharding —
# driven by deterministic fault plans with a fixed jitter seed.
resilience: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_resilience.py -x -q

# Serving-runtime smoke (docs/SERVING.md): daemon up on a unix socket,
# 3 client queries (one result-cache hit), stats verb asserted.
serve: native
	JAX_PLATFORMS=cpu python -m $(PKG).serve.smoke

# Crash-safe lifecycle smoke (docs/SERVING.md "Crash recovery & probes"):
# journal replay after kill -9, graceful drain, health probe, poison
# quarantine — the in-process fast subset of tests/test_lifecycle.py.
lifecycle: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_lifecycle.py -x -q -m "not slow"

# Dispatch-budget regression guard (docs/PERF_NOTES.md "Dispatch diet"):
# scaled-down configs 1 and 4 at K=16 on CPU; asserts megachunk fusion
# keeps >= 2x dispatch reduction and pinned absolute budgets hold.
# Dispatch counts are platform-independent, so this pins the TPU cadence.
perf-smoke:
	JAX_PLATFORMS=cpu python benchmarks/perf_smoke.py

# MXU-engine suite (ops.mxu): the FULL tensor-core matrix, including
# the arms slow-marked out of tier-1 for wall-clock budget — rmat/road/
# stranded parity, K sweep, Pallas tile-chain parity (interpret mode on
# CPU), and every mxu agreement arm.
mxu:
	JAX_PLATFORMS=cpu python -m pytest tests/test_mxu.py -x -q
	JAX_PLATFORMS=cpu python -m pytest tests/test_engines_agree.py -x -q -k "mxu"

# Fleet-scale serving suite (docs/SERVING.md "Fleet"): placement ring,
# failover router, front end, journal satellites, AND the slow-marked
# multi-process chaos chain (replica_kill -> failover -> backoff
# restart -> journal replay, zero acked queries lost).
fleet: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_fleet.py -x -q

# Output-certification suite (docs/RESILIENCE.md "Silent data
# corruption"): certificate invariants, digest folding, the
# 100%-detection bitflip property test at every fault seam, and the
# certify arm of the engines-agreement matrix.
audit: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_certify.py -x -q
	JAX_PLATFORMS=cpu python -m pytest tests/test_engines_agree.py -x -q -k "audit"

# Flash-crowd autoscale suite (docs/SERVING.md "Autoscaling &
# overload"): the fast controller units (autoscaler hysteresis, token
# buckets, priority shed order, brownout ladder, weighted ring) plus
# the elastic-fleet stampede bench — scale-up reaction, interactive
# p99 under the crowd, zero acked-query loss across scale events.
stampede: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_stampede.py -x -q -m "not slow"
	JAX_PLATFORMS=cpu python benchmarks/bench_fleet.py --stampede

# Multi-chip 2D-partition suite (docs/MULTIHOST.md "2D partition"): the
# FULL mesh-shape x merge-tree parity matrix on the forced 8-device
# virtual mesh, including the shapes slow-marked out of tier-1 for
# wall-clock budget, the live-reshard arms (mid-drive chip kill through
# the supervisor), and the mesh2d arms of the engines-agreement matrix.
multichip: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_partition2d.py -x -q
	JAX_PLATFORMS=cpu python -m pytest tests/test_engines_agree.py -x -q -k "mesh2d"

# Dynamic-graph suite (docs/SERVING.md "Mutations & versions"): the
# versioned delta log (fuzz parity against from-scratch rebuilds),
# incremental BFS repair (bit-identical to full recompute + certified),
# the serve mutate/versions verbs with journaled replay, AND the repair
# arm of the engines-agreement matrix.
dynamic: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_dynamic.py -x -q
	JAX_PLATFORMS=cpu python -m pytest tests/test_engines_agree.py -x -q -k "repair"

# Unified-telemetry suite (docs/OBSERVABILITY.md): per-query distributed
# traces end to end (client -> router -> batcher -> supervisor -> engine
# chunk spans), the Prometheus metrics verb, fleet histogram roll-up,
# structured logging, and the crash flight recorder's exit-dump contract.
observe: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_observe.py -x -q

# Repo-native static analysis gate (docs/ANALYSIS.md): trace-safety
# lint over ops/ and parallel/, lock-discipline race detection over
# serve/ and runtime/, MSBFS_* knob-contract enforcement against
# utils/knobs.py + the README table, and raise/exit-code contract
# enforcement against the typed taxonomy + docs/RESILIENCE.md.  Pure
# stdlib ast — no jax import, runs in seconds.  Only findings absent
# from ANALYSIS_BASELINE.json fail the gate.
analyze:
	python -m $(PKG).analysis.cli

# Network-chaos suite (docs/SERVING.md "Cross-machine transport &
# fencing"): the message-level fault kinds (net_partition / net_delay /
# net_dup / net_reorder / half_open) at the frame seam, byte-level
# frame-reader fuzz, the epoch-fence matrix (equal/stale/future at
# ring, router and replica), exactly-once mutate dedup, and the TCP
# transport knobs.  The multi-process partition-heal chain is
# slow-marked out of this tier (run the file without -m to include it).
netchaos: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_netchaos.py -x -q -m "not slow"

# Weighted distance-to-set suite (docs/SERVING.md "Weighted queries"):
# the bucketed delta-stepping subsystem (weighted/) — artifact cost
# sections round-tripped and fuzzed, every negotiated flavor
# bit-identical to the pure-NumPy Dijkstra oracle, the weighted
# five-invariant certificate (including under bitflip chaos -> exit 9),
# certified weighted repair, and the weighted serve round trip — plus
# the weighted arms of the engines-agreement matrix.
weighted: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_weighted.py -x -q -m "not slow"
	JAX_PLATFORMS=cpu python -m pytest tests/test_engines_agree.py -x -q -k "weighted" -m "not slow"

# Dynamic lock-order watchdog (docs/ANALYSIS.md "Lock watchdog"): the
# concurrency-heavy suites run with every threading.Lock/RLock
# instrumented; any pair of locks ever taken in both orders — the
# deadlock precondition, even if this run didn't deadlock — fails the
# session with the witness stacks.
lockwatch: native
	JAX_PLATFORMS=cpu MSBFS_LOCK_WATCHDOG=1 MSBFS_FAULT_SEED=0 python -m pytest \
	    tests/test_serve.py tests/test_lifecycle.py tests/test_fleet.py \
	    tests/test_stampede.py tests/test_netchaos.py -x -q -m "not slow"

# Sharded-graph suite (docs/SERVING.md "Sharded graphs"): the shard
# planner (edge-balanced row splits, deterministic artifact digests),
# per-shard minimal-movement placement properties, the shard-manifest
# journal record fuzzed at every byte truncation, the shard_step verb's
# partial-adjacency guard, router scatter/gather bit-identical to the
# whole-graph oracle (including surviving-copy retry, typed
# ShardUnavailableError exit 11, and the degraded opt-in), and the
# disk_full chaos kinds -> typed StorageError exit 12.  The
# multi-process SIGKILL-mid-scatter reheal chain is slow-marked out of
# this tier (run the file without -m to include it).
shards: native
	JAX_PLATFORMS=cpu MSBFS_FAULT_SEED=0 python -m pytest tests/test_shards.py -x -q -m "not slow"

test: native analyze resilience serve lifecycle perf-smoke mxu fleet audit stampede multichip dynamic observe netchaos weighted shards
	python -m pytest tests/ -x -q
