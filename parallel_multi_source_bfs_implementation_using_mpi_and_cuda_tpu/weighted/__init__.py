"""Weighted distance-to-set: the bucketed delta-stepping subsystem.

Engines here speak the same :class:`ops.engine.QueryEngineBase`
contract as the unit-cost fleet — ``f_values`` is a cost sum instead of
a hop sum — and are negotiated onto representations through the same
capability-token seam (:func:`ops.engine.negotiate_engine`): the
``weighted`` token plus ``windowed`` / ``mesh2d`` structure tokens.
Asking for a combination no flavor provides fails loud naming the
missing tokens, never silently serving hop counts as costs.
"""

from __future__ import annotations

from typing import Optional

from ..ops.engine import negotiate_engine
from ..runtime.supervisor import InputError
from ..utils import knobs
from .deltastep import (
    INF,
    DeltaStepEngineBase,
    WeightedBitBellEngine,
    WeightedMesh2DEngine,
    WeightedStencilEngine,
    resolve_delta,
)

__all__ = [
    "INF",
    "DeltaStepEngineBase",
    "WeightedBitBellEngine",
    "WeightedStencilEngine",
    "WeightedMesh2DEngine",
    "resolve_delta",
    "weighted_candidates",
    "negotiate_weighted_engine",
]

#: flavor name -> extra capability tokens beyond the base ``weighted``.
_FLAVOR_TOKENS = {
    "auto": frozenset(),
    "bitbell": frozenset(),
    "stencil": frozenset({"windowed"}),
    "mesh2d": frozenset({"mesh2d"}),
}


def weighted_candidates(graph, delta: Optional[int] = None):
    """(label, engine_cls, factory) triples in preference order for
    :func:`ops.engine.negotiate_engine` — losers never build."""
    return [
        (
            "weighted-bitbell",
            WeightedBitBellEngine,
            lambda: WeightedBitBellEngine(graph, delta=delta),
        ),
        (
            "weighted-stencil",
            WeightedStencilEngine,
            lambda: WeightedStencilEngine(graph, delta=delta),
        ),
        (
            "weighted-mesh2d",
            WeightedMesh2DEngine,
            lambda: WeightedMesh2DEngine(graph, delta=delta),
        ),
    ]


def negotiate_weighted_engine(
    graph, flavor: Optional[str] = None, delta: Optional[int] = None
):
    """Negotiate a weighted engine for ``graph``.

    ``flavor`` (default: the ``MSBFS_WEIGHTED_ENGINE`` knob, default
    ``auto``) maps to required capability tokens: ``auto``/``bitbell``
    require just ``weighted``; ``stencil`` adds ``windowed``; ``mesh2d``
    adds ``mesh2d``.  Returns ``(label, engine)``.

    Raises :class:`InputError` on a weightless graph or unknown flavor,
    and lets :func:`negotiate_engine`'s ValueError (naming each
    candidate's missing tokens) propagate on an unsatisfiable ask.
    """
    if not getattr(graph, "has_weights", False):
        raise InputError(
            "weighted query against a weightless graph: the artifact has "
            "no edge-cost section (regenerate with gen_cli --weights, or "
            "convert with load_dimacs_gr(keep_weights=True))"
        )
    if flavor is None:
        flavor = knobs.raw("MSBFS_WEIGHTED_ENGINE", "auto") or "auto"
    flavor = flavor.strip().lower() or "auto"
    if flavor not in _FLAVOR_TOKENS:
        raise InputError(
            f"unknown weighted engine flavor {flavor!r} "
            f"(MSBFS_WEIGHTED_ENGINE: auto, bitbell, stencil, mesh2d)"
        )
    required = frozenset({"weighted"}) | _FLAVOR_TOKENS[flavor]
    return negotiate_engine(required, weighted_candidates(graph, delta))
