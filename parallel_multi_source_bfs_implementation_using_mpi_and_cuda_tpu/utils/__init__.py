"""Host-side utilities: binary I/O, timing spans, the rank-0 report."""

from .io import (
    load_graph_bin,
    load_query_bin,
    save_graph_bin,
    save_query_bin,
    pad_queries,
)
from .report import format_report
from .timing import Span

__all__ = [
    "load_graph_bin",
    "load_query_bin",
    "save_graph_bin",
    "save_query_bin",
    "pad_queries",
    "format_report",
    "Span",
]
