"""Distributed query execution: shard_map over the ('q', 'v') mesh.

End-to-end replacement for the reference's MPI phase structure:

* graph broadcast (main.cu:242-255)  -> replicated NamedSharding device_put;
* round-robin assignment (303-307)   -> cyclic grid sharded over 'q';
* per-rank BFS loop (312-322)        -> vmap-batched BFS per shard;
* Gather/Gatherv of (q, F) pairs with a custom MPI struct (324-368)
                                     -> fixed-shape (K,) int64 pmax merge
                                        (each shard contributes its slots,
                                        -1 elsewhere; SPMD static shapes
                                        replace the ragged wire format);
* rank-0 argmin (379-397)            -> on-device masked argmin, replicated.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.csr import CSRGraph, DeviceCSR
from ..ops.bfs import graph_expand, multi_source_bfs
from ..ops.engine import QueryEngineBase
from ..ops.objective import f_of_u
from .mesh import QUERY_AXIS, VERTEX_AXIS
from .scheduler import merge_local_f, shard_queries


@partial(
    jax.jit,
    static_argnames=("mesh", "k", "k_pad", "w", "max_levels", "sparse_budget"),
)
def _distributed_bitbell_run(
    mesh: Mesh,
    graph,  # BellGraph, replicated on every device
    query_grid: jax.Array,  # (W, J, S) cyclic layout
    k: int,
    k_pad: int,
    w: int,
    max_levels,
    sparse_budget: int = 0,
):
    """Merged per-query (f, levels, reached), each (k_pad,), via the
    bit-packed BELL engine per shard (padding slots stay -1, like the
    reference's never-computed all_F_values entries, main.cu:325)."""
    from ..ops.bitbell import WORD_BITS, bitbell_run

    def shard_body(graph, qblock):
        qblock = qblock[0]  # local leading extent 1 on 'q'
        j, s = qblock.shape
        pad = (-j) % WORD_BITS
        if pad:
            qblock = jnp.concatenate(
                [qblock, jnp.full((pad, s), -1, dtype=qblock.dtype)], axis=0
            )
        f, levels, reached = bitbell_run(graph, qblock, max_levels, sparse_budget)
        axes = (QUERY_AXIS, VERTEX_AXIS)
        return (
            merge_local_f(f[:j], j, w, k, k_pad, axes),
            merge_local_f(levels[:j].astype(jnp.int64), j, w, k, k_pad, axes),
            merge_local_f(reached[:j].astype(jnp.int64), j, w, k, k_pad, axes),
        )

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(QUERY_AXIS)),
        out_specs=(P(), P(), P()),
    )(graph, query_grid)


@partial(
    jax.jit,
    static_argnames=("mesh", "k", "k_pad", "w", "query_chunk", "max_levels", "expand"),
)
def _distributed_f_values(
    mesh: Mesh,
    graph: DeviceCSR,
    query_grid: jax.Array,  # (W, J, S) cyclic layout
    k: int,
    k_pad: int,
    w: int,
    query_chunk: int,
    max_levels,
    expand,
) -> jax.Array:
    """Returns the merged (k_pad,) int64 F array, replicated on every device."""

    def shard_body(graph, qblock):
        # qblock arrives as (1, J, S): the mesh-sharded leading axis keeps
        # rank with local extent W/W = 1.  Drop it -> this shard's J queries
        # in cyclic order.
        qblock = qblock[0]
        j = qblock.shape[0]

        def one(q):
            dist = multi_source_bfs(graph, q, max_levels=max_levels, expand=expand)
            return f_of_u(dist)

        chunked = qblock.reshape(j // query_chunk, query_chunk, qblock.shape[1])
        f_local = lax.map(jax.vmap(one), chunked).reshape(j)
        return merge_local_f(f_local, j, w, k, k_pad, (QUERY_AXIS, VERTEX_AXIS))

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(QUERY_AXIS)),
        out_specs=P(),
    )(graph, query_grid)


class DistributedEngine(QueryEngineBase):
    """Query-sharded execution over a mesh, graph replicated per device
    (the reference's full-graph-per-rank model, SURVEY.md C8).

    ``backend`` picks the per-shard engine: ``"bitbell"`` (default) runs the
    bit-packed BELL reduction forest — the fastest single-chip engine — on
    each shard's query slice; ``"csr"`` runs the per-query vmap CSR pull
    (accepts a custom ``expand`` hook, e.g. the dense-MXU frontier)."""

    def __init__(
        self,
        mesh: Mesh,
        graph: CSRGraph | DeviceCSR,
        max_levels: Optional[int] = None,
        query_chunk: Optional[int] = None,
        expand=graph_expand,
        backend: str = "bitbell",
    ):
        self.mesh = mesh
        self.w = mesh.shape[QUERY_AXIS]
        replicated = NamedSharding(mesh, P())
        if backend == "bitbell":
            if expand is not graph_expand or query_chunk is not None:
                # These knobs only exist on the per-query CSR path; accepting
                # them here would silently not apply them.
                raise ValueError(
                    "expand/query_chunk require backend='csr' "
                    "(the bitbell path has no per-query expansion hook)"
                )
            if isinstance(graph, DeviceCSR):
                raise ValueError(
                    "backend='bitbell' builds its own layout; pass the host "
                    "CSRGraph"
                )
            from ..models.bell import BellGraph
            from ..ops.bitbell import default_sparse_budget

            bell = BellGraph.from_host(graph)
            self.bell = jax.device_put(bell, replicated)
            # Per-shard hybrid pull/push (same speedup as the single-chip
            # engine — the sparse scatter is shard-local, no collectives).
            self.sparse_budget = (
                default_sparse_budget(bell.sparse[2].shape[0])
                if bell.sparse is not None
                else 0
            )
            self.graph = None  # keep the attribute set backend-uniform
        elif backend == "csr":
            self.bell = None
            if isinstance(graph, CSRGraph):
                graph = DeviceCSR.from_host(graph, sharding=replicated)
            self.graph = graph
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.max_levels = max_levels
        self.query_chunk = query_chunk
        self.expand = expand

    def f_values(self, queries: np.ndarray) -> jax.Array:
        """(K, S) -1-padded queries -> (K,) int64 F values (replicated)."""
        sharded, k, k_pad, chunk = shard_queries(
            self.mesh, np.asarray(queries), self.query_chunk
        )
        if self.backend == "bitbell":
            merged, _, _ = _distributed_bitbell_run(
                self.mesh,
                self.bell,
                sharded,
                k,
                k_pad,
                self.w,
                self.max_levels,
                self.sparse_budget,
            )
        else:
            merged = _distributed_f_values(
                self.mesh,
                self.graph,
                sharded,
                k,
                k_pad,
                self.w,
                chunk,
                self.max_levels,
                self.expand,
            )
        return merged[:k]

    def query_stats(self, queries):
        """Per-query (levels, reached, F) — multi-chip stats (bitbell
        backend; the per-shard counters merge exactly like F values)."""
        if self.backend != "bitbell":
            return None
        sharded, k, k_pad, _ = shard_queries(
            self.mesh, np.asarray(queries), self.query_chunk
        )
        f, levels, reached = _distributed_bitbell_run(
            self.mesh,
            self.bell,
            sharded,
            k,
            k_pad,
            self.w,
            self.max_levels,
            self.sparse_budget,
        )
        return (
            np.asarray(levels[:k]).astype(np.int32),
            np.asarray(reached[:k]).astype(np.int32),
            np.asarray(f[:k]),
        )
