"""Regression tests for review findings: K=0, corrupt inputs, alias shim."""

import struct

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    Engine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import main
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.objective import (
    select_best,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    load_graph_bin,
    load_query_bin,
    save_graph_bin,
    save_query_bin,
)

import jax.numpy as jnp


def test_select_best_empty():
    min_f, min_k = select_best(jnp.zeros((0,), jnp.int64), jnp.zeros((0,), bool))
    assert (int(min_f), int(min_k)) == (-1, -1)


def test_engine_zero_queries():
    n, edges = generators.gnm_edges(30, 60, seed=81)
    eng = Engine(CSRGraph.from_edges(n, edges).to_device())
    f = eng.f_values(jnp.zeros((0, 1), jnp.int32))
    assert f.shape == (0,)
    assert eng.best(np.zeros((0, 1), np.int32)) == (-1, -1)


def test_cli_k_zero(tmp_path, capsys):
    # Reference with K=0: scans never run, prints minK+1 = 0, minF = -1
    # (main.cu:379-414).
    n, edges = generators.gnm_edges(30, 60, seed=82)
    g, q = str(tmp_path / "g.bin"), str(tmp_path / "q.bin")
    save_graph_bin(g, n, edges)
    save_query_bin(q, [])
    rc = main(["main.py", "-g", g, "-q", q, "-gn", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Query number (k) with minimum F value: 0\n" in out
    assert "Minimum F value: -1\n" in out


def test_truncated_query_group_raises_ioerror(tmp_path):
    path = tmp_path / "q.bin"
    with open(path, "wb") as f:
        f.write(bytes([1, 5]))  # K=1, group of 5 ids, but no payload
        f.write(struct.pack("<i", 3))  # only 1 of 5
    with pytest.raises(IOError):
        load_query_bin(path)


def test_corrupt_graph_vertex_ids(tmp_path, capsys):
    path = tmp_path / "g.bin"
    save_graph_bin(path, 3, np.array([[0, 9]], dtype=np.int32))
    with pytest.raises(ValueError):
        load_graph_bin(path, native=False)
    # CLI converts it to the reference-style error + exit 1.
    qpath = tmp_path / "q.bin"
    save_query_bin(qpath, [[0]])
    rc = main(["main.py", "-g", str(path), "-q", str(qpath), "-gn", "1"])
    assert rc == 1
    assert "Could not open graph file" in capsys.readouterr().err


def test_alias_shim_shares_module_objects():
    import msbfs_tpu  # noqa: F401
    from msbfs_tpu.parallel.distributed import DistributedEngine as A
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.distributed import (
        DistributedEngine as B,
    )

    assert A is B
    import msbfs_tpu.ops.bfs as short_bfs
    import parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bfs as long_bfs

    assert short_bfs is long_bfs
