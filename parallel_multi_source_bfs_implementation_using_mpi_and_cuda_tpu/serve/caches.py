"""Serving-side caches: LRU results + executable/compile bookkeeping.

Two caches front the engines (docs/SERVING.md):

* :class:`LRUCache` / the server's result cache — exact-request
  memoization keyed by (graph key, graph version, canonical query
  bytes).  The graph version rides in the key, so a reload invalidates
  every stale entry by construction (they age out of the LRU rather
  than needing a scan); :meth:`LRUCache.drop_where` additionally frees
  them eagerly on reload.
* :class:`ExecutableCache` — bookkeeping over XLA's own jit cache.  XLA
  already reuses a compiled executable when the (engine, shape) pair
  matches; this class records WHICH (graph, version, bucket) triples
  have been warmed and counts the cold warms, which is exactly what the
  ``stats`` verb reports and the serve tests assert (compile count flat
  across same-bucket requests, +1 for a cold bucket).

A third cache serves the dynamic-graph subsystem (docs/SERVING.md
"Mutations & versions"):

* :class:`PlaneCache` — byte-capped LRU of certified per-query distance
  planes, keyed by (graph name, canonical query bytes) WITHOUT the
  version.  That omission is the point: unlike result-cache entries,
  which a ``mutate`` must make unreachable (stale answers are not
  answers), a stale plane is still a valid repair SEED — the entry
  records which ``(digest, version)`` it was certified against, and the
  repair path composes the delta span from there to the live version.
  Planes survive mutations by design; they age out by bytes.

All are thread-safe: connection handler threads probe the caches while
the batcher thread fills them.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple


class LRUCache:
    """Bounded LRU with hit/miss/eviction counters.

    ``capacity <= 0`` disables caching (every ``get`` misses, ``put`` is
    a no-op) — the documented ``MSBFS_SERVE_RESULT_CACHE=0`` opt-out.
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._data: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        """Value or None (None is never a stored value here: entries are
        response dicts)."""
        with self._lock:
            if self.capacity <= 0 or key not in self._data:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return self._data[key]

    def put(self, key, value) -> None:
        with self._lock:
            if self.capacity <= 0:
                return
            if key in self._data:
                self._data.move_to_end(key)
            self._data[key] = value
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    def drop_where(self, predicate: Callable[[object], bool]) -> int:
        """Eagerly free entries whose key matches (reload invalidation);
        returns the count dropped."""
        with self._lock:
            stale = [k for k in self._data if predicate(k)]
            for k in stale:
                del self._data[k]
            return len(stale)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "size": len(self._data),
                "capacity": self.capacity,
            }


class PlaneCache:
    """Byte-capped LRU of repair-seed distance planes.

    Entries are ``(version, digest, dist)`` with ``dist`` a host (K, n)
    int32 plane certified at that version.  ``max_bytes <= 0`` disables
    (the ``MSBFS_SERVE_PLANE_CACHE_BYTES=0`` opt-out — the repair path
    then always falls back to full recompute).  Keys deliberately
    exclude the version: a mutate must NOT drop these (see module
    docstring); ``put`` overwrites the entry for a key with the newest
    plane, so each query's seed converges back toward version-fresh.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._data: OrderedDict = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key) -> Optional[Tuple[int, str, object]]:
        """(version, digest, dist) or None."""
        with self._lock:
            if self.max_bytes <= 0 or key not in self._data:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return self._data[key]

    def put(self, key, version: int, digest: str, dist) -> None:
        nbytes = int(dist.nbytes)
        with self._lock:
            if self.max_bytes <= 0 or nbytes > self.max_bytes:
                return  # a plane bigger than the cap would evict everything
            if key in self._data:
                self._bytes -= int(self._data[key][2].nbytes)
                self._data.move_to_end(key)
            self._data[key] = (int(version), str(digest), dist)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._data:
                _, (_, _, old) = self._data.popitem(last=False)
                self._bytes -= int(old.nbytes)
                self.evictions += 1

    def drop_where(self, predicate: Callable[[object], bool]) -> int:
        """Eager invalidation for the cases where a seed really IS dead:
        a reload (new file content, no delta chain to compose) or a
        graph eviction."""
        with self._lock:
            stale = [k for k in self._data if predicate(k)]
            for k in stale:
                self._bytes -= int(self._data[k][2].nbytes)
                del self._data[k]
            return len(stale)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "size": len(self._data),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
            }


class ExecutableCache:
    """Warmed-bucket registry + compile counters for the stats verb.

    A key is ``(graph_key, version, k_exec, s_pad)``.  :meth:`warm` runs
    ``warm_fn`` exactly once per cold key (under the lock of that key's
    first caller; the batcher is single-threaded so contention cannot
    actually occur — the lock is correctness insurance, not a hot path)
    and counts it as one compile against the bucket label.
    """

    def __init__(self):
        self._warmed: set = set()
        self._compiles: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    def warm(self, key, bucket_label: str, warm_fn: Callable[[], None]) -> bool:
        """Ensure ``key`` is warmed; returns True when THIS call compiled
        (cold bucket), False on a warm hit."""
        with self._lock:
            if key in self._warmed:
                return False
        warm_fn()  # outside the lock: compiles take seconds on TPU
        with self._lock:
            if key in self._warmed:
                return False  # lost a (theoretical) race; count once
            self._warmed.add(key)
            self._compiles[bucket_label] = self._compiles.get(bucket_label, 0) + 1
        return True

    def compiles(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._compiles)

    def warmed_count(self) -> int:
        """Currently-warm bucket count (the health verb's
        ``warm_buckets``: live set size, unlike the compile odometer)."""
        with self._lock:
            return len(self._warmed)

    def total_compiles(self) -> int:
        with self._lock:
            return sum(self._compiles.values())

    def drop_where(self, predicate: Callable[[object], bool]) -> int:
        """Forget warmed keys matching ``predicate`` (graph reload: the
        rebuilt engine has fresh, unwarmed programs).  Compile counters
        are cumulative and survive — they are a lifetime odometer, not a
        live-set size."""
        with self._lock:
            stale = [k for k in self._warmed if predicate(k)]
            for k in stale:
                self._warmed.discard(k)
            return len(stale)
