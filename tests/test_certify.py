"""Output certification (docs/RESILIENCE.md "Silent data corruption").

Four layers of the distrust-the-hardware defense, bottom up:

* the distance certificate itself (ops/certify.py): the four invariants
  — source-zero, zero-is-source, edge-relaxation, witness — plus the
  f-mismatch comparison, each unit-tested in isolation, and the
  100%-detection property: a BFS distance field is UNIQUE, so flipping
  ANY single bit of a certified field must flunk some invariant;
* the fault seams (utils/faults.py): ``bitflip:plane<i>`` at the
  plane-commit boundary of the host chunk loop, ``bitflip:dist`` at
  result materialize, the thread-local wire taint behind
  ``wire_corrupt`` — each flips exactly one deterministic bit;
* the supervisor escalation ladder (runtime/supervisor.py): audit
  failure -> retry same engine -> alternate engine -> typed
  CorruptionError (exit code 9), never an uncertified answer once an
  attempt flunked;
* the serving daemon: MSBFS_AUDIT wiring into per-request ``audited``
  and the stats verb, crc32 frame integrity on the wire, and journal
  replay refusing a graph whose bytes changed under the journal.
"""

import json
import socket
import struct
import zlib

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops import (
    certify,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (
    ChunkSupervisor,
    CorruptionError,
    TransientError,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve import (
    protocol,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils import (
    faults,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    save_graph_bin,
    save_query_bin,
)


# ---------------------------------------------------------------------------
# fold_digest
# ---------------------------------------------------------------------------


def test_fold_digest_is_deterministic_and_position_sensitive():
    a = np.arange(64, dtype=np.int64)
    assert certify.fold_digest(a) == certify.fold_digest(a.copy())
    # Same multiset of words in a different order must change the
    # digest: a plain xor-fold would be blind to transpositions, which
    # is exactly what a swapped DMA looks like.
    b = a.copy()
    b[3], b[11] = b[11], b[3]
    assert certify.fold_digest(a) != certify.fold_digest(b)
    # Ordinal sensitivity across arrays: (x, y) vs (y, x).
    x, y = np.arange(8), np.arange(8, 16)
    assert certify.fold_digest(x, y) != certify.fold_digest(y, x)
    # Any single-bit flip moves the digest.
    c = a.copy()
    c[20] ^= 1 << 17
    assert certify.fold_digest(a) != certify.fold_digest(c)
    assert certify.fold_digest(np.zeros(0, dtype=np.int64)) >= 0


# ---------------------------------------------------------------------------
# the certificate: reference sweep + invariants
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cert_workload():
    from oracle import oracle_bfs

    n, edges = generators.gnm_edges(180, 540, seed=911)
    g = CSRGraph.from_edges(n, edges)
    # K=70 crosses the 64-query uint64 word boundary of the audit
    # sweep's bit-plane packing; arms include an empty group and an
    # all-out-of-range group (both must certify as all -1).
    queries = generators.random_queries(n, 70, max_group=4, seed=912)
    queries[5] = np.zeros(0, dtype=np.int32)
    queries[9] = np.array([-3, n + 7], dtype=np.int32)
    padded = pad_queries(queries)
    dist_ref = np.asarray(
        [oracle_bfs(n, edges, q) for q in queries], dtype=np.int32
    )
    return g, padded, dist_ref


def test_reference_distances_match_oracle(cert_workload):
    g, padded, dist_ref = cert_workload
    dist = certify.reference_distances(g.row_offsets, g.col_indices, padded)
    np.testing.assert_array_equal(dist, dist_ref)
    assert (
        certify.certify_distances(g.row_offsets, g.col_indices, padded, dist)
        == []
    )


def test_reference_distances_edgeless_graph():
    g = CSRGraph.from_edges(5, np.zeros((0, 2), dtype=np.int64))
    padded = pad_queries([np.array([2], dtype=np.int32)])
    dist = certify.reference_distances(g.row_offsets, g.col_indices, padded)
    want = np.full((1, 5), -1, dtype=np.int32)
    want[0, 2] = 0
    np.testing.assert_array_equal(dist, want)
    assert (
        certify.certify_distances(g.row_offsets, g.col_indices, padded, dist)
        == []
    )


def test_reference_distances_trailing_isolated_vertex():
    """Regression: the reduceat segment starts used to be clamped to
    E - 1, so a trailing isolated vertex (whose CSR row starts at E)
    stole the final edge slot from the last non-empty row — here vertex
    3's [1, 2] adjacency lost its slot for 2, the sweep never reached 3
    from source 2, and the TRUE field flunked its own witness check.
    The pad-row reduction must keep both of vertex 3's slots."""
    edges = np.array([[0, 1], [1, 3], [2, 3]], dtype=np.int64)
    g = CSRGraph.from_edges(5, edges)  # chain 0-1-3-2, vertex 4 isolated
    padded = pad_queries([np.array([2], dtype=np.int32)])
    dist = certify.reference_distances(g.row_offsets, g.col_indices, padded)
    np.testing.assert_array_equal(
        dist, np.array([[3, 2, 0, 1, -1]], dtype=np.int32)
    )
    # Both reduceat sites: the recompute sweep above, the witness check
    # here — the true field must certify clean end to end.
    assert (
        certify.certify_distances(g.row_offsets, g.col_indices, padded, dist)
        == []
    )
    assert (
        certify.audit_f_values(
            g.row_offsets, g.col_indices, padded, np.array([6])
        )
        == []
    )


def _path4():
    """0-1-2-3 path; query from vertex 0: dist = [0, 1, 2, 3]."""
    edges = np.array([[0, 1], [1, 2], [2, 3]], dtype=np.int64)
    g = CSRGraph.from_edges(4, edges)
    padded = pad_queries([np.array([0], dtype=np.int32)])
    dist = np.array([[0, 1, 2, 3]], dtype=np.int32)
    return g, padded, dist


@pytest.mark.parametrize(
    "mutate,expect",
    [
        (lambda d: d.__setitem__((0, 0), 1), "source-zero"),
        (lambda d: d.__setitem__((0, 2), 0), "zero-is-source"),
        (lambda d: d.__setitem__((0, 3), 9), "edge-relaxation"),
        (lambda d: d.__setitem__((0, 3), -1), "edge-relaxation"),
    ],
    ids=["source-zero", "zero-is-source", "jump", "unreached-neighbor"],
)
def test_certify_distances_flags_each_invariant(mutate, expect):
    g, padded, dist = _path4()
    assert (
        certify.certify_distances(g.row_offsets, g.col_indices, padded, dist)
        == []
    )
    bad = dist.copy()
    mutate(bad)
    assert expect in certify.certify_distances(
        g.row_offsets, g.col_indices, padded, bad
    )


def test_certify_distances_witness_needs_a_parent():
    # Two components: {0,1} holds the source, {2,3} is unreachable.
    # Claiming dist 1/2 on the far component is edge-consistent on the
    # (2,3) edge in BOTH directions — only the witness invariant (every
    # dist>=1 vertex has a neighbor at dist-1) can reject it.
    edges = np.array([[0, 1], [2, 3]], dtype=np.int64)
    g = CSRGraph.from_edges(4, edges)
    padded = pad_queries([np.array([0], dtype=np.int32)])
    good = np.array([[0, 1, -1, -1]], dtype=np.int32)
    assert (
        certify.certify_distances(g.row_offsets, g.col_indices, padded, good)
        == []
    )
    bad = np.array([[0, 1, 1, 2]], dtype=np.int32)
    assert "witness" in certify.certify_distances(
        g.row_offsets, g.col_indices, padded, bad
    )


def test_certificate_detects_every_single_bit_flip(cert_workload):
    """The 100%-detection property.  The BFS distance field for a given
    graph + source set is unique, so ANY bit flip that changes the
    field must flunk some invariant.  Sweep a deterministic sample of
    bit positions across the whole buffer — every flip detected."""
    g, padded, dist_ref = cert_workload
    flat = dist_ref.view(np.uint8).reshape(-1)
    total_bits = flat.size * 8
    # ~200 positions, deterministically spread over the buffer.
    for bit in range(0, total_bits, max(1, total_bits // 200)):
        bad = dist_ref.copy()
        bflat = bad.view(np.uint8).reshape(-1)
        bflat[bit // 8] ^= np.uint8(1 << (bit % 8))
        failing = certify.certify_distances(
            g.row_offsets, g.col_indices, padded, bad
        )
        assert failing, f"bit {bit}: corrupt field certified clean"


def test_audit_f_values_clean_and_tampered(cert_workload):
    g, padded, dist_ref = cert_workload
    f = certify.f_from_distances(dist_ref)
    assert (
        certify.audit_f_values(g.row_offsets, g.col_indices, padded, f) == []
    )
    # Every single-bit flip of the F buffer itself is caught: the audit
    # recomputes F from scratch, so any altered word mismatches.
    flat_bits = f.size * 64
    for bit in range(0, flat_bits, max(1, flat_bits // 64)):
        bad = f.copy()
        bflat = bad.view(np.uint8).reshape(-1)
        bflat[bit // 8] ^= np.uint8(1 << (bit % 8))
        assert "f-mismatch" in certify.audit_f_values(
            g.row_offsets, g.col_indices, padded, bad
        )


# ---------------------------------------------------------------------------
# fault seams through a real engine + supervisor
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def seam_workload():
    # 16x16 road lattice: n = 256 exactly fills the Bell block (no
    # padding rows for a flip to land in), K = 32 exactly fills the
    # uint32 plane word (no padding lanes), and the ~30-level diameter
    # gives the level_chunk=1 drive loop a long run of plane<i> seams.
    n, edges = generators.road_edges(16, 16, seed=901)
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 32, max_group=3, seed=902)
    padded = pad_queries(queries)

    def make():
        return BitBellEngine(BellGraph.from_host(g), level_chunk=1)

    clean = np.asarray(make().f_values(padded))
    return g, padded, make, clean


# Sites whose deterministic crc32-keyed flip lands on a bit that alters
# the answer on THIS fixture (pinned seeds, pinned site names — stable
# forever).  The other plane sites flip a settled visited bit the
# frontier has already passed: the answer is unchanged, so there is
# nothing for an end-to-end output audit to detect (a benign upset).
_ANSWER_CORRUPTING = {"plane1", "dist"}


@pytest.mark.parametrize(
    "site", ["plane0", "plane1", "plane2", "plane3", "dist"]
)
def test_single_bitflip_at_each_seam_never_escapes(site, seam_workload):
    g, padded, make, clean = seam_workload
    # Arm the same plan WITHOUT an auditor: this is what the flip does
    # to an unprotected run, and pins which sites corrupt the answer.
    with faults.injected(faults.FaultPlan.parse(f"bitflip:{site}:1")):
        unprotected = np.asarray(
            ChunkSupervisor(make(), auditor=None).f_values(padded)
        )
    corrupts = not np.array_equal(unprotected, clean)
    assert corrupts == (site in _ANSWER_CORRUPTING)
    # The audited run: the answer served is ALWAYS the clean one — the
    # flip either never touched the output, or the audit caught it and
    # the retry (fault fired, second run clean) recovered.
    with faults.injected(faults.FaultPlan.parse(f"bitflip:{site}:1")):
        sup = ChunkSupervisor(
            make(), auditor=certify.make_auditor(g), audit_sample=1.0
        )
        audited = np.asarray(sup.f_values(padded))
    np.testing.assert_array_equal(audited, clean)
    if corrupts:
        assert sup.audit_failures_total == 1
        assert sup.audited_total == 2  # failed attempt + clean retry
        assert [e["action"] for e in sup.events] == ["audit_fail"]
    else:
        assert sup.audit_failures_total == 0


def test_plane_trail_digests_are_deterministic_and_flip_sensitive(
    seam_workload,
):
    g, padded, make, clean = seam_workload
    certify.start_plane_trail()
    make().f_values(padded)
    first = certify.stop_plane_trail()
    assert first, "chunked drive loop journaled no plane digests"
    certify.start_plane_trail()
    make().f_values(padded)
    assert certify.stop_plane_trail() == first
    certify.start_plane_trail()
    with faults.injected(faults.FaultPlan.parse("bitflip:plane1:1")):
        make().f_values(padded)
    flipped = certify.stop_plane_trail()
    assert flipped != first  # the corrupted commit shows in the trail


class _LyingEngine:
    """Adds 1 to every F value — a persistent corruption no retry on
    the same engine can clear."""

    def __init__(self, inner):
        self._inner = inner

    def f_values(self, queries):
        return np.asarray(self._inner.f_values(queries)) + 1

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_supervisor_escalates_persistent_corruption(seam_workload):
    g, padded, make, clean = seam_workload
    auditor = certify.make_auditor(g)
    sup = ChunkSupervisor(
        _LyingEngine(make()), auditor=auditor, audit_sample=1.0
    )
    with pytest.raises(CorruptionError) as err:
        sup.f_values(padded)
    assert err.value.exit_code == 9
    assert "f-mismatch" in err.value.invariants
    assert sup.audit_failures_total >= 2  # first attempt + forced retry


def test_supervisor_audit_ladder_swaps_in_a_clean_engine(seam_workload):
    g, padded, make, clean = seam_workload
    lying = _LyingEngine(make())
    sup = ChunkSupervisor(
        lying,
        ladder=[("bitbell-clean", make)],
        auditor=certify.make_auditor(g),
        audit_sample=1.0,
    )
    out = np.asarray(sup.f_values(padded))
    np.testing.assert_array_equal(out, clean)
    assert "audit_degrade" in [e["action"] for e in sup.events]
    # The audit stepdown is per-call, not a permanent downgrade: the
    # original engine is restored once the clean recompute settles, and
    # the rung it borrowed is NOT consumed from the capacity-degrade
    # ladder (a transient double-upset must leave both intact).
    assert sup.engine is lying
    assert len(sup.ladder) == 1
    # ... so a later call escalates (and recovers) all over again.
    out2 = np.asarray(sup.f_values(padded))
    np.testing.assert_array_equal(out2, clean)
    assert len(sup.ladder) == 1


def test_audit_sampling_accumulator():
    sup = ChunkSupervisor(object(), auditor=lambda q, f: [], audit_sample=0.25)
    due = [sup._audit_due() for _ in range(8)]
    assert due == [False, False, False, True] * 2


# ---------------------------------------------------------------------------
# the wire seam: crc32 framing
# ---------------------------------------------------------------------------


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frames_carry_crc_and_roundtrip():
    a, b = _pair()
    try:
        protocol.send_frame(a, {"op": "ping", "x": [1, 2, 3]})
        assert protocol.recv_frame(b) == {"op": "ping", "x": [1, 2, 3]}
    finally:
        a.close()
        b.close()


def test_legacy_unflagged_frame_still_accepted():
    a, b = _pair()
    try:
        body = json.dumps({"op": "old"}).encode()
        a.sendall(struct.pack("!I", len(body)) + body)  # no crc flag
        assert protocol.recv_frame(b) == {"op": "old"}
    finally:
        a.close()
        b.close()


def test_legacy_send_mode_emits_parseable_prefix(monkeypatch):
    """MSBFS_WIRE_CRC=legacy (phase 1 of a rolling upgrade) must emit
    frames a pre-crc peer can parse: plain length prefix, high bit
    clear, no crc word — while flagged frames are still verified on
    receive (the knob gates sends only)."""
    monkeypatch.setenv("MSBFS_WIRE_CRC", "legacy")
    frame = protocol.encode_frame({"op": "ping"})
    (prefix,) = struct.unpack("!I", frame[:4])
    assert not (prefix & 0x80000000)  # old peers read this as a length
    assert prefix == len(frame) - 4  # and the body follows directly
    a, b = _pair()
    try:
        protocol.send_frame(a, {"op": "ping"})
        assert protocol.recv_frame(b) == {"op": "ping"}
        # Receive-side verification is NOT gated by the knob.
        flagged = protocol.encode_frame({"op": "ping"}, crc=True)
        bad = bytearray(flagged)
        bad[-1] ^= 0x04
        a.sendall(bytes(bad))
        with pytest.raises(protocol.FrameCorruptError):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_any_single_bit_flip_on_the_wire_is_detected():
    payload = {"op": "query", "queries": [[1, 2], [3, 4]], "graph": "g"}
    frame = protocol.encode_frame(payload)
    body = frame[8:]  # 4-byte length|flag prefix + 4-byte crc32
    for bit in range(0, len(body) * 8, max(1, len(body) * 8 // 96)):
        a, b = _pair()
        try:
            bad = bytearray(frame)
            bad[8 + bit // 8] ^= 1 << (bit % 8)
            a.sendall(bytes(bad))
            with pytest.raises(protocol.FrameCorruptError):
                protocol.recv_frame(b)
        finally:
            a.close()
            b.close()


def test_wire_taint_corrupts_exactly_one_frame():
    a, b = _pair()
    try:
        faults.arm_wire_corruption()
        protocol.send_frame(a, {"op": "ping"})
        with pytest.raises(protocol.FrameCorruptError):
            protocol.recv_frame(b)
        # Taint consumed: the next frame is clean.
        protocol.send_frame(a, {"op": "ping"})
        assert protocol.recv_frame(b) == {"op": "ping"}
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the serving daemon: MSBFS_AUDIT, stats, journal digest refusal
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_graph(tmp_path_factory):
    d = tmp_path_factory.mktemp("certify_graphs")
    n, edges = generators.gnm_edges(120, 360, seed=921)
    path = str(d / "g.bin")
    save_graph_bin(path, n, edges)
    return n, path


def _start_server(tmp_path, graph_path, **kwargs):
    import os

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.server import (
        MsbfsServer,
    )

    sock = str(tmp_path / f"s{len(os.listdir(tmp_path))}.sock")
    srv = MsbfsServer(
        listen=f"unix:{sock}",
        graphs={"default": graph_path} if graph_path else {},
        window_s=0.0,
        request_timeout_s=60.0,
        **kwargs,
    )
    srv.start()
    return srv, f"unix:{sock}"


def test_server_full_audit_marks_responses_and_stats(
    served_graph, tmp_path, monkeypatch
):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
        MsbfsClient,
    )

    monkeypatch.setenv("MSBFS_AUDIT", "full")
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    _, path = served_graph
    srv, addr = _start_server(tmp_path, path)
    try:
        with MsbfsClient(addr) as c:
            out = c.query([[1, 2], [3, 4]])
            assert out["audited"] is True
            stats = c.stats()
            assert stats["audited"] >= 1
            assert stats["audit_failures"] == 0
            assert stats["refused_graphs"] == {}
    finally:
        faults.activate(None)
        srv.stop()


def test_server_audit_off_leaves_requests_unaudited(
    served_graph, tmp_path, monkeypatch
):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
        MsbfsClient,
    )

    monkeypatch.setenv("MSBFS_AUDIT", "off")
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    _, path = served_graph
    srv, addr = _start_server(tmp_path, path)
    try:
        with MsbfsClient(addr) as c:
            out = c.query([[1, 2], [3, 4]])
            assert out["audited"] is False
            assert c.stats()["audited"] == 0
    finally:
        faults.activate(None)
        srv.stop()


def test_journal_replay_refuses_swapped_graph_bytes(
    served_graph, tmp_path, monkeypatch
):
    """The file changed underneath the journal: replay must refuse the
    registration typed and report it — never silently serve different
    content than the journal promised."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
        MsbfsClient,
        ServerError,
    )

    monkeypatch.delenv("MSBFS_AUDIT", raising=False)
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    n, edges = generators.gnm_edges(80, 200, seed=922)
    path = str(tmp_path / "swap.bin")
    save_graph_bin(path, n, edges)
    journal = str(tmp_path / "state.journal")
    srv_a, addr_a = _start_server(tmp_path, path, journal_path=journal)
    try:
        with MsbfsClient(addr_a) as c:
            c.query([[1, 2]], graph="default")
    finally:
        srv_a.stop()
    # Same path, silently different bytes — the corruption under test.
    n2, edges2 = generators.gnm_edges(80, 200, seed=923)
    save_graph_bin(path, n2, edges2)
    srv_b, addr_b = _start_server(tmp_path, None, journal_path=journal)
    try:
        assert srv_b._ready.wait(120), "journal replay never finished"
        with MsbfsClient(addr_b) as c:
            refused = c.stats()["refused_graphs"]
            assert "default" in refused
            assert "refusing" in refused["default"]
            with pytest.raises(ServerError) as err:
                c.query([[1, 2]], graph="default")
            assert err.value.exit_code == 1  # unregistered -> InputError
    finally:
        srv_b.stop()


# ---------------------------------------------------------------------------
# the verify CLI verb
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def verify_files(tmp_path_factory):
    from oracle import oracle_bfs, oracle_f

    d = tmp_path_factory.mktemp("certify_verify")
    n, edges = generators.gnm_edges(90, 260, seed=931)
    gpath = str(d / "g.bin")
    save_graph_bin(gpath, n, edges)
    queries = generators.random_queries(n, 6, max_group=3, seed=932)
    qpath = str(d / "q.bin")
    save_query_bin(qpath, [list(map(int, q)) for q in queries])
    f_true = [int(oracle_f(oracle_bfs(n, edges, q))) for q in queries]
    return gpath, qpath, f_true


def test_verify_certifies_engine_output(verify_files, capsys, monkeypatch):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        cli,
    )

    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    gpath, qpath, _ = verify_files
    rc = cli.main(["msbfs", "verify", "-g", gpath, "-q", qpath])
    faults.activate(None)
    assert rc == 0
    assert "CERTIFIED" in capsys.readouterr().out


def test_verify_certifies_stored_f_and_rejects_corrupt(
    verify_files, capsys, monkeypatch
):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        cli,
    )

    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    gpath, qpath, f_true = verify_files
    rc = cli.main(
        ["msbfs", "verify", "-g", gpath, "-q", qpath,
         "--expect-f", json.dumps(f_true)]
    )
    assert rc == 0
    bad = list(f_true)
    bad[0] ^= 1 << 7  # one flipped bit in the stored answer
    rc = cli.main(
        ["msbfs", "verify", "-g", gpath, "-q", qpath,
         "--expect-f", json.dumps(bad)]
    )
    faults.activate(None)
    assert rc == 9
    err = capsys.readouterr().err
    assert "f-mismatch" in err
