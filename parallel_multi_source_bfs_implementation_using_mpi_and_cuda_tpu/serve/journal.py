"""Append-only daemon state journal (docs/SERVING.md "Crash recovery").

The serving daemon's durable state is tiny — which graphs are registered
(name, path, content hash) and which executable buckets have been warmed
— but losing it on a crash means every client must re-``load`` and every
first query re-compiles.  This module journals that state as JSON lines,
fsync'd per append, so a ``kill -9`` loses at most the line being
written (a torn tail is detected and dropped on replay, never
propagated).

Record grammar (one JSON object per line)::

    {"op": "load",   "name": ..., "path": ..., "hash": ...}
    {"op": "reload", "name": ..., "path": ..., "hash": ...}
    {"op": "warm",   "name": ..., "hash": ..., "k_exec": ..., "s_pad": ...}
    {"op": "mutate", "name": ..., "inserts": [[u, v], ...],
     "deletes": [[u, v], ...], "digest": ..., "token": ...}

The optional ``token`` on mutate records is the client's idempotency
token (docs/SERVING.md "Cross-machine transport & fencing"):
tolerated-absent on replay (pre-token journals stay readable), emitted
by compaction when present, and folded into the daemon's bounded dedup
window on restart so a retry that straddles a crash still re-acks
instead of re-applying.

:meth:`StateJournal.replay` folds the line stream into the reconciled
end state — last registration per name wins, warm records survive only
while their (name, hash) still matches the live registration, mutate
records form an ORDERED per-name delta chain that a load/reload resets
(new file content, fresh version 0) and compaction preserves verbatim
(each record's chained ``digest`` lets the restart verify the replayed
chain against what was journaled, the mutation analog of the loader's
``expected_hash`` contract) — and
:meth:`StateJournal.compact` atomically rewrites the file down to that
state (temp file + fsync + rename), so the journal stays proportional
to the live state, not to the daemon's lifetime.

Growth bound (round 9): a fleet replica lives for months, and reload
churn + bucket warms grow the file without limit, so :meth:`append`
auto-compacts when the file exceeds ``MSBFS_JOURNAL_MAX_BYTES``
(default 1 MiB, <= 0 disables).  Auto-compaction replays WITHOUT
tripping the ``journal_replay`` fault seam — that seam models restart
recovery, and a mid-serving compaction firing a restart-armed fault
would make every crash-replay test's trip counts time-dependent.

Fault sites ``journal_append`` / ``journal_replay`` (utils/faults.py)
let the ``crash`` kind kill the process mid-journal deterministically —
the recovery tests' stand-in for a real power cut.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..utils import faults, knobs

_OPS = ("load", "reload", "warm", "mutate", "shard")


def _valid_pairs(pairs) -> bool:
    """Mutate payload shape check: a list of [u, v] int pairs (bools are
    ints to json — exclude them; a corrupt journal line must drop, not
    crash the replay)."""
    if not isinstance(pairs, list):
        return False
    for p in pairs:
        if not (isinstance(p, (list, tuple)) and len(p) == 2):
            return False
        if not all(
            isinstance(x, int) and not isinstance(x, bool) for x in p
        ):
            return False
    return True


@dataclass
class JournalState:
    """The reconciled end state of a journal replay."""

    # name -> (path, hash) of the live registration
    graphs: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # (name, hash, k_exec, s_pad) warmed buckets for live registrations
    warm: Set[Tuple[str, str, int, int]] = field(default_factory=set)
    # name -> ordered mutate records ({"inserts", "deletes", "digest"})
    # for the live registration; order IS the version chain, so these
    # replay (and compact) strictly after the graph's load record
    deltas: Dict[str, List[dict]] = field(default_factory=dict)
    # name -> shard manifest record for a fleet-sharded graph: the
    # parent file's hash plus the ordered shard table ({"name", "path",
    # "hash", "lo", "hi"} each).  Last write wins — a re-plan (or a
    # reheal re-append) replaces the whole manifest, so replay restores
    # exactly the current shard topology (serve/shards.py).
    shards: Dict[str, dict] = field(default_factory=dict)
    replayed: int = 0  # records applied
    dropped: int = 0  # malformed/torn/stale lines skipped

    def records(self) -> List[dict]:
        """The state as a minimal record list (compaction's payload)."""
        out: List[dict] = []
        for n, (p, h) in sorted(self.graphs.items()):
            out.append({"op": "load", "name": n, "path": p, "hash": h})
            for d in self.deltas.get(n, ()):
                rec = {
                    "op": "mutate",
                    "name": n,
                    "inserts": d["inserts"],
                    "deletes": d["deletes"],
                    "digest": d["digest"],
                }
                if d.get("token") is not None:
                    rec["token"] = d["token"]
                out.append(rec)
        out.extend(
            {"op": "warm", "name": n, "hash": h, "k_exec": k, "s_pad": s}
            for n, h, k, s in sorted(self.warm)
        )
        out.extend(
            {
                "op": "shard",
                "name": n,
                "hash": m["hash"],
                "n": m["n"],
                "replicas": m["replicas"],
                "shards": m["shards"],
            }
            for n, m in sorted(self.shards.items())
        )
        return out


class StateJournal:
    """One journal file; append is thread-safe only under the caller's
    serialization (the server appends from its verb handlers and the
    single batcher thread, both already funneled through server locks
    for the state being journaled)."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        if max_bytes is None:
            max_bytes = knobs.get_int("MSBFS_JOURNAL_MAX_BYTES", 1 << 20)
        self.max_bytes = int(max_bytes)
        self.compactions = 0
        # Latched health gauge: False from the moment an append fails
        # until one lands again (the daemon's ``journal_writable``).
        self.writable = True

    def bytes(self) -> int:
        """Current journal size on disk (0 when it does not exist yet) —
        surfaced by the daemon's ``stats`` verb as ``journal_bytes``."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # ---- append side ------------------------------------------------------
    def append(self, record: dict) -> None:
        """Durably append one record: write + flush + fsync, so the
        record survives a process kill the moment append returns.  A
        failed append — ENOSPC, a short write, a yanked volume — raises
        the typed :class:`~..runtime.supervisor.StorageError` (exit 12,
        docs/RESILIENCE.md "Disk exhaustion") and latches ``writable``
        False for the health verb; the DAEMON stays up (each caller
        decides whether its record was a durability promise or a warmth
        hint), and the first append that lands after the disk frees
        flips ``writable`` back.  Past ``max_bytes`` the file is
        auto-compacted down to the reconciled state (which keeps THIS
        record: compaction runs after the durable append, so a crash
        between the two still replays)."""
        from ..runtime.supervisor import StorageError

        line = json.dumps(record, separators=(",", ":"), sort_keys=True)
        try:
            faults.trip("journal_append")
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
                size = f.tell()
        except OSError as exc:
            self.writable = False
            raise StorageError(
                f"journal append to {self.path} failed: {exc} — the "
                "record is NOT durable (a restart will not restore this "
                "state); free disk and retry"
            ) from exc
        self.writable = True
        if self.max_bytes > 0 and size > self.max_bytes:
            self.compact(self._replay(trip=False))
            self.compactions += 1

    # ---- replay side ------------------------------------------------------
    def replay(self) -> JournalState:
        """Read and reconcile the journal.  Missing file = empty state
        (first boot).  A torn final line — the crash-mid-append case —
        is dropped silently; a malformed line elsewhere is dropped with
        a stderr note (something other than a crash corrupted the file,
        the operator should know)."""
        return self._replay(trip=True)

    def _replay(self, trip: bool) -> JournalState:
        if trip:  # restart recovery only; auto-compaction skips the seam
            faults.trip("journal_replay")
        state = JournalState()
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return state
        lines = raw.split("\n")
        torn_tail = bool(lines) and lines[-1] != ""
        if not torn_tail and lines:
            lines.pop()  # the empty split artifact after the final \n
        for i, line in enumerate(lines):
            if not line:
                continue
            record = self._parse(line)
            if record is None:
                state.dropped += 1
                if not (torn_tail and i == len(lines) - 1):
                    print(
                        f"msbfs serve: journal {self.path} line {i + 1} "
                        "is not a valid record; skipping it",
                        file=sys.stderr,
                    )
                continue
            if self._apply(state, record):
                state.replayed += 1
        return state

    @staticmethod
    def _parse(line: str) -> Optional[dict]:
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            return None
        if not isinstance(record, dict) or record.get("op") not in _OPS:
            return None
        return record

    @staticmethod
    def _apply(state: JournalState, record: dict) -> bool:
        """Fold one record into ``state``; False = dropped (stale warm,
        missing fields), which counts as dropped, never as replayed."""
        op = record["op"]
        name = str(record.get("name", "default"))
        if op in ("load", "reload"):
            path, digest = record.get("path"), record.get("hash")
            if not isinstance(path, str) or not isinstance(digest, str):
                state.dropped += 1
                return False
            state.graphs[name] = (path, digest)
            # A re-registration with new content strands the old warms
            # AND resets the delta chain: version 0 is the file content.
            state.warm = {
                w for w in state.warm if not (w[0] == name and w[1] != digest)
            }
            state.deltas.pop(name, None)
            return True
        if op == "mutate":
            if name not in state.graphs:
                state.dropped += 1  # chain with no base graph
                return False
            inserts = record.get("inserts")
            deletes = record.get("deletes")
            digest = record.get("digest")
            if not _valid_pairs(inserts) or not _valid_pairs(deletes) or not isinstance(digest, str):
                state.dropped += 1
                return False
            token = record.get("token")
            if token is not None and not isinstance(token, str):
                token = None  # corrupt token degrades to absent, not a crash
            state.deltas.setdefault(name, []).append(
                {"inserts": inserts, "deletes": deletes, "digest": digest,
                 "token": token}
            )
            return True
        if op == "shard":
            # Fleet shard manifest (serve/shards.py): structural check
            # field by field — a torn or hand-mangled manifest must drop
            # (the supervisor re-plans from the registered parent), not
            # crash replay or resurrect a half-table.
            digest = record.get("hash")
            table = record.get("shards")
            if (
                not isinstance(digest, str)
                or not isinstance(table, list)
                or not table  # a sharded graph with no shards is torn
            ):
                state.dropped += 1
                return False
            try:
                total_n = int(record["n"])
                replicas = int(record["replicas"])
            except (KeyError, TypeError, ValueError):
                state.dropped += 1
                return False
            if isinstance(total_n, bool) or total_n < 0 or replicas < 1:
                state.dropped += 1
                return False
            for row in table:
                if not isinstance(row, dict):
                    state.dropped += 1
                    return False
                if not all(
                    isinstance(row.get(k), str) and row.get(k)
                    for k in ("name", "path", "hash")
                ):
                    state.dropped += 1
                    return False
                lo, hi = row.get("lo"), row.get("hi")
                if not all(
                    isinstance(x, int) and not isinstance(x, bool)
                    for x in (lo, hi)
                ) or not (0 <= lo < hi <= total_n):
                    state.dropped += 1
                    return False
            state.shards[name] = {
                "hash": digest,
                "n": total_n,
                "replicas": replicas,
                "shards": table,
            }
            return True
        # op == "warm"
        digest = record.get("hash")
        live = state.graphs.get(name)
        if live is None or not isinstance(digest, str):
            state.dropped += 1
            return False
        if live[1] != digest:
            state.dropped += 1  # warm for content no longer registered
            return False
        try:
            k_exec, s_pad = int(record["k_exec"]), int(record["s_pad"])
        except (KeyError, TypeError, ValueError):
            state.dropped += 1
            return False
        state.warm.add((name, digest, k_exec, s_pad))
        return True

    # ---- compaction -------------------------------------------------------
    def compact(self, state: JournalState) -> None:
        """Atomically rewrite the journal to the reconciled state: temp
        file in the same directory, fsync, rename — a crash at any point
        leaves either the old journal or the new one, never a mix."""
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(
            prefix=".journal.", suffix=".tmp", dir=directory
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                for record in state.records():
                    f.write(
                        json.dumps(record, separators=(",", ":"),
                                   sort_keys=True) + "\n"
                    )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError as exc:
            print(
                f"msbfs serve: journal compaction failed: {exc}; keeping "
                "the uncompacted journal",
                file=sys.stderr,
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass
