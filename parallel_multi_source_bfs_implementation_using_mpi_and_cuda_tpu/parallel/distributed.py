"""Distributed query execution: shard_map over the ('q', 'v') mesh.

End-to-end replacement for the reference's MPI phase structure:

* graph broadcast (main.cu:242-255)  -> replicated NamedSharding device_put;
* round-robin assignment (303-307)   -> cyclic grid sharded over 'q';
* per-rank BFS loop (312-322)        -> vmap-batched BFS per shard;
* Gather/Gatherv of (q, F) pairs with a custom MPI struct (324-368)
                                     -> fixed-shape (K,) int64 pmax merge
                                        (each shard contributes its slots,
                                        -1 elsewhere; SPMD static shapes
                                        replace the ragged wire format);
* rank-0 argmin (379-397)            -> on-device masked argmin, replicated.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.csr import CSRGraph, DeviceCSR
from ..ops.bfs import graph_expand, multi_source_bfs
from ..ops.engine import QueryEngineBase
from ..ops.objective import f_of_u
from .mesh import QUERY_AXIS, VERTEX_AXIS
from .scheduler import merge_local_f, shard_queries


@partial(
    jax.jit,
    static_argnames=("mesh", "k", "k_pad", "w", "query_chunk", "max_levels", "expand"),
)
def _distributed_f_values(
    mesh: Mesh,
    graph: DeviceCSR,
    query_grid: jax.Array,  # (W, J, S) cyclic layout
    k: int,
    k_pad: int,
    w: int,
    query_chunk: int,
    max_levels,
    expand,
) -> jax.Array:
    """Returns the merged (k_pad,) int64 F array, replicated on every device."""

    def shard_body(graph, qblock):
        # qblock arrives as (1, J, S): the mesh-sharded leading axis keeps
        # rank with local extent W/W = 1.  Drop it -> this shard's J queries
        # in cyclic order.
        qblock = qblock[0]
        j = qblock.shape[0]

        def one(q):
            dist = multi_source_bfs(graph, q, max_levels=max_levels, expand=expand)
            return f_of_u(dist)

        chunked = qblock.reshape(j // query_chunk, query_chunk, qblock.shape[1])
        f_local = lax.map(jax.vmap(one), chunked).reshape(j)
        return merge_local_f(f_local, j, w, k, k_pad, (QUERY_AXIS, VERTEX_AXIS))

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(QUERY_AXIS)),
        out_specs=P(),
    )(graph, query_grid)


class DistributedEngine(QueryEngineBase):
    """Query-sharded execution over a mesh, graph replicated per device
    (the reference's full-graph-per-rank model, SURVEY.md C8)."""

    def __init__(
        self,
        mesh: Mesh,
        graph: CSRGraph | DeviceCSR,
        max_levels: Optional[int] = None,
        query_chunk: Optional[int] = None,
        expand=graph_expand,
    ):
        self.mesh = mesh
        self.w = mesh.shape[QUERY_AXIS]
        replicated = NamedSharding(mesh, P())
        if isinstance(graph, CSRGraph):
            graph = DeviceCSR.from_host(graph, sharding=replicated)
        self.graph = graph
        self.max_levels = max_levels
        self.query_chunk = query_chunk
        self.expand = expand

    def f_values(self, queries: np.ndarray) -> jax.Array:
        """(K, S) -1-padded queries -> (K,) int64 F values (replicated)."""
        sharded, k, k_pad, chunk = shard_queries(
            self.mesh, np.asarray(queries), self.query_chunk
        )
        merged = _distributed_f_values(
            self.mesh,
            self.graph,
            sharded,
            k,
            k_pad,
            self.w,
            chunk,
            self.max_levels,
            self.expand,
        )
        return merged[:k]
