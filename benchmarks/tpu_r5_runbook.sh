#!/bin/bash
# Round-5 TPU measurement runbook — the executed steps and their raw
# artifacts (every step was run on 2026-07-31 and is independently
# re-runnable; the persistent XLA compilation cache makes repeats cheap).
#
# Executed artifacts under benchmarks/raw_r5/:
#   step 1  bench_rmat24_k256.json         (attempt: unchunked W=8 BFS in one
#           dispatch crashed the TPU worker — honest root cause)
#           bench_rmat24_k256_retry.json   (CERTIFIED 2.107 GTEPS, vs 1.41)
#   step 2  config4_stencil_detail.json    (first stencil row 0.97 s + 4g
#           gather shootout 11.79 s + config 1)
#   step 2b config4_stencil2_detail.json   (post-optimization: config 4
#           0.255 s vs_baseline 0.786; config 1 0.145 s)
#           road_k64_stencil.json (0.277 s, vs 3.00)
#           road_k256_stencil.json (0.715 s, vs 4.67)
#   step 3  level_trace_road1024.txt       (MSBFS_STATS=2 stepped trace +
#           sub-op micros; the stepped mode reads ~109 ms/level of pure
#           tunnel RTT — per-level device cost needs fixed-count fori
#           probes, docs/PERF_NOTES.md "Round-5 findings")
#   step 4  bench_headline.json            (the BENCH_r05 artifact twin)
#   step 5  gr_end_to_end.txt              (23M-arc .gr -> convert -> main.py)
#
# NOTE (hard-won): never OVERWRITE PYTHONPATH on a TPU run — the axon
# plugin registers via PYTHONPATH=/root/.axon_site; append instead.
set -uo pipefail
cd "$(dirname "$0")/.."
RAW=benchmarks/raw_r5
mkdir -p "$RAW"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
echo "runbook start $(stamp)" | tee -a "$RAW/runbook_meta.txt"
python -c "import jax; print('jax', jax.__version__)" 2>/dev/null \
    | tee -a "$RAW/runbook_meta.txt"

echo "== 1. RMAT-24 x K=256 (certified config: bounded dispatches + slot budget)"
BENCH_CONFIGS= BENCH_SCALE=24 BENCH_K=256 BENCH_REPEATS=2 BENCH_EXTRA_KS= \
    BENCH_SPARSE=0 MSBFS_SLOT_BUDGET=33554432 BENCH_LEVEL_CHUNK=2 \
    BENCH_WAIT_S=900 BENCH_RUN_S=7200 python bench.py \
    2> "$RAW/bench_rmat24_k256_retry.stderr" | tee "$RAW/bench_rmat24_k256_retry.json"

echo "== 2. config sweep rows 4,4g,1 (stencil vs gather shootout + latency split)"
BENCH_CONFIGS=4,4g,1 BENCH_RUN_S=3600 \
    BENCH_DETAIL_PATH="$RAW/config4_stencil2_detail.json" python bench.py \
    2> "$RAW/config4_stencil2.stderr" | tee "$RAW/config4_stencil2.json"

echo "== 2c. road-class K scaling through the stencil route"
for K in 64 256; do
  BENCH_CONFIGS= BENCH_GRAPH=road BENCH_ENGINE=stencil BENCH_SCALE=20 \
      BENCH_K=$K BENCH_MAX_S=8 BENCH_LEVEL_CHUNK=auto BENCH_EXTRA_KS= \
      BENCH_REPEATS=3 BENCH_RUN_S=1800 python bench.py \
      2> "$RAW/road_k${K}_stencil.stderr" | tee "$RAW/road_k${K}_stencil.json"
done

echo "== 3. on-chip MSBFS_STATS=2 per-level trace + sub-op micros, road-1024"
PYTHONPATH=$PWD:${PYTHONPATH:-} timeout 1800 python benchmarks/exp_level_trace.py \
    2>&1 | tee "$RAW/level_trace_road1024.txt" || true

echo "== 4. headline sweep (2,2c,4,1 — the BENCH_r05 artifact twin)"
BENCH_DETAIL_PATH="$RAW/bench_headline_detail.json" BENCH_RUN_S=2400 python bench.py \
    2> "$RAW/bench_headline.stderr" | tee "$RAW/bench_headline.json"

echo "== 5. real-format .gr end-to-end (converter path at 23M arcs)"
timeout 3600 bash benchmarks/exp_gr_end_to_end.sh "$RAW" \
    2>&1 | tee "$RAW/gr_end_to_end.txt" || true

echo "== 6. multi-chip decisions that still need pod hardware: see"
echo "      benchmarks/tpu_r4_runbook.sh step 7 (push-vs-pull ICI routing,"
echo "      configs 3/5/6) — one command when a pod exists."
echo "runbook end $(stamp)" | tee -a "$RAW/runbook_meta.txt"
