"""Query scheduling: the reference's static round-robin, mesh-sharded.

The reference assigns query k to rank ``k % world_size`` (the
``for(kidx = world_rank; kidx < K; kidx += world_size)`` loop,
main.cu:303-307).  Here the (K, S) padded query array is laid out as a
(W, J, S) cyclic grid — slot [r, j] holds global query ``r + j*W`` — and the
leading axis is sharded over the ``'q'`` mesh axis, so shard r receives
exactly the reference's query set, in the reference's order.

No work stealing and no cost model, faithfully (SURVEY.md C9 notes the load
imbalance is inherited behavior; improving it is an opt-in extension).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import QUERY_AXIS


def cyclic_assignment(k: int, w: int) -> List[List[int]]:
    """Global query ids owned by each of w shards (reference main.cu:303-307)."""
    return [list(range(r, k, w)) for r in range(w)]


def reassign(k: int, w: int, failed_ranks) -> List[List[int]]:
    """Degrade-to-survivors rescheduling: the cyclic assignment for ``w``
    shards with ``failed_ranks`` lost, their query groups redistributed
    cyclically over the survivors (the same round-robin the reference
    uses for the initial assignment, main.cu:303-307, applied to the
    orphaned ids in ascending order).

    Returns a length-``w`` list: failed rows are empty, each survivor
    keeps its original ids plus its cyclic share of the orphans.
    Deterministic in (k, w, failed_ranks), so the supervisor's recovery
    trace replays exactly; the merged (F, argmin) result is bit-identical
    to the fault-free run because each query's F value depends only on
    the query, never on which rank computed it (scheduler merge
    semantics, :func:`merge_local_f`).  Raises when no rank survives —
    that loss is unrecoverable and must surface as a DeviceError."""
    failed = {int(r) for r in failed_ranks if 0 <= int(r) < w}
    survivors = [r for r in range(w) if r not in failed]
    if not survivors:
        raise ValueError(f"no surviving ranks (w={w}, failed={sorted(failed)})")
    base = cyclic_assignment(k, w)
    out = [list(base[r]) if r in set(survivors) else [] for r in range(w)]
    orphans = sorted(g for r in failed for g in base[r])
    for i, gid in enumerate(orphans):
        out[survivors[i % len(survivors)]].append(gid)
    return out


def cyclic_grid(
    queries: np.ndarray, w: int, min_j_multiple: int = 1
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Lay out (K, S) -1-padded queries as a (W, J, S) cyclic grid.

    Returns (grid, gids, k_pad) where ``grid[r, j] = queries[r + j*w]``,
    ``gids[r, j] = r + j*w`` and rows past K are -1 padding (excluded from
    the result merge — the analog of main.cu:325's -1-initialized
    all_F_values).  J is rounded up to ``min_j_multiple`` (query-chunk
    alignment).
    """
    k, s = queries.shape
    j = max(1, -(-k // w))
    j = -(-j // min_j_multiple) * min_j_multiple
    k_pad = w * j
    padded = np.full((k_pad, s), -1, dtype=np.int32)
    padded[:k] = queries
    grid = padded.reshape(j, w, s).transpose(1, 0, 2)  # grid[r, j] = padded[r + j*w]
    gids = (np.arange(w)[:, None] + np.arange(j)[None, :] * w).astype(np.int32)
    return np.ascontiguousarray(grid), gids, k_pad


def shard_queries(
    mesh, queries: np.ndarray, query_chunk: Optional[int]
) -> Tuple[jax.Array, int, int, int]:
    """Cyclic-grid a (K, S) query array and place it sharded over 'q'.

    Returns (sharded (W, J, S) grid, k, k_pad, chunk) — the common prologue
    of every distributed engine.
    """
    from ..utils.faults import trip

    w = mesh.shape[QUERY_AXIS]
    k = queries.shape[0]
    chunk = query_chunk or max(1, -(-k // w))
    grid, _, k_pad = cyclic_grid(np.asarray(queries), w, min_j_multiple=chunk)
    trip("device_put")  # fault seam: upload failures are injectable here
    sharded = jax.device_put(grid, NamedSharding(mesh, P(QUERY_AXIS)))
    return sharded, k, k_pad, chunk


def pack_padded_requests(
    blocks: List[np.ndarray], k_exec: int, s_pad: int
) -> Tuple[np.ndarray, List[int]]:
    """Stack per-request (K_i, S_i) -1-padded query blocks into one
    (k_exec, s_pad) batch; returns (batch, offsets) with ``offsets`` of
    length len(blocks)+1 so request i owns rows [offsets[i], offsets[i+1]).

    The serving micro-batcher's packing step (serve/batcher.py): requests
    in the same shape bucket coalesce into one dispatch, and the -1 fill
    rows past the last request are inert exactly like the reference's
    out-of-range source ids (main.cu:46-51) and this scheduler's own
    cyclic-grid padding rows.  Fails loud on a bucket-policy violation
    (block wider than s_pad, or more rows than k_exec) — a silent
    truncation would return wrong F values for the clipped queries.
    """
    offsets = [0]
    for b in blocks:
        if b.ndim != 2 or b.shape[1] > s_pad:
            raise ValueError(
                f"request block {b.shape} does not fit group width {s_pad}"
            )
        offsets.append(offsets[-1] + int(b.shape[0]))
    if offsets[-1] > k_exec:
        raise ValueError(
            f"{offsets[-1]} packed rows exceed the {k_exec}-row bucket"
        )
    batch = np.full((k_exec, s_pad), -1, dtype=np.int32)
    for b, lo in zip(blocks, offsets):
        batch[lo : lo + b.shape[0], : b.shape[1]] = b
    return batch, offsets


def merge_local_f(f_local: jax.Array, j: int, w: int, k: int, k_pad: int, axes):
    """Merge one shard's (J,) F values into the replicated (k_pad,) result.

    Each shard writes its cyclic slots (gid = r + j*W) and -1 elsewhere —
    padding slots stay "never computed" like the reference's -1-initialized
    all_F_values (main.cu:325, 370-375) — then a max all-reduce over ``axes``
    reconstructs the full array (every real slot is >= 0 on exactly one
    shard): the SPMD fixed-shape analog of MPI_Gatherv + scatter-by-q
    (main.cu:362-375).

    The 64-bit max rides as TWO u32 maxes of the +1-biased value's halves:
    the TPU AOT path behind the axon tunnel rejects 64-bit non-sum
    all-reduces ("Supported lowering only of Sum all reduce" — probed and
    committed, benchmarks/raw_r4/axon_collective_probe.txt) while u32/s32
    reductions lower fine.  The split is exact, not approximate: exactly
    one shard owns each slot and every other shard contributes the biased
    identity 0 = (0, 0), so the componentwise u32 maxes reconstruct the
    owner's exact halves (no lexicographic coupling between words can
    arise when all non-owner words are zero).
    """
    r = lax.axis_index(QUERY_AXIS)
    gids = r.astype(jnp.int32) + jnp.arange(j, dtype=jnp.int32) * w
    f_local = jnp.where(gids < k, f_local, jnp.int64(-1))
    merged = jnp.full((k_pad,), jnp.int64(-1)).at[gids].set(f_local)
    biased = (merged + 1).astype(jnp.uint64)  # >= 0; non-owner slots 0
    hi = lax.pmax((biased >> 32).astype(jnp.uint32), axes)
    lo = lax.pmax(biased.astype(jnp.uint32), axes)
    out = (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
    return out.astype(jnp.int64) - 1
