# Build the native (C++) runtime components.
PKG := parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu
CXX ?= g++
CXXFLAGS ?= -O3 -march=native -std=c++17 -fPIC -Wall -Wextra -pthread

.PHONY: native clean test

native: $(PKG)/runtime/librt_loader.so

$(PKG)/runtime/librt_loader.so: $(PKG)/runtime/loader.cpp
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

clean:
	rm -f $(PKG)/runtime/librt_loader.so

test: native
	python -m pytest tests/ -x -q
