"""Wire protocol: length-prefixed JSON frames (docs/SERVING.md).

One frame = a 4-byte big-endian unsigned length followed by that many
bytes of UTF-8 JSON encoding a single object.  Both directions use the
same framing; a connection carries any number of request/response pairs
in order (no pipelining guarantees beyond FIFO per connection).

Requests are objects with an ``op`` field (``ping`` / ``health`` /
``load`` / ``reload`` / ``query`` / ``mutate`` / ``versions`` /
``stats`` / ``trace`` / ``metrics`` / ``posture`` / ``shutdown``);
responses carry ``ok: true`` plus op-specific fields, or ``ok: false``
with a typed ``error`` object mirroring the supervisor taxonomy
(``{"type", "message", "exit_code"}`` — docs/RESILIENCE.md exit-code
table).  ``ping`` answers with the daemon's ``pid`` (the stale-socket
probe and "already running" diagnostics key on it); ``health`` is the
readiness report (docs/SERVING.md probe table).  ``query`` accepts an
optional ``deadline_s`` number — a client-relative budget the server
uses to shed requests whose caller has already given up.  Query ids
and F values are plain JSON numbers: F fits in int64 and JSON numbers
are exact through 2^53, far beyond any sum of n hop-distances this
system can hold in HBM.

Observability fields (docs/OBSERVABILITY.md): any request MAY carry an
optional ``trace`` object (``{"trace_id": <hex string>}``) naming the
distributed-trace context the handling should be attributed to; the
rollout is tolerated-absent exactly like the crc flag — receivers
ignore unknown fields, so a pre-trace peer interoperates unchanged in
both directions.  ``trace`` (the op) returns a trace's recorded span
events; ``metrics`` returns a Prometheus text exposition snapshot.

The length prefix is bounded (:data:`MAX_FRAME_BYTES`,
``MSBFS_SERVE_MAX_FRAME`` overrides): a corrupt or hostile prefix must
never turn into a multi-GiB allocation — the same fail-before-allocate
posture as the binary graph loader (utils/io.py header checks).

Frame integrity: the high bit of the length prefix (:data:`_CRC_FLAG`)
flags that a 4-byte big-endian crc32 of the body follows the prefix.
Frames WITHOUT the flag are always accepted (tolerated-absent), so the
compat is one-way: a pre-crc peer can SEND to this version, but it
cannot parse a flagged frame (its prefix read sees a length >= 2^31
and errors).  Rolling a mixed-version fleet forward therefore takes
two phases, the standard recipe: first deploy every node with
``MSBFS_WIRE_CRC=legacy`` — send unflagged frames, still verify any
flagged frame received — then, once no pre-crc peer remains, unset the
knob (default ``on``) to turn checksummed sends on everywhere.  A crc
mismatch raises :class:`FrameCorruptError`, which both seams convert to
the TRANSIENT class, not Input: the payload was damaged in flight, a
resend or a different replica plausibly succeeds, and the fleet
router's failover path (serve/router.py) handles it like any dropped
connection.  The checksum lives OUTSIDE the JSON on purpose — a flipped
bit can destroy the body's parseability, so an in-band checksum field
could never be read back off a corrupt frame.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Optional

from ..utils import faults, knobs

_LEN = struct.Struct("!I")
_CRC = struct.Struct("!I")

# The flag bit caps checksummed frames at 2 GiB - 1; the 64 MiB frame
# bound (and any sane override) sits far below it.
_CRC_FLAG = 0x80000000

# 64 MiB default: a 255-group x 255-source query batch plus its response
# is < 1 MiB of JSON, so this bounds damage, not capability.
MAX_FRAME_BYTES = 64 << 20


class ProtocolError(ValueError):
    """Malformed frame (oversized prefix, truncated body, non-JSON,
    non-object payload).  Classified as InputError at the server seam."""


class FrameCorruptError(ProtocolError):
    """A frame whose body does not match its crc32: damaged in flight,
    not malformed by the sender.  Classified as TransientError at both
    seams (resend/failover recovers), unlike its ProtocolError parent.
    """


def max_frame_bytes() -> int:
    """The active bound (env-overridable, malformed values fall back —
    the repo-wide knob convention)."""
    raw = knobs.raw("MSBFS_SERVE_MAX_FRAME", "")
    if raw:
        try:
            v = int(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return MAX_FRAME_BYTES


def crc_sends_enabled() -> bool:
    """``MSBFS_WIRE_CRC``: ``on`` (default) sends checksummed flagged
    frames; ``legacy`` (or ``off``/``0``) sends unflagged pre-crc
    frames that any older peer can parse — the phase-1 setting of the
    two-phase rolling upgrade (module docstring).  Receiving is NOT
    gated: flagged frames are verified, unflagged frames accepted,
    whatever the knob says."""
    raw = knobs.raw("MSBFS_WIRE_CRC", "on").strip().lower()
    return raw not in ("legacy", "off", "0")


def encode_frame(obj: dict, crc: Optional[bool] = None) -> bytes:
    """One object -> one frame.  ``crc`` None defers to the
    ``MSBFS_WIRE_CRC`` knob; True/False force the framing (tests)."""
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > max_frame_bytes() or len(body) >= _CRC_FLAG:
        raise ProtocolError(
            f"frame of {len(body)} bytes exceeds the "
            f"{min(max_frame_bytes(), _CRC_FLAG - 1)}-byte bound"
        )
    if crc is None:
        crc = crc_sends_enabled()
    if not crc:
        return _LEN.pack(len(body)) + body
    return (
        _LEN.pack(len(body) | _CRC_FLAG)
        + _CRC.pack(zlib.crc32(body))
        + body
    )


# ``net_reorder`` holds one outbound frame per thread so the NEXT frame
# overtakes it on the wire; _flush_held delivers stragglers before any
# read on the same thread (a held request with no successor would
# otherwise deadlock the request/response pair waiting on itself).
_REORDER = threading.local()


def _flush_held() -> None:
    held = getattr(_REORDER, "held", None)
    if not held:
        return
    _REORDER.held = []
    for held_sock, held_frame in held:
        try:
            held_sock.sendall(held_frame)
        except OSError:
            # The overtaking frame's connection may already be gone —
            # delivering late to a dead peer is exactly what a reordered
            # network does; the receiver side's framing survives either
            # way.
            pass


def send_frame(sock: socket.socket, obj: dict) -> None:
    frame = encode_frame(obj)
    if faults.consume_wire_taint():
        # ``wire_corrupt`` chaos seam: flip one body bit AFTER the crc32
        # was computed — the receiver's checksum check is the recovery
        # path under test (a taint on an empty body degrades to nothing
        # to flip, which no real frame has).  Legacy-mode frames have no
        # crc word, so locate the body off the flag bit, not a fixed 8.
        (prefix_word,) = _LEN.unpack(frame[: _LEN.size])
        prefix = _LEN.size + (
            _CRC.size if prefix_word & _CRC_FLAG else 0
        )
        if len(frame) > prefix:
            buf = bytearray(frame)
            buf[prefix + (len(buf) - prefix) // 2] ^= 0x10
            frame = bytes(buf)
    # Network chaos seam (utils/faults.py "Network chaos kinds"): whole-
    # frame filters armed by the router's trip, consumed here so the
    # fault fires at the protocol boundary itself — the receiver (and
    # the dedup window, and the failover walk) sees byte-for-byte what a
    # lossy network would deliver.
    dup = False
    for filt in faults.consume_frame_chaos():
        mode = filt["mode"]
        if mode == "drop":
            _flush_held()
            faults.raise_partition_drop(
                filt["replica"], filt["side"], filt["target_side"]
            )
        if mode == "delay":
            time.sleep(filt["delay_ms"] / 1000.0)
        elif mode == "dup":
            dup = True
        elif mode == "reorder":
            held = getattr(_REORDER, "held", None)
            if held is None:
                held = _REORDER.held = []
            held.append((sock, frame))
            return
        elif mode == "half_open":
            # The peer's SYN/ACK state survived but its process is gone:
            # our write vanishes (reported as success — TCP buffers it),
            # and the response never arrives.  Arm the read black hole
            # and write NOTHING.
            faults.arm_read_blackhole(filt["replica"])
            return
    sock.sendall(frame)
    if dup:
        # Retransmit-after-lost-ack: the same frame lands twice and the
        # receiver processes both copies.
        sock.sendall(frame)
    _flush_held()


def _read_exact(sock: socket.socket, count: int) -> Optional[bytes]:
    """Read exactly ``count`` bytes, or None on clean EOF at a frame
    boundary (mid-frame EOF is a ProtocolError: the peer vanished)."""
    chunks = []
    got = 0
    while got < count:
        chunk = sock.recv(min(count - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError(
                f"connection closed mid-frame ({got}/{count} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Optional[dict]:
    """One frame -> dict, or None on clean EOF (peer done)."""
    # A frame held for reordering must go out before this thread blocks
    # on a response, or the request/response pair deadlocks on itself.
    _flush_held()
    blackhole = faults.consume_read_blackhole()
    if blackhole is not None:
        faults.raise_half_open(blackhole)
    header = _read_exact(sock, _LEN.size)
    if header is None:
        return None
    (prefix,) = _LEN.unpack(header)
    want_crc = bool(prefix & _CRC_FLAG)
    length = prefix & ~_CRC_FLAG
    if length > max_frame_bytes():
        raise ProtocolError(
            f"frame prefix claims {length} bytes, bound is "
            f"{max_frame_bytes()}"
        )
    crc_expected = None
    if want_crc:
        crc_header = _read_exact(sock, _CRC.size)
        if crc_header is None:
            raise ProtocolError("connection closed between prefix and crc")
        (crc_expected,) = _CRC.unpack(crc_header)
    body = _read_exact(sock, length) if length else b""
    if body is None:
        raise ProtocolError("connection closed between prefix and body")
    if crc_expected is not None and zlib.crc32(body) != crc_expected:
        raise FrameCorruptError(
            f"frame crc32 mismatch: expected {crc_expected:#010x}, body "
            f"hashes to {zlib.crc32(body):#010x} ({length} bytes) — "
            "frame damaged in flight"
        )
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ProtocolError(f"frame body is not JSON: {exc}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"frame body must be a JSON object, got {type(obj).__name__}"
        )
    return obj


def error_body(err) -> dict:
    """Typed error -> the wire's ``error`` object (taxonomy class name,
    message, documented exit code — docs/RESILIENCE.md)."""
    return {
        "ok": False,
        "error": {
            "type": type(err).__name__,
            "message": str(err),
            "exit_code": int(getattr(err, "exit_code", 6)),
        },
    }


def parse_address(addr: str):
    """``unix:<path>`` or ``<host>:<port>`` -> (family, target).

    The unix form is the default deployment (single host, no TCP
    exposure); TCP is opt-in for multi-host clients.
    """
    if addr.startswith("unix:"):
        path = addr[len("unix:"):]
        if not path:
            raise ValueError("unix: address needs a socket path")
        return socket.AF_UNIX, path
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"address {addr!r}: want unix:<path> or <host>:<port>"
        )
    try:
        return socket.AF_INET, (host, int(port))
    except ValueError:
        raise ValueError(f"address {addr!r}: port {port!r} is not an "
                         "integer") from None


def _float_knob(name: str, fallback: float) -> float:
    raw = knobs.raw(name, str(fallback))
    try:
        v = float(raw)
    except ValueError:
        return fallback
    return v if v >= 0 else fallback


def net_connect_timeout_s() -> float:
    """``MSBFS_NET_CONNECT_TIMEOUT_S`` (default 5): bound on the TCP/unix
    connect handshake when the caller gave no explicit timeout — a
    partitioned or half-open peer must fail the dial in bounded time,
    not hang a router walk.  0 disables (blocking connect)."""
    return _float_knob("MSBFS_NET_CONNECT_TIMEOUT_S", 5.0)


def net_read_timeout_s() -> float:
    """``MSBFS_NET_READ_TIMEOUT_S`` (default 0 = inherit the caller's
    request timeout): per-read socket timeout after connect.  Non-zero
    turns a silent half-open peer into a timeout error the taxonomy
    classifies TRANSIENT, so the router fails over instead of waiting
    forever."""
    return _float_knob("MSBFS_NET_READ_TIMEOUT_S", 0.0)


def net_keepalive_enabled() -> bool:
    """``MSBFS_NET_KEEPALIVE`` (default 1): SO_KEEPALIVE on TCP legs so
    the kernel probes idle cross-machine connections and surfaces dead
    peers as errors instead of eternal silence.  Unix sockets never need
    it (a dead peer is an immediate EOF on the same host)."""
    raw = knobs.raw("MSBFS_NET_KEEPALIVE", "1").strip().lower()
    return raw not in ("0", "off", "false", "")


def connect(addr: str, timeout: Optional[float] = None) -> socket.socket:
    """Dial ``addr`` with the cross-machine transport discipline
    (docs/SERVING.md "Cross-machine transport & fencing"): the connect
    phase is bounded by ``timeout`` (or ``MSBFS_NET_CONNECT_TIMEOUT_S``
    when None), TCP legs get keepalive, and after the handshake the
    socket's read timeout is ``MSBFS_NET_READ_TIMEOUT_S`` if set, else
    the caller's ``timeout`` (None = blocking, the pre-TCP behavior)."""
    family, target = parse_address(addr)
    sock = socket.socket(family, socket.SOCK_STREAM)
    try:
        connect_t = timeout if timeout is not None else net_connect_timeout_s()
        if connect_t:
            sock.settimeout(connect_t)
        sock.connect(target)
        if family == socket.AF_INET and net_keepalive_enabled():
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        read_t = net_read_timeout_s()
        sock.settimeout(read_t if read_t else timeout)
    except (OSError, ValueError):
        sock.close()
        raise
    return sock
