"""Output certification: prove a distance-to-set answer, don't trust it.

At fleet scale on real accelerators silent data corruption is a when,
not an if: a flipped bit in a frontier plane, a distance buffer, or a
wire payload propagates into a wrong F(U_k) and a wrong argmin with no
error raised anywhere.  BFS has a rare gift here — its output is
**self-certifying** in one O(E) vectorized pass over the CSR:

``source-zero``      every valid in-range source has distance 0;
``zero-is-source``   every distance-0 vertex IS a source;
``edge-relaxation``  for every directed slot u->v with u reached,
                     v is reached and dist[v] <= dist[u] + 1 (the CSR
                     stores both slot directions, so this pins
                     |dist[u] - dist[v]| <= 1 and forbids a
                     reached->unreached edge);
``witness``          every vertex at distance d >= 1 has a neighbor at
                     distance d - 1.

Any int array satisfying all four IS the BFS distance field for that
source set — there is exactly one such field.  The engines only report
F(U_k) (the per-query distance sum), so the auditor recomputes the
distance field with an *untrusted* host-side level sweep, certifies the
recompute against the invariants (making the recompute trustless: a bug
or a flipped bit in the audit path itself flunks the certificate), and
then checks the engine's claimed F against the certified field
(``f-mismatch``).  Total cost O(E) per BFS level, vectorized numpy on
the host CSR — independent of which engine, chunking, mesh or kernel
produced the answer, which is the point.

:func:`fold_digest` is the companion fingerprint: a position-sensitive
xor-fold of any buffer set, used by the drive loops to journal
per-plane digests at chunk/stream/megachunk boundaries (two clean runs
produce identical trails; a corrupted run's trail diverges at exactly
the corrupted chunk) and by the fleet router to compare answers across
replicas without shipping the full payload twice.

See docs/RESILIENCE.md "Silent data corruption".
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = [
    "INVARIANTS",
    "WEIGHTED_INVARIANTS",
    "fold_digest",
    "reference_distances",
    "certify_distances",
    "f_from_distances",
    "audit_f_values",
    "make_auditor",
    "reference_weighted_distances",
    "certify_weighted_distances",
    "audit_weighted_f_values",
    "make_weighted_auditor",
    "start_plane_trail",
    "stop_plane_trail",
    "plane_trail",
    "trail_armed",
    "record_plane_digest",
]

INVARIANTS = (
    "source-zero",
    "zero-is-source",
    "edge-relaxation",
    "witness",
    "f-mismatch",
)

#: The weighted certificate (weighted/ delta-stepping outputs): same
#: one-pass self-certifying structure, hop bounds replaced by cost
#: bounds.  ``weighted-relaxation`` is the triangle inequality over
#: every directed CSR slot — dist[v] <= dist[u] + w(u, v) with u
#: reached forcing v reached (both slot directions carry the record's
#: cost, so this pins |dist[u] - dist[v]| <= w from both sides);
#: ``weighted-witness`` demands every reached non-source v have a
#: neighbor u with dist[u] + w(u, v) == dist[v] (a tight predecessor).
#: An int field satisfying all five IS the weighted distance-to-set
#: field — positive costs make the SSSP fixpoint unique.
WEIGHTED_INVARIANTS = (
    "source-zero",
    "zero-is-source",
    "weighted-relaxation",
    "weighted-witness",
    "f-mismatch",
)

_W_INF = np.int64(1) << np.int64(62)  # audit-side unreached sentinel

_MIX_A = np.uint32(0x9E3779B9)  # golden-ratio index salt
_MIX_B = np.uint32(0x7FEB352D)  # 2-round integer-hash finalizer
_MIX_C = np.uint32(0x846CA68B)


def _mix32(x: np.ndarray) -> np.ndarray:
    """Elementwise avalanche finalizer (uint32 -> uint32): a plain
    xor-fold would let two flips cancel and is insensitive to WHERE a
    bit flipped; mixing each word with its position salt first makes
    every (position, bit) pair land on an independent-looking word."""
    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        x = x ^ (x >> np.uint32(16))
        x = x * _MIX_B
        x = x ^ (x >> np.uint32(15))
        x = x * _MIX_C
        return x ^ (x >> np.uint32(16))


def fold_digest(*arrays) -> int:
    """Position-sensitive xor-fold digest of one or more buffers.

    Returns a python int in [0, 2^32).  Any single-bit change in any
    buffer — including moving a value between positions, or between
    buffers — changes the digest (up to 32-bit collision odds).  Cost:
    one vectorized pass over the bytes; safe on any dtype/shape,
    including jax arrays (materialized via ``np.asarray``).
    """
    acc = np.uint32(len(arrays))
    for ordinal, a in enumerate(arrays):
        v = np.ascontiguousarray(np.asarray(a))
        b = v.view(np.uint8).reshape(-1)
        if b.size % 4:
            b = np.concatenate(
                [b, np.zeros(4 - b.size % 4, dtype=np.uint8)]
            )
        w = b.view(np.uint32)
        idx = np.arange(w.size, dtype=np.uint32)
        with np.errstate(over="ignore"):  # uint32 wraparound is the point
            mixed = _mix32(w ^ (idx * _MIX_A) ^ np.uint32(ordinal + 1))
        acc ^= np.bitwise_xor.reduce(mixed) if w.size else np.uint32(0)
        acc = _mix32(acc ^ np.uint32(b.size))
    return int(acc)


def _edge_endpoints(row_offsets: np.ndarray, col_indices: np.ndarray):
    """(u_all, v_all): source/target of every directed CSR slot."""
    n = row_offsets.size - 1
    degrees = np.diff(row_offsets)
    u_all = np.repeat(np.arange(n, dtype=np.int64), degrees)
    return u_all, np.asarray(col_indices, dtype=np.int64)


def _valid_sources(rows: np.ndarray, n: int) -> np.ndarray:
    """(K, S) bool: which padded source slots are live — the reference
    loader's bounds contract (out-of-range sources are dropped, -1 is
    padding)."""
    rows = np.asarray(rows)
    return (rows >= 0) & (rows < n)


def reference_distances(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    rows: np.ndarray,
    endpoints=None,
) -> np.ndarray:
    """Untrusted audit recompute: (K, n) int32 distance-to-set fields
    for the padded query batch ``rows`` ((K, S) int32, -1 padding), by
    a batched host-side level sweep over the CSR — one vectorized
    (K, E) expansion per BFS level for the WHOLE batch, no JAX, no
    shared code with any engine's device path.  "Untrusted" is fine:
    :func:`certify_distances` validates the result before anything is
    compared against it.  ``endpoints`` takes a precomputed
    :func:`_edge_endpoints` pair (the auditor closure caches it)."""
    row_offsets = np.asarray(row_offsets)
    n = row_offsets.size - 1
    u_all, v_all = (
        _edge_endpoints(row_offsets, col_indices)
        if endpoints is None else endpoints
    )
    rows = np.asarray(rows)
    if rows.ndim == 1:
        rows = rows[None, :]
    k_total = rows.shape[0]
    # (n, K) internal layout: the per-level gather becomes an axis-0
    # take of contiguous K-wide rows — numpy's fast fancy-index path —
    # instead of K strided axis-1 gathers.
    dist_t = np.full((n, k_total), -1, dtype=np.int32)
    live = _valid_sources(rows, n)
    k_idx = np.repeat(np.arange(k_total), live.sum(axis=1))
    dist_t[rows[live], k_idx] = 0
    if v_all.size == 0:
        return np.ascontiguousarray(dist_t.T)  # no edges: sources only
    # Pull sweep over K bit-planes (the host-side analogue of the
    # bitbell engines' packing, arrived at independently so the audit
    # shares no formulation with the audited path): each vertex carries
    # ceil(K/64) uint64 words, one bit per query, so a level is ONE
    # contiguous axis-0 take plus ONE bitwise_or.reduceat — per-query
    # cost amortizes to a bit.  The gathered edge array carries one
    # zero pad row so a trailing empty row's start (== E) stays a valid
    # reduceat index WITHOUT clamping — clamping would truncate the
    # last non-empty row's segment; empty rows are masked out after the
    # reduction either way.
    starts = np.asarray(row_offsets[:-1], dtype=np.intp)
    empty = np.diff(row_offsets) == 0
    words = (k_total + 63) // 64
    pad = np.zeros((1, words), dtype=np.uint64)
    shifts = np.arange(64, dtype=np.uint64)
    frontier = np.zeros((n, words), dtype=np.uint64)
    seed_v, seed_k = (dist_t == 0).nonzero()
    np.bitwise_or.at(
        frontier,
        (seed_v, seed_k // 64),
        np.uint64(1) << (seed_k % 64).astype(np.uint64),
    )
    visited = frontier.copy()
    level = np.int32(0)
    while frontier.any():
        reach = np.bitwise_or.reduceat(
            np.concatenate([frontier[v_all], pad]), starts, axis=0
        )
        reach[empty] = 0
        new_bits = reach & ~visited
        hot = new_bits.any(axis=1)
        if not hot.any():
            break
        level += 1
        visited |= new_bits
        rows_hot = hot.nonzero()[0]
        mask = (
            ((new_bits[rows_hot, :, None] >> shifts) & np.uint64(1))
            .astype(bool)
            .reshape(rows_hot.size, words * 64)[:, :k_total]
        )
        block = dist_t[rows_hot]
        block[mask] = level
        dist_t[rows_hot] = block
        frontier = new_bits
    return np.ascontiguousarray(dist_t.T)


def certify_distances(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    rows: np.ndarray,
    dist: np.ndarray,
    endpoints=None,
) -> List[str]:
    """The O(E) certificate: check ``dist`` ((K, n) int) against the
    four BFS invariants for the padded query batch ``rows``.  Returns
    the failing invariant names ([] = ``dist`` IS the distance field).
    """
    row_offsets = np.asarray(row_offsets)
    n = row_offsets.size - 1
    u_all, v_all = (
        _edge_endpoints(row_offsets, col_indices)
        if endpoints is None else endpoints
    )
    rows = np.asarray(rows)
    if rows.ndim == 1:
        rows = rows[None, :]
    dist = np.asarray(dist)
    if dist.ndim == 1:
        dist = dist[None, :]
    k_total = rows.shape[0]
    live = _valid_sources(rows, n)
    failing: List[str] = []

    # canonical-unreached: unreached is exactly -1.  Every other
    # negative encodes the same ANSWER (f ignores negatives), which is
    # precisely how a bit flipped into an unreached slot would hide —
    # pinning the encoding closes that blind spot, so any single-bit
    # corruption of the field is detectable.
    if bool((dist < -1).any()):
        failing.append("canonical-unreached")

    # source-zero / zero-is-source: (K, n) source membership mask.
    is_source = np.zeros((k_total, n), dtype=bool)
    k_idx = np.repeat(np.arange(k_total), live.sum(axis=1))
    is_source[k_idx, rows[live]] = True
    if not bool((dist[is_source] == 0).all()):
        failing.append("source-zero")
    if bool(((dist == 0) & ~is_source).any()):
        failing.append("zero-is-source")

    # edge-relaxation + witness, one (E, K) pass in the same transposed
    # layout as the recompute sweep (axis-0 takes).  int16 halves the
    # gather traffic; the cast is gated on the WHOLE field (corrupt
    # values included) fitting well inside int16, so a flipped-to-
    # garbage entry can never wrap into a plausible value — out-of-
    # range fields keep the exact int32 path.
    if v_all.size == 0:
        if bool((dist >= 1).any()):
            failing.append("witness")  # reached depth >= 1 with no edges
        return failing
    d_t = np.ascontiguousarray(dist.T)
    if d_t.size and -2**14 <= d_t.min() and d_t.max() < 2**14:
        d_t = d_t.astype(np.int16)  # diff below stays in range
    du = d_t[u_all]
    dv = d_t[v_all]
    diff = dv - du  # |values| < 2^14, so the difference fits int16
    reached_u = du >= 0
    if bool((reached_u & ((dv < 0) | (diff > 1))).any()):
        failing.append("edge-relaxation")
    # witness[u, k] = some row-u slot's neighbor sits at dist[u] - 1
    # (same pad-row segment reduction as the recompute sweep — trailing
    # empty rows keep start == E valid without clamping into the last
    # non-empty row's segment; du >= 1 keeps a dv == -1 unreached
    # neighbor from "witnessing" a source).
    starts = np.asarray(row_offsets[:-1], dtype=np.intp)
    empty = np.diff(row_offsets) == 0
    witness = np.maximum.reduceat(
        np.concatenate(
            [(du >= 1) & (diff == -1),
             np.zeros((1, k_total), dtype=bool)]
        ),
        starts,
        axis=0,
    )
    witness[empty] = False
    if bool(((d_t >= 1) & ~witness).any()):
        failing.append("witness")
    return failing


def f_from_distances(dist: np.ndarray) -> np.ndarray:
    """The objective on a host distance field: F = sum of non-negative
    distances, int64 — the same contract as ``ops.objective.f_of_u``."""
    dist = np.asarray(dist)
    return np.where(dist >= 0, dist, 0).sum(axis=-1, dtype=np.int64)


def audit_f_values(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    rows: np.ndarray,
    f_claimed: np.ndarray,
    endpoints=None,
) -> List[str]:
    """End-to-end audit of a claimed F vector for the padded query
    batch ``rows``: recompute the distance fields, certify the
    recompute, compare F.  Returns failing invariant names ([] = the
    claimed output is certified correct)."""
    dist = reference_distances(
        row_offsets, col_indices, rows, endpoints=endpoints
    )
    failing = certify_distances(
        row_offsets, col_indices, rows, dist, endpoints=endpoints
    )
    f_ref = f_from_distances(dist)
    f_claimed = np.asarray(f_claimed, dtype=np.int64).reshape(f_ref.shape)
    if not bool(np.array_equal(f_ref, f_claimed)):
        failing.append("f-mismatch")
    return failing


def reference_weighted_distances(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    edge_weights: np.ndarray,
    rows: np.ndarray,
    endpoints=None,
) -> np.ndarray:
    """Untrusted weighted audit recompute: (K, n) int32 weighted
    distance-to-set fields by a vectorized host Jacobi Bellman-Ford
    sweep over the CSR — per pass, every row pulls
    ``min(dist[neighbor] + w)`` via one contiguous gather plus one
    ``minimum.reduceat``, iterated to fixpoint.  Deliberately a
    DIFFERENT formulation from the engines' bucketed delta-stepping
    (no buckets, no light/heavy split, no JAX): with positive costs
    both converge to the unique SSSP fixpoint, and
    :func:`certify_weighted_distances` validates this recompute before
    anything is compared against it, so the recompute stays untrusted.
    Each pass extends shortest paths by at least one edge, so the sweep
    terminates within n - 1 passes (far fewer in practice)."""
    row_offsets = np.asarray(row_offsets)
    n = row_offsets.size - 1
    _, v_all = (
        _edge_endpoints(row_offsets, col_indices)
        if endpoints is None else endpoints
    )
    w_all = np.asarray(edge_weights, dtype=np.int64)
    rows = np.asarray(rows)
    if rows.ndim == 1:
        rows = rows[None, :]
    k_total = rows.shape[0]
    # Same (n, K) transposed layout as the unit-cost sweep: the gather
    # is an axis-0 take of contiguous K-wide rows.
    dist_t = np.full((n, k_total), _W_INF, dtype=np.int64)
    live = _valid_sources(rows, n)
    k_idx = np.repeat(np.arange(k_total), live.sum(axis=1))
    dist_t[rows[live], k_idx] = 0
    if v_all.size and k_total:
        starts = np.asarray(row_offsets[:-1], dtype=np.intp)
        empty = np.diff(row_offsets) == 0
        pad = np.full((1, k_total), _W_INF, dtype=np.int64)
        w_col = w_all[:, None]
        for _ in range(max(1, n - 1)):
            offers = np.minimum.reduceat(
                np.concatenate([dist_t[v_all] + w_col, pad]),
                starts,
                axis=0,
            )
            offers[empty] = _W_INF
            new = np.minimum(dist_t, offers)
            if np.array_equal(new, dist_t):
                break
            dist_t = new
    out = np.where(dist_t >= _W_INF, np.int64(-1), dist_t)
    return np.ascontiguousarray(out.T).astype(np.int32)


def certify_weighted_distances(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    edge_weights: np.ndarray,
    rows: np.ndarray,
    dist: np.ndarray,
    endpoints=None,
) -> List[str]:
    """The O(E) weighted certificate: check ``dist`` ((K, n) int)
    against :data:`WEIGHTED_INVARIANTS` for the padded query batch
    ``rows``.  Returns the failing invariant names ([] = ``dist`` IS
    the weighted distance field — positive costs make it unique)."""
    row_offsets = np.asarray(row_offsets)
    n = row_offsets.size - 1
    u_all, v_all = (
        _edge_endpoints(row_offsets, col_indices)
        if endpoints is None else endpoints
    )
    w_all = np.asarray(edge_weights, dtype=np.int64)
    rows = np.asarray(rows)
    if rows.ndim == 1:
        rows = rows[None, :]
    dist = np.asarray(dist)
    if dist.ndim == 1:
        dist = dist[None, :]
    k_total = rows.shape[0]
    live = _valid_sources(rows, n)
    failing: List[str] = []

    # canonical-unreached: same encoding pin as the unit-cost
    # certificate — unreached is exactly -1, nothing else.
    if bool((dist < -1).any()):
        failing.append("canonical-unreached")

    is_source = np.zeros((k_total, n), dtype=bool)
    k_idx = np.repeat(np.arange(k_total), live.sum(axis=1))
    is_source[k_idx, rows[live]] = True
    if not bool((dist[is_source] == 0).all()):
        failing.append("source-zero")
    if bool(((dist == 0) & ~is_source).any()):
        failing.append("zero-is-source")

    if v_all.size == 0 or k_total == 0:
        if bool((dist >= 1).any()):
            failing.append("weighted-witness")  # reached with no edges
        return failing
    # Both checks in one (E, K) transposed pass.  int64 throughout:
    # du + w must never wrap, whatever garbage a flipped bit wrote.
    d_t = np.ascontiguousarray(dist.T).astype(np.int64)
    du = d_t[u_all]
    dv = d_t[v_all]
    w_col = w_all[:, None]
    reached_u = du >= 0
    # Triangle inequality over every directed slot; a reached ->
    # unreached slot is a violation by itself.
    if bool((reached_u & ((dv < 0) | (dv > du + w_col))).any()):
        failing.append("weighted-relaxation")
    # weighted-witness[u, k]: some slot in u's row has a reached
    # neighbor v with dv + w == du — a tight predecessor (both slot
    # directions carry the record's cost, so checking from the row-
    # owner side covers every vertex).  Same pad-row reduceat as the
    # unit-cost certificate.
    starts = np.asarray(row_offsets[:-1], dtype=np.intp)
    empty = np.diff(row_offsets) == 0
    witness = np.maximum.reduceat(
        np.concatenate(
            [(du >= 1) & (dv >= 0) & (dv + w_col == du),
             np.zeros((1, k_total), dtype=bool)]
        ),
        starts,
        axis=0,
    )
    witness[empty] = False
    if bool(((d_t >= 1) & ~witness).any()):
        failing.append("weighted-witness")
    return failing


def audit_weighted_f_values(
    row_offsets: np.ndarray,
    col_indices: np.ndarray,
    edge_weights: np.ndarray,
    rows: np.ndarray,
    f_claimed: np.ndarray,
    endpoints=None,
) -> List[str]:
    """End-to-end weighted audit of a claimed F vector: recompute the
    weighted distance fields, certify the recompute, compare F.
    Returns failing invariant names ([] = certified correct)."""
    dist = reference_weighted_distances(
        row_offsets, col_indices, edge_weights, rows, endpoints=endpoints
    )
    failing = certify_weighted_distances(
        row_offsets, col_indices, edge_weights, rows, dist,
        endpoints=endpoints,
    )
    f_ref = f_from_distances(dist)
    f_claimed = np.asarray(f_claimed, dtype=np.int64).reshape(f_ref.shape)
    if not bool(np.array_equal(f_ref, f_claimed)):
        failing.append("f-mismatch")
    return failing


def make_weighted_auditor(graph) -> Callable[[object, object], List[str]]:
    """The weighted twin of :func:`make_auditor`: a ChunkSupervisor
    auditor closure over one weighted host graph's CSR + cost buffers.
    Raises ValueError on a weightless graph — building a weighted
    auditor over a graph with no costs is a wiring bug, not a runtime
    condition."""
    if not getattr(graph, "has_weights", False):
        raise ValueError("make_weighted_auditor: graph has no edge_weights")
    row_offsets = np.asarray(graph.row_offsets)
    col_indices = np.asarray(graph.col_indices)
    edge_weights = np.asarray(graph.edge_weights)
    endpoints = _edge_endpoints(row_offsets, col_indices)

    def auditor(queries, f) -> List[str]:
        return audit_weighted_f_values(
            row_offsets,
            col_indices,
            edge_weights,
            np.asarray(queries),
            np.asarray(f),
            endpoints=endpoints,
        )

    return auditor


def make_auditor(graph) -> Callable[[object, object], List[str]]:
    """Build the :class:`..runtime.supervisor.ChunkSupervisor` auditor
    for one host graph (``models.csr.CSRGraph``): a closure
    ``auditor(queries, f) -> [failing invariants]`` over the graph's
    CSR buffers.  The edge-endpoint expansion is precomputed — one
    O(E) int64 buffer per graph, shared by every audited call."""
    row_offsets = np.asarray(graph.row_offsets)
    col_indices = np.asarray(graph.col_indices)
    endpoints = _edge_endpoints(row_offsets, col_indices)

    def auditor(queries, f) -> List[str]:
        return audit_f_values(
            row_offsets,
            col_indices,
            np.asarray(queries),
            np.asarray(f),
            endpoints=endpoints,
        )

    return auditor


# ---- per-plane digest trail (chunk/stream/megachunk boundaries) -----------
# Opt-in: the host drive loops record fold_digest(state) after every
# committed chunk while the trail is armed.  Two clean runs of the same
# program produce identical trails; a corrupted run's trail diverges at
# exactly the corrupted chunk — the localization tool behind the
# bitflip property tests and `msbfs verify`.
_TRAIL: Optional[List[int]] = None


def start_plane_trail() -> None:
    global _TRAIL
    _TRAIL = []


def stop_plane_trail() -> List[int]:
    global _TRAIL
    trail, _TRAIL = list(_TRAIL or ()), None
    return trail


def plane_trail() -> List[int]:
    return list(_TRAIL or ())


def trail_armed() -> bool:
    return _TRAIL is not None


def record_plane_digest(state) -> None:
    """One committed chunk's state digest.  ``state`` may be any array
    or sequence of arrays (a drive-loop carry)."""
    if _TRAIL is None:
        return
    if isinstance(state, (tuple, list)):
        _TRAIL.append(fold_digest(*state))
    else:
        _TRAIL.append(fold_digest(state))
