#!/usr/bin/env python3
"""Cross-round benchmark trajectory table + headline regression gate.

Every driver round leaves a ``BENCH_r<NN>.json`` record at the repo root
(``{n, cmd, rc, tail, parsed}`` — ``parsed`` is bench.py's last JSON
line: headline ``metric``/``value`` plus ``detail.sweep`` with one
``{value, error}`` row per BASELINE config).  Nothing reads them ACROSS
rounds, so a regression that lands between two TPU sessions — a config
that quietly got slower while the headline config held — only surfaces
when someone eyeballs two JSON blobs by hand.

This script is that cross-round read:

  1. TABLE — one row per config ever measured (plus the headline),
     one column per round, GTEPS-formatted, so the trajectory of every
     config is a single glance (``--table`` alone never gates).
  2. GATE — the headline config's latest measured value must be within
     ``--threshold`` (default 10%) of its best PRIOR round.  The
     comparison is per-CONFIG, not per-record-position: round records
     whose headline fell back to a different config (r06's sweep ran
     only the MXU configs, so its top-level value is config 6's) would
     otherwise "regress" by orders of magnitude against a different
     workload.  A config absent from the latest round is skipped with a
     warning — an unmeasured config is a coverage gap, not a measured
     regression.

Exit 0 when every comparable config holds; exit 1 with a per-config
report on any >threshold drop.  The final stdout line is one JSON
record (``{"rounds", "compared", "violations", ...}``) so the
perf-smoke trend row can consume it without re-parsing the table.

Deliberately jax-free: this runs as a perf-smoke row on every
``make test``, and parsing a handful of JSON files must never pay an
accelerator-runtime import.
"""

import argparse
import glob
import json
import os
import re
import sys

# Configs whose regressions gate (the headline family): config 2 is the
# BASELINE headline workload; the others each anchor a subsystem round.
# Diagnostic variants (2c, 7t, 7l, ...) ride the table but not the gate
# — they exist to explain the anchors, not to pin them.  7k / 7m are
# the round-20 lattice compositions (lowk byte planes on the
# streamed mesh; MXU tile matmul on the mesh).
GATED_CONFIGS = ("2", "4", "5", "6", "7", "7s", "7a", "7k", "7m", "8", "9")


def load_rounds(root):
    """[(round_number, parsed-record-or-None)] sorted by round, from the
    driver's BENCH_r*.json artifacts."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            rounds.append((int(m.group(1)), None))
            continue
        rounds.append((int(m.group(1)), rec.get("parsed") or None))
    return rounds


def config_values(parsed):
    """{config_id: value} for one round's parsed record: the per-config
    sweep rows, plus "headline" for the top-level value.  Rounds before
    sweep mode (r01-r04) only carry the headline."""
    out = {}
    if not parsed:
        return out
    if isinstance(parsed.get("value"), (int, float)):
        out["headline"] = parsed["value"]
    sweep = (parsed.get("detail") or {}).get("sweep") or {}
    for cfg, row in sweep.items():
        if isinstance(row, dict) and isinstance(
            row.get("value"), (int, float)
        ):
            out[cfg] = row["value"]
    return out


def _fmt(v):
    if v is None:
        return "-"
    if v >= 1e9:
        return f"{v / 1e9:.2f}G"
    if v >= 1e6:
        return f"{v / 1e6:.1f}M"
    if v >= 1e3:
        return f"{v / 1e3:.0f}k"
    return str(int(v))


def _config_order(cfg):
    # "headline" first, then BASELINE id order (numeric, then suffix).
    if cfg == "headline":
        return (0, 0, "")
    m = re.match(r"(\d+)(.*)", cfg)
    return (1, int(m.group(1)), m.group(2)) if m else (2, 0, cfg)


def trajectory(rounds):
    """(config ids in display order, {cfg: {round: value}})."""
    table = {}
    for rnum, parsed in rounds:
        for cfg, val in config_values(parsed).items():
            table.setdefault(cfg, {})[rnum] = val
    return sorted(table, key=_config_order), table


def print_table(rounds, configs, table, out=sys.stdout):
    rnums = [r for r, _ in rounds]
    head = ["config"] + [f"r{r:02d}" for r in rnums]
    rows = [
        [cfg] + [_fmt(table[cfg].get(r)) for r in rnums] for cfg in configs
    ]
    widths = [
        max(len(head[i]), *(len(row[i]) for row in rows)) if rows
        else len(head[i])
        for i in range(len(head))
    ]
    for line in [head] + rows:
        print(
            "  ".join(c.rjust(widths[i]) for i, c in enumerate(line)),
            file=out,
        )


def gate(rounds, table, threshold):
    """(compared, violations): per-config latest-vs-best-prior check on
    the gated anchors.  A config needs >= 2 measured rounds to compare;
    one measured round is a baseline being established, not a trend."""
    compared, violations = 0, []
    for cfg in GATED_CONFIGS:
        hist = sorted((table.get(cfg) or {}).items())
        if len(hist) < 2:
            continue
        (_, latest), prior = hist[-1], [v for _, v in hist[:-1]]
        best = max(prior)
        compared += 1
        if latest < best * (1.0 - threshold):
            violations.append(
                f"config {cfg}: r{hist[-1][0]:02d} {_fmt(latest)} is "
                f"{100 * (1 - latest / best):.1f}% below best prior "
                f"{_fmt(best)} (threshold {100 * threshold:.0f}%)"
            )
    return compared, violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json records (repo root)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="gated fractional drop vs best prior round (default 0.10)",
    )
    ap.add_argument(
        "--table",
        action="store_true",
        help="print the trajectory table only; never gate",
    )
    args = ap.parse_args(argv)

    rounds = load_rounds(args.root)
    configs, table = trajectory(rounds)
    if not rounds:
        print(f"trend: no BENCH_r*.json under {args.root}", file=sys.stderr)
        print(json.dumps({"rounds": 0, "compared": 0, "violations": 0}))
        return 0

    print_table(rounds, configs, table)
    if args.table:
        return 0

    compared, violations = gate(rounds, table, args.threshold)
    for v in violations:
        print("REGRESSION " + v, file=sys.stderr)
    missing = [
        cfg
        for cfg in GATED_CONFIGS
        if cfg in table and rounds[-1][0] not in table[cfg]
    ]
    if missing:
        print(
            "trend: not measured in latest round (coverage gap, not "
            "gated): " + ", ".join(missing),
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "rounds": len(rounds),
                "compared": compared,
                "violations": len(violations),
                "missing_latest": missing,
            }
        )
    )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
