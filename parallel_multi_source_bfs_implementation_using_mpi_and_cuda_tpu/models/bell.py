"""Bucketed hierarchical ELL ("BELL"): a scatter-free frontier-reduce layout.

Motivation (measured on TPU v5e): XLA lowers ``segment_max`` — the per-level
neighbor reduce of the flat CSR path — to a scatter, which runs two orders of
magnitude below HBM bandwidth on TPU.  The reference kernel's push-style
update (main.cu:30-33) is scatter-shaped too, so a faithful translation
inherits the same wall.  BELL restructures the whole per-level reduce as
*gathers + dense fixed-width reductions*, which TPUs execute at full vector
throughput:

* Each vertex's neighbor list is assigned to a **width bucket** (the
  smallest W in ``widths`` with deg <= W); its slots are padded to exactly W
  with a sentinel index pointing at an always-zero frontier row.  Per BFS
  level the bucket is one ``take`` (rows of the frontier matrix) plus one
  dense ``max``/``or`` over the W axis — no data-dependent control flow,
  no scatter.
* Vertices with deg > max(widths) ("hubs") are split into ceil(d/W_max)
  chunk rows; the chunk hits are reduced by a **second (recursively, L-th)
  bucketed level** whose rows gather from the previous level's output
  array.  Depth is ceil(log_Wmax(max_degree)), i.e. 2-3 levels for any real
  graph.
* The final per-vertex hit is a plain gather ``V[final_slot[v]]`` from the
  concatenation of all level outputs — again no scatter, and no vertex
  renumbering is needed.

Total gathered slots = sum of padded bucket rows ~= alpha * E with alpha
typically 1.2-1.8 on power-law graphs (reported as ``fill``).

The layout is built once on the host (vectorized NumPy, no per-edge Python
loops) and uploaded; it is the TPU analog of the reference's one-time device
CSR residency (main.cu:282-295).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .csr import CSRGraph

# Width ladder: dense 1..16 (degree-exact for the bulk of a power-law degree
# distribution) then ~1.3x geometric steps to the 256-wide hub chunk rows.
# Measured on RMAT-20 (edge_factor 16): fill 0.91 vs 0.70 for the coarse
# (2, 8, 32, 128) ladder — 24% fewer gathered rows per BFS level.
DEFAULT_WIDTHS = tuple(range(1, 17)) + (21, 27, 34, 44, 56, 72, 92, 118, 152, 196, 256)


def _bucket_rows(
    item_start: np.ndarray,  # (V,) int64: start of each owner's item range
    item_count: np.ndarray,  # (V,) int64: number of items per owner
    widths: Sequence[int],
    sentinel: int,
) -> Tuple[List[np.ndarray], np.ndarray, np.ndarray]:
    """Assign each owner's contiguous item range [start, start+count) to
    padded fixed-width rows.

    Returns (cols_per_bucket, row_owner_count, owner_first_row):
      * cols_per_bucket[b] is an (R_b, W_b) int64 array of item indices
        (padding = ``sentinel``);
      * rows are globally ordered bucket-by-bucket, and within a bucket by
        owner; ``owner_first_row[v]`` is the global row index of owner v's
        first row and ``row_owner_count[v]`` the number of rows it owns
        (consecutive).  Owners with count 0 get 0 rows.
    """
    v_total = item_count.shape[0]
    w_max = widths[-1]
    cols_per_bucket: List[np.ndarray] = []
    owner_first_row = np.zeros(v_total, dtype=np.int64)
    owner_rows = np.zeros(v_total, dtype=np.int64)
    row_base = 0
    prev_w = 0
    for w in widths:
        if w == w_max:
            sel = item_count > prev_w  # hubs fall into chunked W_max rows
            rows_per = -(-item_count // w)  # ceil
        else:
            sel = (item_count > prev_w) & (item_count <= w)
            rows_per = np.ones(v_total, dtype=np.int64)
        owners = np.nonzero(sel)[0]
        prev_w = w
        if owners.size == 0:
            cols_per_bucket.append(np.empty((0, w), dtype=np.int64))
            continue
        rpo = rows_per[owners]  # rows per selected owner
        r_b = int(rpo.sum())
        # Row r (bucket-local) belongs to owner owners[oidx[r]] and is that
        # owner's chunk number r - first[oidx[r]].
        first = np.zeros(owners.size + 1, dtype=np.int64)
        np.cumsum(rpo, out=first[1:])
        oidx = np.repeat(np.arange(owners.size, dtype=np.int64), rpo)
        chunk = np.arange(r_b, dtype=np.int64) - first[oidx]
        start = item_start[owners][oidx] + chunk * w
        remain = np.minimum(item_count[owners][oidx] - chunk * w, w)
        cols = start[:, None] + np.arange(w, dtype=np.int64)[None, :]
        cols[np.arange(w)[None, :] >= remain[:, None]] = sentinel
        cols_per_bucket.append(cols)
        owner_first_row[owners] = row_base + first[:-1]
        owner_rows[owners] = rpo
        row_base += r_b
    return cols_per_bucket, owner_rows, owner_first_row


@jax.tree_util.register_pytree_node_class
class BellGraph:
    """Device-resident BELL layout (see module docstring).

    Each forest level's bucket cols are stored as ONE flat int32 array
    (``level_cols[li]``, all buckets concatenated row-major) plus shape
    metadata (``level_shapes[li]`` = ((R_b, W_b), ...)).  A single array
    per level lets the per-level frontier gather run as one big take —
    measurably faster than per-bucket takes on v5e — WITHOUT a hoisted
    runtime concatenation keeping a second copy of every slot index live
    in HBM.  Indices address rows of the previous level's *extended*
    value array (the frontier for level 0), whose last row is an
    always-zero sentinel.  ``final_slot`` (n,) indexes the concatenation
    of all level outputs (+ trailing zero row) to yield per-vertex hits.
    The :attr:`levels` property reconstructs per-bucket views for
    host-side consumers (the sharded harmonizer, tests).
    """

    def __init__(
        self,
        level_cols,
        level_shapes,
        final_slot,
        n,
        n_pad,
        level_sizes,
        fill,
        sparse=None,
        sparse_weights=None,
    ):
        self.level_cols = list(level_cols)  # list[jax.Array (..., S_li) i32]
        self.level_shapes = tuple(tuple(s) for s in level_shapes)
        self.final_slot = final_slot  # (n,) int32 into concat of outputs
        self.n = int(n)
        self.n_pad = int(n_pad)
        self.level_sizes = tuple(level_sizes)  # rows per level (pre-concat)
        self.fill = float(fill)  # E / padded slot count (diagnostic)
        # Optional dedup CSR (item_start (n,), item_count (n,), item_vals
        # (E,), all int32): the push-side structure the hybrid engine's
        # frontier-sparse levels scatter through (ops.bitbell.sparse
        # expand).  None when not kept (e.g. sharded sub-layouts).
        self.sparse = sparse
        # Optional parallel cost array ((E,) int32) aligned with
        # ``sparse[2]`` (item_vals): the dedup CSR's per-slot edge cost,
        # min per parallel edge — the weighted/ subsystem's relaxation
        # seam.  Only present when the host CSR carries edge_weights and
        # the sparse CSR was kept.
        self.sparse_weights = sparse_weights

    @property
    def levels(self):
        """Per-bucket (…, R_b, W_b) views reconstructed from the flat
        per-level arrays (host-side/introspection convenience; the device
        gather path reads ``level_cols`` directly)."""
        out = []
        for flat, shapes in zip(self.level_cols, self.level_shapes):
            bucket = []
            off = 0
            lead = flat.shape[:-1]
            for r, w in shapes:
                seg = flat[..., off : off + r * w]
                bucket.append(seg.reshape(*lead, r, w))
                off += r * w
            out.append(bucket)
        return out

    @staticmethod
    def pack_level(cols_per_bucket):
        """(list of (..., R_b, W_b) arrays) -> (flat (..., S) array, shapes).
        The inverse of the :attr:`levels` property for one level."""
        shapes = tuple(c.shape[-2:] for c in cols_per_bucket)
        if not cols_per_bucket:
            return np.zeros((0,), dtype=np.int32), shapes
        lead = cols_per_bucket[0].shape[:-2]
        flats = [np.reshape(c, lead + (-1,)) for c in cols_per_bucket]
        return np.concatenate(flats, axis=-1), shapes

    @staticmethod
    def estimate_hbm_bytes(
        n: int, e: int, k: int = 64, vertex_shards: int = 1
    ) -> int:
        """Worst-case PER-CHIP device-memory footprint of a bit-plane run
        over this layout (measured structure on v5e; docs/PERF_NOTES.md
        "HBM ceiling"):

        * forest cols arrays: ~e/fill slots x 4 B (fill >= 0.7 floor);
        * per-level gather intermediate: slots x ceil(k/32) words x 4 B
          (XLA materializes the take before the OR-fold);
        * hybrid dedup CSR: (e + 2n) x 4 B (single chip only); the
          sharded engine instead carries its in-block push CSR — ~e/p
          neighbor slots plus a <= min(n, e/p)-entry source table of
          three int32 arrays per shard (parallel/sharded_bell.py
          build_push_halo);
        * bit planes (+ the hybrid's byte-lane scratch on one chip):
          n x words x 16 B (+ n x k_pad B) — NOT divided by vertex
          shards: the halo exchange reconstructs global planes each level
          (parallel/sharded_bell), so a shard's transients still span
          n_pad rows.

        ``k`` is padded to the engine's word multiple.  Only the
        edge-proportional terms shrink with ``vertex_shards``; used by the
        CLI to route graphs that exceed one chip onto the vertex-sharded
        engine instead of dying in an allocator error.
        tests/test_hbm_estimate.py pins the estimate against the actually
        constructed layouts (and against memory_stats on real TPU)."""
        k_pad = max(32, -(-k // 32) * 32)
        w = k_pad // 32
        # Fill floor is scale-dependent: measured RMAT fills are 0.34-0.50
        # below ~2^25 directed edges (padding overhead dominates the short
        # ladders of small graphs) and >= 0.7 from RMAT-18 up (0.766) —
        # the scales where routing decisions actually matter.  Small
        # graphs use the conservative floor; over-reserving them is
        # harmless since they fit either way.
        fill_floor = 0.7 if e >= (1 << 25) else 0.33
        slots = int(e / fill_floor) + 1
        per_shard_edges = (4 * slots + 4 * w * slots) // max(1, vertex_shards)
        if vertex_shards > 1:
            push_csr = (4 * e + 12 * min(n, e)) // vertex_shards
            return per_shard_edges + push_csr + 16 * w * n
        return per_shard_edges + 4 * (e + 2 * n) + n * (16 * w + k_pad)

    @staticmethod
    def default_min_bucket_rows(n: int, e: int) -> int:
        """Measured on v5e: pruning near-empty rungs trades padding fill for
        fewer per-bucket dispatches.  The overhead is fixed per bucket, so
        it dominates on smaller graphs (RMAT-18: 16384 was 17% faster than
        no pruning) while fill dominates on bigger ones (RMAT-20: 16384
        cost 3%, 65536 cost 13%) — scale down as the edge count grows; the
        n/4 cap keeps small graphs off the cliff where every rung merges
        into the max-width bucket and fill collapses."""
        return min(16384 if e < (1 << 24) else 2048, max(1, n // 4))

    @staticmethod
    def resolve_widths(
        widths: Sequence[int],
        degrees: np.ndarray,
        n: int,
        e: int,
        min_bucket_rows: Optional[int],
    ) -> Tuple[int, ...]:
        """Shared ladder policy for the single-chip and sharded builders:
        auto-prune (e-scaled threshold) only when ``widths`` is the default
        ladder — an explicitly chosen ladder is an API contract — unless the
        caller passes ``min_bucket_rows`` explicitly."""
        widths = tuple(sorted(widths))
        if min_bucket_rows is None:
            min_bucket_rows = (
                BellGraph.default_min_bucket_rows(n, e)
                if widths == tuple(sorted(DEFAULT_WIDTHS))
                else 0
            )
        if min_bucket_rows:
            widths = BellGraph.adaptive_widths(degrees, widths, min_bucket_rows)
        return widths

    @staticmethod
    def adaptive_widths(
        degrees: np.ndarray,
        widths: Sequence[int] = DEFAULT_WIDTHS,
        min_bucket_rows: int = 4096,
    ) -> Tuple[int, ...]:
        """Prune ladder rungs whose bucket would hold < min_bucket_rows
        owners (their owners pad up to the next kept width).  Fewer buckets
        = fewer gather/reduce ops per BFS level = faster XLA compile and
        lower per-level dispatch overhead, at a small fill cost; the
        histogram walk keeps every width that actually carries weight."""
        widths = sorted(widths)
        hist = np.bincount(
            np.clip(degrees, 0, widths[-1]), minlength=widths[-1] + 1
        )
        kept = []
        prev_w = 0
        pending = 0
        for w in widths[:-1]:
            pending += int(hist[prev_w + 1 : w + 1].sum())
            prev_w = w
            if pending >= min_bucket_rows:
                kept.append(w)
                pending = 0
        kept.append(widths[-1])  # hub chunk width always survives
        return tuple(kept)

    @staticmethod
    def from_host(
        g: CSRGraph,
        widths: Sequence[int] = DEFAULT_WIDTHS,
        dedup: bool = True,
        min_bucket_rows: Optional[int] = None,
        keep_sparse: bool = True,
        device: bool = True,
    ) -> "BellGraph":
        """Build the layout.  ``dedup`` drops duplicate neighbors and
        self-loops per vertex: the per-level hit is a *set* predicate ("is
        any neighbor in the frontier"), so removing repeats cannot change
        BFS distances or F(U) — it only shrinks the gather (the reference
        stores duplicates verbatim, main.cu:114-115, and its kernel
        likewise just wastes the repeated reads, main.cu:26-35).  Self-loop
        removal is safe because a frontier vertex is already visited and
        can never be newly reached by its own loop (main.cu:30-32).

        ``keep_sparse`` also uploads the dedup CSR itself (int32; skipped
        when E >= 2^31), enabling the hybrid engine's frontier-sparse
        levels; pass False to save the extra E+2n ints of HBM.

        ``device=False`` keeps every array host-side (NumPy, sparse
        dropped): the layout for the host-streamed engine
        (ops.streamed), whose forest must NEVER be committed to device
        memory — it is built precisely because it does not fit there."""
        n = g.n
        e = int(g.num_directed_edges)

        # ---- level 0: owners = vertices, items = CSR slots -> frontier ids.
        # Gathering from the frontier: item value array = frontier (n rows)
        # + sentinel zero row at index n.
        slot_weights = None
        if dedup and e:
            if g.has_weights:
                # Weighted dedup keeps the parallel cost array aligned
                # with the dedup slots (min cost per parallel edge) —
                # the weighted/ subsystem's relaxation data.
                _, item_vals, slot_weights, item_count = g.deduped_weighted()
            else:
                _, item_vals, item_count = g.deduped_pairs()
            item_start = np.zeros(n, dtype=np.int64)
            np.cumsum(item_count[:-1], out=item_start[1:])
        else:
            item_vals = np.asarray(g.col_indices, dtype=np.int64)
            item_start = np.asarray(g.row_offsets[:-1], dtype=np.int64)
            item_count = np.asarray(g.degrees, dtype=np.int64)
            if g.has_weights:
                slot_weights = np.asarray(g.edge_weights, dtype=np.int32)
        widths = BellGraph.resolve_widths(
            widths, item_count, n, e, min_bucket_rows
        )

        item_count_0 = item_count
        sparse = None
        sparse_weights = None
        if device and keep_sparse and n and item_vals.shape[0] < (1 << 31):
            sparse = (
                jnp.asarray(item_start.astype(np.int32)),
                jnp.asarray(item_count.astype(np.int32)),
                jnp.asarray(item_vals.astype(np.int32)),
            )
            if slot_weights is not None:
                sparse_weights = jnp.asarray(slot_weights.astype(np.int32))
        level_cols: List[jax.Array] = []
        level_shapes: List[tuple] = []
        level_sizes: List[int] = []
        padded_slots = 0
        # Global (cross-level) output offset bookkeeping for the final take:
        # outputs of all levels are concatenated in order.
        out_offset: List[int] = []

        from ..runtime import native_loader  # lazy: avoid import cycle

        first_row = None
        rows_per_owner = None
        walk: List[Tuple[np.ndarray, np.ndarray]] = []  # (rpo, fr) per level
        while True:
            # Sentinel slots point at the previous value array's always-zero
            # row: index n of the extended frontier for level 0, the
            # previous level's row count for deeper levels.
            prev_rows = n if not level_sizes else level_sizes[-1]
            native = native_loader.bell_level(
                item_start, item_count, item_vals, widths, prev_rows
            )
            if native is not None:
                # Fused native build: assignment + padded fill + value map
                # + sentinel fix in two passes writing the final int32
                # directly (runtime/loader.cpp msbfs_bell_assign/fill).
                flat, shapes, rows_per_owner, first_row = native
            else:
                cols_b, rows_per_owner, first_row = _bucket_rows(
                    item_start, item_count, widths, item_vals.shape[0]
                )
                # Map item indices -> value-array row ids (level 0:
                # frontier ids; deeper: previous-level output rows); the
                # sentinel item maps to the zero row.
                vals_ext = np.concatenate(
                    [item_vals, np.asarray([prev_rows], dtype=np.int64)]
                )
                flat, shapes = BellGraph.pack_level(
                    [vals_ext[cb].astype(np.int32) for cb in cols_b]
                )
            walk.append((rows_per_owner, first_row))
            level_rows = sum(r for r, _ in shapes)
            level_cols.append(
                jnp.asarray(flat)
                if device
                else np.asarray(flat, dtype=np.int32)
            )
            level_shapes.append(shapes)
            level_sizes.append(level_rows)
            padded_slots += sum(r * w for r, w in shapes)
            out_offset.append(sum(level_sizes[:-1]))

            if int(rows_per_owner.max(initial=0)) <= 1:
                break
            # Next level: owners unchanged, items = this level's output rows
            # (contiguous per owner).  Owners that are already down to one
            # row are done — zero their count so they get no deeper rows.
            item_vals = np.arange(level_rows, dtype=np.int64)
            item_start = first_row
            item_count = np.where(rows_per_owner == 1, 0, rows_per_owner)

        # Final slot per vertex: owners with >= 1 row finished with exactly
        # one row at the LAST level they appeared in.  Track per vertex the
        # level at which its row count became 1.
        # Re-walk the construction cheaply: a vertex with degree 0 never got
        # rows -> zero row.  Otherwise its terminal level is the first level
        # where its row count == 1.
        final_slot = np.full(n, -1, dtype=np.int64)
        done = np.asarray(g.degrees) == 0  # deg-0 -> global zero row (below)
        for li, (rpo, fr) in enumerate(walk):
            newly = (~done) & (rpo == 1)
            final_slot[newly] = out_offset[li] + fr[newly]
            done |= newly
        total_rows = sum(level_sizes)
        final_slot[final_slot < 0] = total_rows  # zero sentinel row

        return BellGraph(
            level_cols=level_cols,
            level_shapes=level_shapes,
            final_slot=(
                jnp.asarray(final_slot.astype(np.int32))
                if device
                else final_slot.astype(np.int32)
            ),
            n=n,
            n_pad=n,
            level_sizes=level_sizes,
            # fill counts level-0 slots only in the numerator (items actually
            # gathered from the frontier, post-dedup) over all padded slots.
            fill=int(np.sum(item_count_0)) / max(padded_slots, 1),
            sparse=sparse,
            sparse_weights=sparse_weights,
        )

    def expand_frontier(self, dist, level):
        from ..ops.bell import bell_expand  # lazy: models stays op-free

        return bell_expand(dist, level, self)

    def tree_flatten(self):
        aux = (
            self.level_shapes,
            self.n,
            self.n_pad,
            self.level_sizes,
            self.fill,
            self.sparse is not None,
            self.sparse_weights is not None,
        )
        sparse = tuple(self.sparse) if self.sparse is not None else ()
        weights = (
            (self.sparse_weights,) if self.sparse_weights is not None else ()
        )
        return (
            tuple(self.level_cols) + (self.final_slot,) + sparse + weights,
            aux,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        (
            level_shapes, n, n_pad, level_sizes, fill, has_sparse,
            has_weights,
        ) = aux
        children = list(children)
        sparse_weights = None
        if has_weights:
            sparse_weights = children.pop()
        sparse = None
        if has_sparse:
            sparse = tuple(children[-3:])
            children = children[:-3]
        final_slot = children.pop()
        return cls(
            children, level_shapes, final_slot, n, n_pad, level_sizes, fill,
            sparse, sparse_weights,
        )

    def __repr__(self):
        return (
            f"BellGraph(n={self.n}, levels={[s for s in self.level_sizes]}, "
            f"fill={self.fill:.2f})"
        )
