"""Pure-NumPy/Python oracle reimplementing the reference semantics
(independent of JAX), per the test strategy of SURVEY.md section 4(a).

Oracle behaviors mirror /root/reference/main.cu exactly:
* adjacency doubling with insertion order (main.cu:106-129);
* source bounds check s in [0, n) (main.cu:46-51);
* level-synchronous BFS from the multi-source frontier (main.cu:16-73);
* F(U) skipping unreached vertices (main.cu:75-89);
* argmin over valid entries, ties to lowest index (main.cu:379-397).
"""

from collections import deque
from typing import List, Sequence, Tuple

import numpy as np


def oracle_adjacency(n: int, edges: np.ndarray) -> List[List[int]]:
    adj: List[List[int]] = [[] for _ in range(n)]
    for u, v in np.asarray(edges):
        adj[int(u)].append(int(v))
        adj[int(v)].append(int(u))
    return adj


def oracle_csr(n: int, edges: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    adj = oracle_adjacency(n, edges)
    row_offsets = np.zeros(n + 1, dtype=np.int64)
    for i in range(n):
        row_offsets[i + 1] = row_offsets[i] + len(adj[i])
    col_indices = np.array(
        [v for row in adj for v in row], dtype=np.int32
    ) if row_offsets[-1] else np.zeros(0, dtype=np.int32)
    return row_offsets, col_indices


def oracle_bfs(n: int, edges: np.ndarray, sources: Sequence[int]) -> np.ndarray:
    adj = oracle_adjacency(n, edges)
    dist = np.full(n, -1, dtype=np.int64)
    q = deque()
    for s in sources:
        s = int(s)
        if 0 <= s < n and dist[s] != 0:
            dist[s] = 0
            q.append(s)
    while q:
        u = q.popleft()
        for v in adj[u]:
            if dist[v] == -1:
                dist[v] = dist[u] + 1
                q.append(v)
    return dist


def oracle_f(dist: np.ndarray) -> int:
    return int(dist[dist >= 0].sum())


def oracle_dijkstra(
    n: int,
    edges: np.ndarray,
    weights: np.ndarray,
    sources: Sequence[int],
) -> np.ndarray:
    """Weighted distance-to-set by textbook lazy-deletion Dijkstra over
    the same undirected adjacency as :func:`oracle_bfs` — the weighted
    subsystem's independent oracle (no buckets, no JAX, no vectorized
    sweeps).  Unreached is -1, matching the BFS encoding."""
    import heapq

    adj: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
    for (u, v), w in zip(np.asarray(edges), np.asarray(weights)):
        adj[int(u)].append((int(v), int(w)))
        adj[int(v)].append((int(u), int(w)))
    dist = np.full(n, -1, dtype=np.int64)
    heap = []
    for s in sources:
        s = int(s)
        if 0 <= s < n and dist[s] != 0:
            dist[s] = 0
            heapq.heappush(heap, (0, s))
    while heap:
        d, u = heapq.heappop(heap)
        if d != dist[u]:
            continue  # stale entry: u settled cheaper already
        for v, w in adj[u]:
            nd = d + w
            if dist[v] < 0 or nd < dist[v]:
                dist[v] = nd
                heapq.heappush(heap, (nd, v))
    return dist


def oracle_best(f_values: Sequence[int]) -> Tuple[int, int]:
    min_f, min_k = -1, -1
    for i, f in enumerate(f_values):
        if f >= 0:
            min_f, min_k = int(f), i
            break
    for i, f in enumerate(f_values):
        if 0 <= f < min_f:
            min_f, min_k = int(f), i
    return min_f, min_k
