"""Distributed layer: mesh bring-up, query scheduling, XLA collectives.

Replaces the reference's MPI runtime (SURVEY.md C7-C10): process bring-up
(MPI_Init, main.cu:197-201) becomes ``jax.distributed`` + a
``jax.sharding.Mesh``; the graph broadcast (main.cu:242-280) becomes a
replicated sharding; the round-robin query assignment (main.cu:303-307)
becomes a cyclic reshape sharded over the ``'q'`` mesh axis; the
Gather/Gatherv of (q, F) pairs with a custom struct datatype
(main.cu:324-368) becomes a fixed-shape pmax merge of a (K,) int64 array —
SPMD static shapes replace the ragged wire format.
"""

from .mesh import make_mesh, default_mesh
from .scheduler import cyclic_assignment, cyclic_grid, QUERY_AXIS
from .distributed import DistributedEngine

__all__ = [
    "make_mesh",
    "default_mesh",
    "cyclic_assignment",
    "cyclic_grid",
    "QUERY_AXIS",
    "DistributedEngine",
]
