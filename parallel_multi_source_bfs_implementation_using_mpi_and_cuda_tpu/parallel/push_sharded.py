"""Owner-partitioned push BFS over the 'v' mesh axis (round 4).

The missing scale story this module closes: a road-class graph too big for
one chip's HBM.  The vertex-sharded pull engines (parallel.sharded_csr /
parallel.sharded_bell) handle the capacity, but every level still gathers
the shard's whole edge partition — O(D * E / p) work per shard on a
diameter-D graph, thousands of nearly-empty passes on road networks.  The
single-chip push engine (ops.push) is work-optimal but replicates the
adjacency (as does its query-sharded twin, parallel.push_dist — deliberate
there, matching the reference's broadcast model, main.cu:242-280).

This engine is the intersection: the adjacency is PARTITIONED by owner
(shard b holds only rows [b*L, (b+1)*L)), each shard advances a compacted
frontier queue over its OWN rows for all K bit-packed queries at once, and
per level the shards exchange only the BOUNDARY discoveries — candidates
whose owner is another shard — as compacted (global id, query words)
pairs over one 'v'-axis ``all_gather`` (the same pair wire format as the
sparse halo in parallel.sharded_bell).  Per-level cost is proportional to
the wavefront, not the edge partition:

  * gather:   (C, w) own-frontier adjacency rows (C = frontier capacity,
    w = max degree — the road-class width cap of ops.push);
  * scatter:  in-block candidates land directly in the shard's own hit
    planes (byte-lane scatter-max = bitwise OR, the well-defined form of
    the reference kernel's benign write race, main.cu:30-33);
  * exchange: p * B * 4 * (1 + W) bytes of boundary pairs (B = boundary
    budget) — for contiguous range partitions of road graphs the boundary
    is the cut between blocks, orders of magnitude below E/p.

Capacities are static shapes.  Like ops.push, results are NEVER silently
truncated: the loop tracks the peak own-frontier and boundary counts
(pmax over the mesh), and the engine re-runs at a grown capacity when a
dispatch overflowed (one discarded run + one recompile, worst case
capacity = L and boundary = C * w, both always sufficient).

Semantics are the reference's exactly (main.cu:16-89): source bounds
check, level-synchronous expansion, unreached vertices excluded from
F(U); results merge over ('q', 'v') with the same Gatherv+argmin contract
(main.cu:324-397) as every other distributed engine.
"""

from __future__ import annotations

import sys
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.csr import CSRGraph
from ..ops.engine import QueryEngineBase
from ..ops.push import (
    DEFAULT_MAX_WIDTH,
    compact_frontier_planes,
    compact_indices,
)
from ..ops.bitbell import (
    pack_byte_planes,
    pack_queries,
    unpack_byte_planes,
    unpack_counts,
)
from .distributed import _distributed_bitbell_finish, _pad_qblock
from .mesh import QUERY_AXIS, VERTEX_AXIS
from ..utils.timing import record_dispatch
from .scheduler import shard_queries


def build_sharded_adjacency(
    g: CSRGraph, p: int, max_width: int = DEFAULT_MAX_WIDTH
) -> Tuple[jax.Array, int, int, int]:
    """Partition ``g`` into ``p`` contiguous vertex blocks of length L and
    build the stacked (p, L + 1, w) width-padded own-row tables.

    Neighbor values are GLOBAL vertex ids (sentinel n_pad pads); row L of
    every shard is all-sentinel — the landing pad for padded frontier
    slots, exactly like ops.push.PaddedAdjacency's row n.  Duplicate
    neighbors and self-loops are dropped (set semantics, cannot change BFS
    distances or F(U)).  Raises ValueError when the graph's max degree
    exceeds ``max_width`` — the engine targets the road-network class.

    Returns (stacked rows, L, n_pad, w).
    """
    n = g.n
    L = -(-max(n, 1) // p)
    n_pad = p * L
    u, v, deg = g.deduped_pairs()
    w = int(deg.max()) if n and deg.size else 0
    w = max(w, 1)
    if w > max_width:
        raise ValueError(
            f"max degree {w} exceeds width cap {max_width}: the "
            "owner-partitioned push engine targets low-degree "
            "(road-class) graphs; use the sharded bitbell engine instead"
        )
    # Fill the (p, L+1, w) stacked layout DIRECTLY (one sentinel-filled
    # allocation, rows scattered via (owner block, local row)): no
    # intermediate (n_pad, w) table or per-block copies — peak host
    # memory is one padded table, which matters because this engine
    # exists for graphs too big for a chip.  It stays a HOST array: the
    # constructor device_puts it with the 'v' NamedSharding directly, so
    # the full table is never resident on one chip either.
    stacked = np.full((p, L + 1, w), n_pad, dtype=np.int32)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=offs[1:])
    col = np.arange(u.size, dtype=np.int64) - offs[u]
    stacked[u // L, u % L, col] = v.astype(np.int32)
    return stacked, L, n_pad, w


def default_capacity(n_pad: int, block: int) -> int:
    """Auto own-frontier capacity per shard.  A road wavefront can live
    entirely inside one shard, so size from the GLOBAL vertex count like
    ops.push (8*sqrt(n), floor 2048), capped at the block length (always
    sufficient)."""
    return int(min(max(block, 1), max(2048, 8 * int(max(n_pad, 1) ** 0.5))))


def default_boundary(capacity: int, width: int) -> int:
    """Auto boundary-pair budget per shard.  Contiguous range partitions
    of road-class graphs cut few edges per wavefront, so start well below
    the worst case (capacity * width, always sufficient) and let the
    overflow protocol grow on demand."""
    return int(min(capacity * width, max(1024, capacity // 2)))


def _push_level(adj_own, visited_own, frontier_own, block, n_pad, cap, bnd):
    """One owner-partitioned push level inside shard_map.

    Returns (new_own (L, W) planes, own-frontier rows this level, boundary
    candidates this level) — the counts feed the overflow tracking.
    """
    w_words = frontier_own.shape[1]
    me = lax.axis_index(VERTEX_AXIS)
    lo = me * block
    # Compact the own frontier: local row ids (sentinel `block` -> the
    # adjacency's landing-pad row) and their query words.
    own_rows, ids, valid, words = compact_frontier_planes(
        frontier_own, cap, block
    )
    # Gather the frontier rows' neighbors: (C, w) GLOBAL ids.  Padded
    # slots hit row `block` (all n_pad) and drop everywhere below.
    nbrs = jnp.take(adj_own, ids, axis=0)
    c, w_deg = nbrs.shape
    flat_dst = nbrs.reshape(-1)  # (C*w,)
    flat_words = jnp.broadcast_to(
        words[:, None, :], (c, w_deg, w_words)
    ).reshape(c * w_deg, w_words)
    src_bytes = unpack_byte_planes(flat_words)  # (C*w, K) 0/1 bytes
    # In-block candidates scatter straight into the own hit planes.
    local_dst = flat_dst - lo
    in_block = (local_dst >= 0) & (local_dst < block)
    hit_bytes = (
        jnp.zeros((block + 1, src_bytes.shape[1]), jnp.uint8)
        .at[jnp.where(in_block, local_dst, block)]
        .max(src_bytes)
    )
    # Boundary candidates (another shard owns them): compact to (B,) pairs
    # and exchange over 'v'.  Sentinel-padded slots (dst == n_pad) are not
    # boundary; receivers drop pairs outside their block.
    is_boundary = (flat_dst < n_pad) & ~in_block
    bcount = jnp.sum(is_boundary, dtype=jnp.int32)
    bslots = compact_indices(is_boundary, bnd, fill_value=c * w_deg)
    bvalid = bslots < c * w_deg
    safe = jnp.minimum(bslots, c * w_deg - 1)
    bdst = jnp.where(bvalid, jnp.take(flat_dst, safe), n_pad)
    # Exchange PACKED words — p * B * 4 * (1 + W) bytes on the wire, the
    # sparse halo's pair format — and unpack to byte lanes on receive.
    bwords = jnp.where(
        bvalid[:, None], jnp.take(flat_words, safe, axis=0), jnp.uint32(0)
    )
    all_dst = lax.all_gather(bdst, VERTEX_AXIS).reshape(-1)  # (p*B,)
    all_words = lax.all_gather(bwords, VERTEX_AXIS).reshape(-1, w_words)
    recv_local = all_dst - lo
    recv_mine = (recv_local >= 0) & (recv_local < block)
    hit_bytes = hit_bytes.at[jnp.where(recv_mine, recv_local, block)].max(
        unpack_byte_planes(all_words)
    )
    hits_own = pack_byte_planes(hit_bytes[:block])
    return hits_own & ~visited_own, own_rows, bcount


@partial(jax.jit, static_argnames=("mesh", "block", "n_pad"))
def _sharded_push_init(
    mesh: Mesh, query_grid: jax.Array, block: int, n_pad: int
):
    """Per-(q,v)-shard loop carries: own-block (L, W) planes sharded over
    ('v', 'q'), per-q-shard counter rows, and the two replicated peak
    counters (own-frontier rows / boundary candidates) at zero."""

    def shard_body(qblock):
        qblock, _ = _pad_qblock(qblock)
        frontier0 = pack_queries(n_pad, qblock)
        counts0 = unpack_counts(frontier0)
        me = lax.axis_index(VERTEX_AXIS)
        own0 = lax.dynamic_slice_in_dim(frontier0, me * block, block, axis=0)
        return (
            own0,  # visited = sources
            own0,  # frontier
            (counts0.astype(jnp.int64) * 0)[None],
            jnp.where(counts0 > 0, 1, 0).astype(jnp.int32)[None],
            counts0[None],
            jnp.int32(0)[None],
            jnp.any(counts0 > 0)[None],
            jnp.zeros((), jnp.int32),  # peak own-frontier rows
            jnp.zeros((), jnp.int32),  # peak boundary candidates
        )

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(QUERY_AXIS),),
        out_specs=(P(VERTEX_AXIS, QUERY_AXIS),) * 2
        + (P(QUERY_AXIS),) * 5
        + (P(), P()),
    )(query_grid)


@partial(
    jax.jit,
    static_argnames=("mesh", "block", "n_pad", "cap", "bnd", "max_levels"),
)
def _sharded_push_chunk(
    mesh: Mesh,
    adj,  # (p, L+1, w) stacked own-row tables, sharded over 'v'
    carry,
    chunk,
    block: int,
    n_pad: int,
    cap: int,
    bnd: int,
    max_levels,
):
    """Advance every shard's carry by <= ``chunk`` push levels in one
    dispatch.  Discovery counts are a psum over 'v' of own-block counts
    (each vertex counts exactly once, on its owner), so every shard sees
    identical convergence state; the peak own-frontier/boundary counters
    are pmax'd so the host can detect truncation and re-run."""

    def shard_body(adj, v_own, f_own, f, lv, rc, level, upd, pk_f, pk_b):
        adj_own = adj[0]
        start = level[0]

        def cond(c):
            go = jnp.logical_and(c[6], c[5] < start + chunk)
            if max_levels is not None:
                go = jnp.logical_and(go, c[5] < max_levels)
            return go

        def body(c):
            visited, frontier, f, levels, reached, lvl, _, pf, pb = c
            new, own_rows, bcount = _push_level(
                adj_own, visited, frontier, block, n_pad, cap, bnd
            )
            counts = lax.psum(unpack_counts(new), VERTEX_AXIS)
            found = counts > 0
            dist = lvl + 1
            return (
                visited | new,
                new,
                f + counts.astype(jnp.int64) * dist.astype(jnp.int64),
                jnp.where(found, dist + 1, levels),
                reached + counts,
                lvl + 1,
                jnp.any(found),
                jnp.maximum(pf, own_rows),
                jnp.maximum(pb, bcount),
            )

        # The peak counters arrive replicated (P() specs) but the loop
        # body computes them from shard-varying values; align the carry's
        # varying-axes types up front (same concern bit_level_init's
        # ``cast`` handles for the bit-plane engines).
        vary = lambda x: lax.pcast(x, (QUERY_AXIS, VERTEX_AXIS), to="varying")
        out = lax.while_loop(
            cond,
            body,
            (
                v_own,
                f_own,
                f[0],
                lv[0],
                rc[0],
                level[0],
                upd[0],
                vary(pk_f),
                vary(pk_b),
            ),
        )
        axes = (QUERY_AXIS, VERTEX_AXIS)
        any_up = lax.pmax(out[6].astype(jnp.int32), axes)
        max_level = lax.pmax(out[5], axes)
        return (
            (out[0], out[1])
            + tuple(x[None] for x in out[2:7])
            + (lax.pmax(out[7], axes), lax.pmax(out[8], axes))
            + (any_up, max_level)
        )

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(VERTEX_AXIS),)
        + (P(VERTEX_AXIS, QUERY_AXIS),) * 2
        + (P(QUERY_AXIS),) * 5
        + (P(), P()),
        out_specs=(P(VERTEX_AXIS, QUERY_AXIS),) * 2
        + (P(QUERY_AXIS),) * 5
        + (P(), P())
        + (P(), P()),
    )(adj, *carry)


def sharded_push_run(
    mesh: Mesh,
    adj,
    query_grid: jax.Array,
    k: int,
    k_pad: int,
    w: int,
    block: int,
    n_pad: int,
    cap: int,
    bnd: int,
    max_levels,
    level_chunk: int,
):
    """Host-chunked owner-partitioned push over the full mesh.  Returns
    (f, levels, reached, peak_frontier, peak_boundary): the first three
    replicated (k_pad,) merged results, the peaks for the caller's
    overflow protocol (> cap / > bnd means this run was truncated and
    must be discarded)."""
    carry = _sharded_push_init(mesh, query_grid, block, n_pad)
    # np.int32, hoisted: an eager jnp scalar would be its own blocking
    # device commit EVERY iteration (utils.timing documents the floor).
    bound = np.int32(level_chunk)
    while True:
        *carry, any_up, max_level = _sharded_push_chunk(
            mesh,
            adj,
            tuple(carry),
            bound,
            block,
            n_pad,
            cap,
            bnd,
            max_levels,
        )
        record_dispatch()
        if not int(np.asarray(any_up)):
            break
        if max_levels is not None and int(np.asarray(max_level)) >= max_levels:
            break
    peak_f, peak_b = int(np.asarray(carry[7])), int(np.asarray(carry[8]))
    j = query_grid.shape[1]
    f, levels, reached = _distributed_bitbell_finish(
        mesh, carry[2], carry[3], carry[4], j, k, k_pad, w
    )
    return f, levels, reached, peak_f, peak_b


class ShardedPushEngine(QueryEngineBase):
    """Owner-partitioned work-optimal BFS: queries round-robin over 'q',
    adjacency partitioned over 'v', per-level boundary-pair exchange.

    ``capacity``/``boundary`` bound the per-shard compacted frontier and
    the per-shard boundary send (static shapes).  None = auto mode: start
    from wavefront-sized guesses (:func:`default_capacity` /
    :func:`default_boundary`); a run whose pmax'd peak exceeded either
    bound is DISCARDED and re-run at the measured need (ops.push's
    protocol — results are never silently truncated).  Explicit ints are
    hard bounds: overflow raises :class:`FrontierOverflow`.

    ``level_chunk`` bounds per-dispatch work (default 64 levels, the push
    engine's chunk default) — this engine exists for thousands-of-levels
    graphs, so the bound is always on.
    """

    CAPABILITIES = frozenset(
        {
            "query_sharded",
            "vertex_sharded",
            # Lattice axes: owner-partitioned word push on a 1D shard.
            "plane:word",
            "residency:hbm",
            "partition:1d",
            "kernel:xla",
        }
    )

    def __init__(
        self,
        mesh: Mesh,
        graph: CSRGraph,
        max_levels: Optional[int] = None,
        max_width: int = DEFAULT_MAX_WIDTH,
        capacity: Optional[int] = None,
        boundary: Optional[int] = None,
        level_chunk: Optional[int] = None,
    ):
        from ..ops.push import default_push_chunk

        self.mesh = mesh
        self.w = mesh.shape[QUERY_AXIS]
        self.p = mesh.shape[VERTEX_AXIS]
        self.n = graph.n
        stacked, self.block, self.n_pad, self.width = build_sharded_adjacency(
            graph, self.p, max_width
        )
        self.adj = jax.device_put(
            stacked, NamedSharding(mesh, P(VERTEX_AXIS))
        )
        self.max_levels = max_levels
        self.auto_capacity = capacity is None
        self.capacity = (
            default_capacity(self.n_pad, self.block)
            if capacity is None
            else int(capacity)
        )
        self.auto_boundary = boundary is None
        self.boundary = (
            default_boundary(self.capacity, self.width)
            if boundary is None
            else int(boundary)
        )
        from ..ops.bfs import validate_level_chunk

        self.level_chunk = (
            validate_level_chunk(level_chunk) or default_push_chunk()
        )
        self._peak_f = 0  # historical peaks (shrink guard, ops.push style)
        self._peak_b = 0
        self._level_warm_shapes = set()

    def _bounds_held(self, peak_f: int, peak_b: int) -> bool:
        """The never-silently-truncated contract: True when the run's
        pmax'd peaks fit the static bounds; otherwise grow (auto mode,
        caller re-runs) or raise (explicit hard bounds)."""
        from ..ops.push import FrontierOverflow

        ok_f, ok_b = peak_f <= self.capacity, peak_b <= self.boundary
        if ok_f and ok_b:
            self._peak_f = max(self._peak_f, peak_f)
            self._peak_b = max(self._peak_b, peak_b)
            return True
        if (not ok_f and not self.auto_capacity) or (
            not ok_b and not self.auto_boundary
        ):
            raise FrontierOverflow(
                f"sharded push overflow: a level needed frontier >= "
                f"{peak_f} (capacity={self.capacity}) or boundary >= "
                f"{peak_b} (boundary={self.boundary}); construct "
                "ShardedPushEngine with larger bounds"
            )
        if not ok_f:
            self.capacity = min(
                self.block, max(2 * self.capacity, 4 * peak_f)
            )
        if not ok_b:
            self.boundary = min(
                self.capacity * self.width,
                max(2 * self.boundary, 4 * peak_b),
            )
        print(
            "ShardedPushEngine: overflow (frontier "
            f"{peak_f}, boundary {peak_b}); re-running at "
            f"capacity={self.capacity}, boundary={self.boundary}",
            file=sys.stderr,
        )
        return False

    def _prologue(self, queries: np.ndarray):
        queries = np.asarray(queries)
        queries = np.where(
            (queries >= 0) & (queries < self.n), queries, -1
        )
        return shard_queries(self.mesh, queries, None)

    def _run(self, queries: np.ndarray):
        sharded, k, k_pad, _ = self._prologue(queries)
        while True:
            f, levels, reached, peak_f, peak_b = sharded_push_run(
                self.mesh,
                self.adj,
                sharded,
                k,
                k_pad,
                self.w,
                self.block,
                self.n_pad,
                self.capacity,
                self.boundary,
                self.max_levels,
                self.level_chunk,
            )
            if self._bounds_held(peak_f, peak_b):
                return f, levels, reached, k

    def level_stats(self, queries):
        """Per-level trace (MSBFS_STATS=2): the shared stepped driver
        (parallel.distributed.stepped_level_stats) over this engine's
        init/chunk programs at chunk=1; an overflowed trace is discarded
        and re-traced at the grown bounds, like :meth:`_run`."""
        from .distributed import stepped_level_stats

        sharded, k, k_pad, _ = self._prologue(queries)
        j = sharded.shape[1]
        while True:
            peaks = {}

            def init():
                return _sharded_push_init(
                    self.mesh, sharded, self.block, self.n_pad
                )

            def step(carry):
                *out, _, _ = _sharded_push_chunk(
                    self.mesh,
                    self.adj,
                    tuple(carry),
                    np.int32(1),
                    self.block,
                    self.n_pad,
                    self.capacity,
                    self.boundary,
                    self.max_levels,
                )
                peaks["fb"] = (out[7], out[8])
                return tuple(out)

            def finish(carry):
                return _distributed_bitbell_finish(
                    self.mesh, carry[2], carry[3], carry[4], j, k, k_pad,
                    self.w,
                )

            key = (np.asarray(queries).shape, self.capacity, self.boundary)
            out = stepped_level_stats(
                init, step, finish, k, self.max_levels,
                key in self._level_warm_shapes,
            )
            self._level_warm_shapes.add(key)
            peak_f, peak_b = (
                (int(np.asarray(x)) for x in peaks["fb"])
                if peaks
                else (0, 0)
            )
            if self._bounds_held(peak_f, peak_b):
                return out

    def f_values(self, queries) -> jax.Array:
        f, _, _, k = self._run(queries)
        return f[:k]

    def query_stats(self, queries):
        f, levels, reached, k = self._run(queries)
        return (
            np.asarray(levels)[:k].astype(np.int32),
            np.asarray(reached)[:k].astype(np.int32),
            np.asarray(f)[:k],
        )
