"""Packed query-major engine: oracle parity, chunking invariance, K padding."""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
    PackedEngine,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, edges, q)) for q in queries]


GRAPHS = {
    "gnm": generators.gnm_edges(140, 460, seed=101),
    "grid": generators.grid_edges(19, 7),
    "rmat": generators.rmat_edges(8, edge_factor=8, seed=102),
    "sparse_disconnected": generators.gnm_edges(180, 70, seed=103),
}


@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_packed_matches_oracle(name):
    n, edges = GRAPHS[name]
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 11, max_group=5, seed=104)
    queries[2] = np.zeros(0, dtype=np.int32)
    padded = pad_queries(queries)
    eng = PackedEngine(g.to_device())
    got = np.asarray(eng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


@pytest.mark.parametrize("chunks", [1, 2, 3, 7])
def test_edge_chunking_invariant(chunks):
    n, edges = GRAPHS["rmat"]
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 6, max_group=4, seed=105)
    padded = pad_queries(queries)
    eng = PackedEngine(g.to_device(), edge_chunks=chunks)
    got = np.asarray(eng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_k_not_aligned():
    n, edges = GRAPHS["gnm"]
    g = CSRGraph.from_edges(n, edges)
    for k in (1, 3, 8, 13):
        queries = generators.random_queries(n, k, max_group=3, seed=106 + k)
        padded = pad_queries(queries)
        eng = PackedEngine(g.to_device())
        got = np.asarray(eng.f_values(padded))
        np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))
        assert got.shape == (k,)


def test_packed_best_and_out_of_range_sources():
    n, edges = GRAPHS["grid"]
    g = CSRGraph.from_edges(n, edges)
    queries = [np.array([0, -1, n + 5], dtype=np.int32), np.array([n - 1])]
    padded = pad_queries(queries)
    eng = PackedEngine(g.to_device())
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(np.asarray(eng.f_values(padded)), want)
    assert eng.best(padded) == oracle_best(want)
