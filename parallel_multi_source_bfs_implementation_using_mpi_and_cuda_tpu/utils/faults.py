"""Deterministic fault injection for the resilient execution runtime.

The reference treats every failure as fatal (a corrupt byte or a lost
rank kills the whole batch, main.cu:95-99); growing toward a production
service needs every recovery path in :mod:`..runtime.supervisor` to be
*testable* — on the 8-device virtual CPU mesh, on every CI run, with no
real hardware misbehaving on cue.  This module is that test harness's
only moving part: a seeded, replayable plan of injected faults that the
runtime's seams consult at well-known sites.

Grammar (``MSBFS_FAULTS`` / :meth:`FaultPlan.parse`)::

    MSBFS_FAULTS="<kind>:<site>:<n>[,<kind>:<site>:<n>...]"

Each spec arms one fault that fires exactly once, on the ``n``-th trip
(1-based) of its site.  Sites are plain strings named by the seams:
``load_graph`` / ``load_query`` (the binary loaders, utils/io.py),
``device_put`` (query upload, parallel/scheduler.py) and ``dispatch``
(every supervised engine call, runtime/supervisor.py).  Kinds:

``io``         raise ``IOError`` at the site (unreadable file, lost NFS).
``corrupt``    raise ``ValueError`` (corrupt bytes past the header checks).
``oom``        raise a simulated ``RESOURCE_EXHAUSTED`` runtime error —
               classified as ``CapacityError`` so the supervisor steps
               down the routing ladder exactly as on a real TPU OOM.
``transient``  raise a simulated ``UNAVAILABLE`` error — classified as
               ``TransientError`` and retried with backoff.
``hang``       stall the site for ``MSBFS_FAULT_HANG`` seconds (default
               60) so the dispatch watchdog fires; the stalled thread
               then raises ``UNAVAILABLE`` and exits.
``chip``       site must be ``rank<r>``; trips on ``dispatch`` and raises
               a simulated chip loss carrying ``failed_ranks={r}`` —
               classified as ``DeviceError``, triggering survivor
               resharding.

Example: ``MSBFS_FAULTS="io:load_graph:1,oom:dispatch:2,hang:dispatch:3,
chip:rank1:1"``.  Trip counters are plain per-site integers, so a given
plan replays identically for a given call sequence; ``MSBFS_FAULT_SEED``
seeds the supervisor's backoff jitter (not this module) so whole
recovery traces replay too.  See docs/RESILIENCE.md.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

KINDS = ("io", "corrupt", "oom", "transient", "hang", "chip")

_RANK_RE = re.compile(r"rank(\d+)\Z")


class SimulatedResourceExhausted(RuntimeError):
    """Stands in for the XLA runtime's RESOURCE_EXHAUSTED error (the
    message carries the status name, which is what classification keys
    on — same as the real error's repr)."""


class SimulatedUnavailable(RuntimeError):
    """Stands in for a transient runtime error (UNAVAILABLE /
    DEADLINE_EXCEEDED family): succeeds if simply tried again."""


class SimulatedChipLoss(RuntimeError):
    """A virtual mesh rank disappearing mid-batch.  Carries the failed
    rank set so recovery can reshard onto the survivors."""

    def __init__(self, msg: str, failed_ranks):
        super().__init__(msg)
        self.failed_ranks = frozenset(int(r) for r in failed_ranks)


@dataclass
class FaultSpec:
    kind: str
    site: str
    at: int  # fires on the at-th trip of trip_site, 1-based
    rank: Optional[int] = None  # chip faults only
    fired: bool = False

    @property
    def trip_site(self) -> str:
        # Chips die during dispatches; the spec's site names WHICH rank.
        return "dispatch" if self.kind == "chip" else self.site


class FaultPlan:
    """An armed set of :class:`FaultSpec`, with per-site trip counters.

    Thread-safe: the dispatch seam runs inside the supervisor's watchdog
    worker thread, so counter updates take a lock (the fire itself —
    sleep + raise — happens outside it).
    """

    def __init__(self, specs, hang_seconds: float = 60.0):
        self.specs: List[FaultSpec] = list(specs)
        self.hang_seconds = float(hang_seconds)
        self.counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    # ---- construction -----------------------------------------------------
    @classmethod
    def parse(cls, text: str, hang_seconds: float = 60.0) -> "FaultPlan":
        """Parse the ``kind:site:n`` grammar; malformed specs fail loud
        (a typo'd fault plan silently arming nothing would make every
        "recovery works" test vacuous)."""
        specs = []
        for raw in text.split(","):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":")
            if len(parts) != 3:
                raise ValueError(
                    f"fault spec {raw!r}: want <kind>:<site>:<n>"
                )
            kind, site, n = parts
            if kind not in KINDS:
                raise ValueError(
                    f"fault spec {raw!r}: unknown kind {kind!r} "
                    f"(one of {', '.join(KINDS)})"
                )
            try:
                at = int(n)
            except ValueError:
                raise ValueError(f"fault spec {raw!r}: trip count {n!r} "
                                 "is not an integer") from None
            if at < 1:
                raise ValueError(f"fault spec {raw!r}: trip count must be >= 1")
            rank = None
            if kind == "chip":
                m = _RANK_RE.match(site)
                if not m:
                    raise ValueError(
                        f"fault spec {raw!r}: chip faults need site "
                        "rank<r> (e.g. chip:rank1:1)"
                    )
                rank = int(m.group(1))
            specs.append(FaultSpec(kind=kind, site=site, at=at, rank=rank))
        return cls(specs, hang_seconds=hang_seconds)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        """Plan from ``MSBFS_FAULTS`` (+ ``MSBFS_FAULT_HANG``), or None
        when unset/empty (the normal no-faults case)."""
        raw = os.environ.get("MSBFS_FAULTS", "").strip()
        if not raw:
            return None
        hang = 60.0
        env = os.environ.get("MSBFS_FAULT_HANG", "")
        if env:
            try:
                hang = float(env)
            except ValueError:
                pass  # malformed knob falls back, file-wide convention
        return cls.parse(raw, hang_seconds=hang)

    # ---- execution --------------------------------------------------------
    def reset(self) -> None:
        """Re-arm every spec and zero the counters (replay)."""
        with self._lock:
            self.counters.clear()
            for s in self.specs:
                s.fired = False

    def trip(self, site: str) -> None:
        """One execution of ``site``: increments its counter and fires
        any spec due at this count.  No-op when nothing is due."""
        with self._lock:
            count = self.counters.get(site, 0) + 1
            self.counters[site] = count
            due = [
                s
                for s in self.specs
                if s.trip_site == site and s.at == count and not s.fired
            ]
            for s in due:
                s.fired = True
        for s in due:  # outside the lock: hangs sleep, fires raise
            self._fire(s)

    def pending(self) -> List[FaultSpec]:
        with self._lock:
            return [s for s in self.specs if not s.fired]

    def _fire(self, s: FaultSpec) -> None:
        where = f"at {s.site} (trip {s.at})"
        if s.kind == "io":
            raise IOError(f"injected io fault {where}")
        if s.kind == "corrupt":
            raise ValueError(f"injected corrupt input {where}")
        if s.kind == "oom":
            raise SimulatedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected oom {where}"
            )
        if s.kind == "transient":
            raise SimulatedUnavailable(
                f"UNAVAILABLE: injected transient fault {where}"
            )
        if s.kind == "hang":
            time.sleep(self.hang_seconds)
            raise SimulatedUnavailable(
                f"UNAVAILABLE: injected hang {where} released after "
                f"{self.hang_seconds:g}s"
            )
        if s.kind == "chip":
            raise SimulatedChipLoss(
                f"injected chip loss: rank {s.rank} {where}", {s.rank}
            )
        raise AssertionError(f"unreachable kind {s.kind!r}")


# ---- process-wide active plan (the seams' lookup point) -------------------
_active: Optional[FaultPlan] = None


def activate(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` as the process-wide plan (None clears).  The CLI
    installs a fresh plan from the environment on every ``main()`` call,
    so repeated in-process runs never see a stale half-fired plan."""
    global _active
    _active = plan
    if plan is not None:
        plan.reset()


def active_plan() -> Optional[FaultPlan]:
    return _active


def trip(site: str) -> None:
    """Seam entry point: near-free when no plan is active."""
    if _active is not None:
        _active.trip(site)


class injected:
    """``with injected(plan):`` — scoped activation for tests."""

    def __init__(self, plan: Optional[FaultPlan]):
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> Optional[FaultPlan]:
        self._prev = _active
        activate(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        activate(self._prev)
