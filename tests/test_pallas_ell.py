"""ELL-slab layout + Pallas frontier kernel (interpret mode on CPU):
layout correctness and full-BFS oracle parity through the standard engine."""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    Engine,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.ell import (
    EllGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bfs import (
    multi_source_bfs,
)

from oracle import oracle_adjacency, oracle_best, oracle_bfs, oracle_f


def test_ell_layout_covers_all_slots():
    n, edges = generators.rmat_edges(7, edge_factor=8, seed=121)  # power-law
    g = CSRGraph.from_edges(n, edges)
    ell = EllGraph.from_host(g, width=8)
    cols = np.asarray(ell.cols).T  # (R, width)
    vrow = np.asarray(ell.vrow_vertex)
    adj = oracle_adjacency(n, edges)
    # Reconstruct per-vertex neighbor multisets from the slabs.
    rebuilt = [[] for _ in range(n)]
    for r in range(cols.shape[0]):
        v = int(vrow[r])
        if v == n:
            assert (cols[r] == n).all()  # padding rows are all-sentinel
            continue
        rebuilt[v].extend(int(c) for c in cols[r] if c != n)
    for v in range(n):
        assert sorted(rebuilt[v]) == sorted(adj[v])


def test_ell_high_degree_vertex_splits_rows():
    # Star: hub degree 40 with width 8 -> 5 virtual rows for the hub.
    edges = np.array([[0, i] for i in range(1, 41)], dtype=np.int32)
    g = CSRGraph.from_edges(41, edges)
    ell = EllGraph.from_host(g, width=8)
    vrow = np.asarray(ell.vrow_vertex)
    assert (vrow == 0).sum() == 5
    assert (vrow[vrow != 41] >= 0).all()


@pytest.mark.parametrize(
    "maker",
    [
        lambda: generators.gnm_edges(120, 400, seed=122),
        lambda: generators.grid_edges(17, 9),
        lambda: generators.rmat_edges(7, edge_factor=8, seed=123),
        lambda: generators.gnm_edges(200, 60, seed=124),  # sparse, isolated
    ],
)
@pytest.mark.parametrize("width", [4, 16])
def test_ell_bfs_matches_oracle(maker, width):
    n, edges = maker()
    ell = EllGraph.from_host(CSRGraph.from_edges(n, edges), width=width)
    rng = np.random.default_rng(125)
    sources = rng.integers(-1, n, size=5).astype(np.int32)
    dist = np.asarray(multi_source_bfs(ell, sources))
    np.testing.assert_array_equal(dist, oracle_bfs(n, edges, sources))


def test_ell_engine_end_to_end():
    n, edges = generators.gnm_edges(150, 500, seed=126)
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 7, max_group=4, seed=127)
    padded = pad_queries(queries)
    eng = Engine(EllGraph.from_host(g))
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want)
    assert eng.best(padded) == oracle_best(want)


def test_ell_tile_rows_not_kernel_aligned():
    # Regression: row padding smaller than the kernel tile (TILE_R=512) must
    # not drop tail virtual rows.
    n, edges = generators.gnm_edges(100, 300, seed=128)
    ell = EllGraph.from_host(CSRGraph.from_edges(n, edges), width=4, tile_rows=64)
    assert ell.num_vrows % 512 != 0  # actually exercises the pad path
    dist = np.asarray(multi_source_bfs(ell, np.array([0], dtype=np.int32)))
    np.testing.assert_array_equal(dist, oracle_bfs(n, edges, [0]))


def test_ell_empty_graph():
    g = CSRGraph.from_edges(5, np.zeros((0, 2), dtype=np.int32))
    ell = EllGraph.from_host(g, width=4)
    dist = np.asarray(multi_source_bfs(ell, np.array([2], dtype=np.int32)))
    want = np.full(5, -1)
    want[2] = 0
    np.testing.assert_array_equal(dist, want)
