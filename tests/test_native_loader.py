"""Native C++ loader parity with the NumPy loader (runtime/loader.cpp).

Builds the shared library on the fly if the toolchain is present; skips
cleanly otherwise (the framework must work unbuilt, NumPy fallback).
"""

import shutil
import subprocess

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
    native_loader,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    load_graph_bin,
    save_graph_bin,
)

from conftest import REPO_ROOT


@pytest.fixture(scope="module")
def built():
    if not native_loader.available():
        if shutil.which("g++") is None:
            pytest.skip("no g++ and librt_loader.so not built")
        subprocess.run(["make", "native"], cwd=REPO_ROOT, check=True)
        # Reset the module's negative cache from any earlier probe.
        native_loader._load_failed = False
        native_loader._lib = None
    assert native_loader.available()


def test_native_matches_numpy(built, tmp_path):
    n, edges = generators.gnm_edges(300, 1200, seed=61)
    path = tmp_path / "g.bin"
    save_graph_bin(path, n, edges)
    g_np = load_graph_bin(path, native=False)
    g_cc = load_graph_bin(path, native=True)
    assert (g_cc.n, g_cc.m) == (g_np.n, g_np.m)
    np.testing.assert_array_equal(g_cc.row_offsets, g_np.row_offsets)
    np.testing.assert_array_equal(g_cc.col_indices, g_np.col_indices)


def test_native_self_loops_and_dups(built, tmp_path):
    edges = np.array([[0, 0], [1, 2], [1, 2], [2, 1]], dtype=np.int32)
    path = tmp_path / "g.bin"
    save_graph_bin(path, 3, edges)
    g_np = load_graph_bin(path, native=False)
    g_cc = load_graph_bin(path, native=True)
    np.testing.assert_array_equal(g_cc.row_offsets, g_np.row_offsets)
    np.testing.assert_array_equal(g_cc.col_indices, g_np.col_indices)


def test_native_rejects_out_of_range_vertex(built, tmp_path):
    # The reference would index out of bounds (UB) on a bad vertex id
    # (main.cu:114); the native loader returns an error instead.
    path = tmp_path / "g.bin"
    save_graph_bin(path, 3, np.array([[0, 7]], dtype=np.int32))
    with pytest.raises(IOError):
        native_loader.load_graph_csr(str(path))


def test_native_truncated_file(built, tmp_path):
    import struct

    path = tmp_path / "g.bin"
    with open(path, "wb") as f:
        f.write(struct.pack("<iq", 4, 100))
    with pytest.raises(IOError):
        native_loader.load_graph_csr(str(path))


def test_native_dedup_rows_matches_numpy():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )

    if not native_loader.available():
        pytest.skip("native library not built")
    n = 40
    rng = np.random.default_rng(601)
    base = rng.integers(0, n, size=(120, 2)).astype(np.int64)
    edges = np.concatenate([base, base[:30], np.stack([np.arange(6)] * 2, 1)])
    g = CSRGraph.from_edges(n, edges)
    got = native_loader.dedup_rows(g.row_offsets, g.col_indices)
    assert got is not None
    v, deg = got
    # NumPy reference (the fallback path, forced)
    src = np.repeat(np.arange(n, dtype=np.int64), g.degrees.astype(np.int64))
    dst = g.col_indices.astype(np.int64)
    keep = src != dst
    pairs = np.unique(src[keep] * n + dst[keep])
    np.testing.assert_array_equal(v, pairs % n)
    np.testing.assert_array_equal(deg, np.bincount(pairs // n, minlength=n))


def test_csr_from_edges_matches_numpy_path():
    """The native in-memory CSR build must reproduce the NumPy argsort
    path bit-for-bit (same insertion-order adjacency, same offsets)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        pytest.skip("librt_loader.so not built")
    rng = np.random.default_rng(77)
    for n, m in ((1, 0), (5, 9), (200, 1000), (64, 64)):
        edges = rng.integers(0, n, size=(m, 2), dtype=np.int64)
        if m:
            edges[0] = (0, 0)  # self-loop record
            edges[-1] = edges[m // 2]  # duplicate record
        got = native_loader.csr_from_edges(n, edges)
        assert got is not None
        row_offsets, col_indices = got
        # Independent NumPy construction (the fallback path's algorithm).
        src = np.empty(2 * m, dtype=np.int64)
        dst = np.empty(2 * m, dtype=np.int32)
        src[0::2] = edges[:, 0]
        src[1::2] = edges[:, 1]
        dst[0::2] = edges[:, 1]
        dst[1::2] = edges[:, 0]
        counts = np.bincount(src, minlength=n).astype(np.int64)
        want_offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=want_offsets[1:])
        want_cols = dst[np.argsort(src, kind="stable")]
        np.testing.assert_array_equal(row_offsets, want_offsets)
        np.testing.assert_array_equal(col_indices, want_cols)


def test_csr_from_edges_bounds():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        pytest.skip("librt_loader.so not built")
    with pytest.raises(ValueError, match="out of range"):
        native_loader.csr_from_edges(4, np.asarray([[0, 9]], dtype=np.int64))


def test_native_bell_level_parity():
    """The fused native BELL level build (msbfs_bell_assign/fill) must
    reproduce the NumPy builder's arrays exactly — flat cols, shapes,
    rows_per_owner, first_row — across degree profiles including hubs
    (multi-row chunking), degree-0 owners, and empty ladders."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
        _bucket_rows,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        pytest.skip("native library not built")
    rng = np.random.default_rng(9)
    widths = (1, 2, 4, 8, 16)
    for trial in range(5):
        v = int(rng.integers(1, 60))
        item_count = rng.integers(0, 40, size=v).astype(np.int64)
        if trial == 0:
            item_count[:] = 0  # all-empty owners
        item_start = np.zeros(v, dtype=np.int64)
        np.cumsum(item_count[:-1], out=item_start[1:])
        total = int(item_count.sum())
        item_vals = rng.integers(0, 1000, size=total).astype(np.int64)
        prev_rows = 1000
        native = native_loader.bell_level(
            item_start, item_count, item_vals, widths, prev_rows
        )
        assert native is not None
        flat_n, shapes_n, rpo_n, fr_n = native
        cols_b, rpo, fr = _bucket_rows(item_start, item_count, widths, total)
        vals_ext = np.concatenate(
            [item_vals, np.asarray([prev_rows], dtype=np.int64)]
        )
        flat, shapes = BellGraph.pack_level(
            [vals_ext[cb].astype(np.int32) for cb in cols_b]
        )
        assert shapes_n == shapes
        np.testing.assert_array_equal(flat_n, flat)
        np.testing.assert_array_equal(rpo_n, rpo)
        np.testing.assert_array_equal(fr_n, fr)


def test_bell_from_host_native_vs_numpy_builder(monkeypatch):
    """End-to-end BellGraph.from_host parity: force the NumPy fallback and
    compare every layout leaf against the native-path build."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        pytest.skip("native library not built")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        CSRGraph,
    )

    n, edges = generators.rmat_edges(9, edge_factor=12, seed=77)
    g = CSRGraph.from_edges(n, edges)
    a = BellGraph.from_host(g)
    monkeypatch.setattr(native_loader, "bell_level", lambda *args: None)
    b = BellGraph.from_host(g)
    assert a.level_shapes == b.level_shapes
    assert a.level_sizes == b.level_sizes
    assert a.fill == b.fill
    for x, y in zip(a.level_cols, b.level_cols):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    np.testing.assert_array_equal(
        np.asarray(a.final_slot), np.asarray(b.final_slot)
    )


def test_native_rmat_edges_distribution():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        pytest.skip("native library not built")
    scale, m = 10, 1 << 14
    e1 = native_loader.rmat_edges(scale, m, 0.57, 0.19, 0.19, seed=5)
    e2 = native_loader.rmat_edges(scale, m, 0.57, 0.19, 0.19, seed=5)
    e3 = native_loader.rmat_edges(scale, m, 0.57, 0.19, 0.19, seed=6)
    np.testing.assert_array_equal(e1, e2)  # deterministic per seed
    assert not np.array_equal(e1, e3)
    assert e1.shape == (m, 2) and e1.dtype == np.int32
    assert e1.min() >= 0 and e1.max() < (1 << scale)
    # Power-law skew: the max degree far exceeds the mean (hub formation),
    # matching the NumPy generator's qualitative profile.
    deg = np.bincount(e1.ravel(), minlength=1 << scale)
    assert deg.max() > 8 * deg.mean()


def test_thread_count_invariance(built, monkeypatch):
    """Round 4: every parallelized pass (CSR build, dedup, BELL
    bucketing, R-MAT sampling) must produce BYTE-IDENTICAL output at any
    MSBFS_NATIVE_THREADS — the parallel decomposition preserves the
    serial insertion/assignment order by construction."""
    n, edges = generators.rmat_edges(11, edge_factor=16, seed=17, native=False)
    outs = []
    for t in ("1", "8"):
        monkeypatch.setenv("MSBFS_NATIVE_THREADS", t)
        ro, ci = native_loader.csr_from_edges(n, edges)
        dst, deg = native_loader.dedup_rows(ro, ci)
        e = native_loader.rmat_edges(10, 1 << 14, 0.57, 0.19, 0.19, seed=3)
        counts = np.maximum(deg, 0)
        start = np.zeros(n, dtype=np.int64)
        np.cumsum(counts[:-1], out=start[1:])
        bell = native_loader.bell_level(
            start, counts, dst, [4, 16, 64], sentinel_value=-1
        )
        outs.append((ro, ci, dst, deg, e, bell))
    a, b = outs
    for x, y in zip(a[:5], b[:5]):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(a[5], b[5]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_dedup_rows_nonzero_first_offset(built):
    """row_offsets[0] > 0 is valid at the C ABI (slots before the first
    row are simply not part of any row); the compaction must land block 0
    at output offset 0 (round-4 review caught the parallel version
    skipping block 0's relocation)."""
    row_offsets = np.array([1, 3, 4], dtype=np.int64)
    col_indices = np.array([99, 1, 1, 0], dtype=np.int32)  # slot 0 unused
    dst, deg = native_loader.dedup_rows(row_offsets, col_indices)
    np.testing.assert_array_equal(deg, [1, 1])
    np.testing.assert_array_equal(dst, [1, 0])


def test_gr_parse_matches_python(built, tmp_path, monkeypatch):
    """Native DIMACS .gr parse == Python line loop, including the
    canonicalization downstream, and invariant in the thread count."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_dimacs_gr,
        save_dimacs_gr,
    )

    n, edges = generators.road_edges(20, 14, seed=71)
    p = tmp_path / "road.gr"
    save_dimacs_gr(p, n, edges, comment="native-parity fixture")
    n_py, e_py = load_dimacs_gr(p, native=False)
    n_cc, e_cc = load_dimacs_gr(p, native=True)
    assert n_cc == n_py
    np.testing.assert_array_equal(e_cc, e_py)
    monkeypatch.setenv("MSBFS_NATIVE_THREADS", "3")
    n_t3, e_t3 = load_dimacs_gr(p, native=True)
    assert n_t3 == n_py
    np.testing.assert_array_equal(e_t3, e_py)


def test_gr_parse_errors_match_python_contract(built, tmp_path):
    """Native .gr errors keep the Python parser's fail-loud messages:
    missing header -> 'header', bad endpoint -> 'outside'."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_dimacs_gr,
    )

    p = tmp_path / "bad.gr"
    p.write_text("a 1 2 3\n")
    with pytest.raises(ValueError, match="header"):
        load_dimacs_gr(p, native=True)
    p.write_text("p sp 2 1\na 1 9 4\n")
    with pytest.raises(ValueError, match="outside"):
        load_dimacs_gr(p, native=True)
    # Comment/blank/weird lines are ignored like the Python loop; a
    # final arc line without a trailing newline still parses.
    p.write_text("c x\n\nq zz\np sp 3 2\na 1 2 9\na 2 3 9")
    n, e = load_dimacs_gr(p, native=True)
    assert n == 3 and e.tolist() == [[0, 1], [1, 2]]


def test_snap_parse_matches_python(built, tmp_path, monkeypatch):
    """Native SNAP edge-list parse == Python line loop (comments, blank
    lines, both-direction duplicates), thread-invariant."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_edgelist,
    )

    p = tmp_path / "snap.txt"
    rng = np.random.default_rng(81)
    pairs = rng.integers(0, 300, size=(900, 2))
    lines = ["# SNAP-ish header", "% alt comment", "   ", ""]
    lines += [f"{u} {v}" for u, v in pairs]
    lines += [f"{v}\t{u}" for u, v in pairs[:100]]  # tabs + reverse dups
    p.write_text("\n".join(lines) + "\n")
    n_py, e_py = load_edgelist(p, native=False)
    n_cc, e_cc = load_edgelist(p, native=True)
    assert n_cc == n_py
    np.testing.assert_array_equal(e_cc, e_py)
    monkeypatch.setenv("MSBFS_NATIVE_THREADS", "3")
    n_t3, e_t3 = load_edgelist(p, native=True)
    assert n_t3 == n_py
    np.testing.assert_array_equal(e_t3, e_py)


def test_snap_parse_errors(built, tmp_path):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_edgelist,
    )

    p = tmp_path / "bad.txt"
    p.write_text("# only comments\n\n")
    with pytest.raises(ValueError, match="no edges"):
        load_edgelist(p, native=True)
    p.write_text("1 2\njunk line\n")
    with pytest.raises(ValueError, match="malformed"):
        load_edgelist(p, native=True)
    # Final line without trailing newline still parses.
    p.write_text("# c\n3 4\n1 2")
    n, e = load_edgelist(p, native=True)
    assert n == 5 and e.tolist() == [[1, 2], [3, 4]]
