"""The rank-0 stdout report — the reference's CLI output contract.

Format reproduced verbatim from main.cu:403-414: fixed 9-decimal times, the
winning query reported 1-based (``minK + 1``, main.cu:409), and the literal
``GPU # : <numGPU> GPU`` line (the flag name is part of the public contract
even though the devices are TPU chips here).
"""

from __future__ import annotations


def format_report(
    graph_path: str,
    query_path: str,
    min_k: int,
    min_f: int,
    num_gpu: int,
    preprocessing_time: float,
    computation_time: float,
) -> str:
    return (
        f"Graph: {graph_path}\n"
        f"Query: {query_path}\n"
        f"Query number (k) with minimum F value: {min_k + 1}\n"
        f"Minimum F value: {min_f}\n"
        f"GPU # : {num_gpu} GPU\n"
        f"Preprocessing time: {preprocessing_time:.9f} s\n"
        f"Computation time: {computation_time:.9f} s\n"
    )


def format_server_stats(stats: dict) -> str:
    """Human-readable rendering of the serving daemon's ``stats`` verb
    (docs/SERVING.md) — the client CLI's --stats output.  The wire form
    is the JSON object itself; this is for eyeballs and smoke logs."""
    lines = [f"uptime: {stats.get('uptime_s', 0):.1f} s"]
    for name, g in sorted(stats.get("graphs", {}).items()):
        lines.append(
            f"graph {name}: v{g['version']} hash {g['hash']} "
            f"({g['n']} vertices, {g['directed_edges']} directed edges)"
        )
    q = stats.get("queue", {})
    lines.append(
        f"queue: depth {q.get('depth', 0)}/{q.get('capacity', 0)}, "
        f"rejected {q.get('rejected', 0)}, batches {q.get('batches', 0)}, "
        f"coalesced {q.get('coalesced', 0)}"
    )
    rc = stats.get("result_cache", {})
    lines.append(
        f"result cache: {rc.get('hits', 0)} hits / "
        f"{rc.get('misses', 0)} misses, size {rc.get('size', 0)}/"
        f"{rc.get('capacity', 0)}, evictions {rc.get('evictions', 0)}"
    )
    lines.append(
        f"requests: {stats.get('requests_total', 0)} total, "
        f"{stats.get('requests_failed', 0)} failed, "
        f"{stats.get('requests_shed', 0)} shed, "
        f"{stats.get('requests_quarantined', 0)} quarantined; "
        f"compiles: {stats.get('compiles_total', 0)}"
    )
    if stats.get("draining"):
        lines.append("state: DRAINING (refusing new work)")
    if stats.get("journal"):
        lines.append(f"journal: {stats['journal']}")
    for label, b in sorted(stats.get("buckets", {}).items()):
        lines.append(
            f"bucket {label}: {b['requests']} requests in {b['batches']} "
            f"batches, p50 {b['p50_ms']} ms, p95 {b['p95_ms']} ms, "
            f"p99 {b['p99_ms']} ms"
        )
    n_rec = len(stats.get("recovery_events", []))
    if n_rec:
        lines.append(f"recovery events: {n_rec} (see stats JSON)")
    return "\n".join(lines) + "\n"


def format_failure(err, recovery_events=()) -> str:
    """One-line failure report for the typed taxonomy (stderr; stdout
    stays reference-exact).  ``<class>: <msg> (exit <code>)`` plus a
    recovery-attempt count when the supervisor tried before giving up —
    docs/RESILIENCE.md documents the exit-code table."""
    tried = (
        f" after {len(recovery_events)} recovery attempt"
        f"{'s' if len(recovery_events) != 1 else ''}"
        if recovery_events
        else ""
    )
    return (
        f"msbfs: {type(err).__name__}: {err}{tried} "
        f"(exit {getattr(err, 'exit_code', 1)})\n"
    )
