"""Serving-runtime latency/throughput benchmark (docs/SERVING.md).

Boots the daemon in-process on a unix socket with a fabricated graph,
then measures the three costs the runtime is built to separate:

* cold  — first query of a shape bucket (pays the XLA compile);
* warm  — repeat same-bucket queries with distinct payloads
          (executable-cache hit, full BFS execution) → p50/p95/p99;
* cached — exact repeat payload (result-cache hit, no execution);

plus closed-loop throughput from several concurrent client
connections, exercising the micro-batcher's coalescing path.

Emits one line of JSON per metric on stdout in the BENCH_*.json style
({"metric", "value", "unit", "vs_baseline", "detail"});
``vs_baseline`` on the warm metric is the cold/warm ratio — the
amortisation the daemon exists to deliver.

Run::

    JAX_PLATFORMS=cpu python benchmarks/bench_serve.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WARM_QUERIES = int(os.environ.get("BENCH_SERVE_WARM", "60"))
CACHED_QUERIES = int(os.environ.get("BENCH_SERVE_CACHED", "30"))
CLIENTS = int(os.environ.get("BENCH_SERVE_CLIENTS", "4"))
PER_CLIENT = int(os.environ.get("BENCH_SERVE_PER_CLIENT", "25"))
N_VERTICES = int(os.environ.get("BENCH_SERVE_N", "20000"))
N_EDGES = int(os.environ.get("BENCH_SERVE_M", "80000"))
K, S = 8, 4  # per-request groups x ids: bucket 8x4 once coalesced


def _percentiles(samples_ms):
    xs = sorted(samples_ms)

    def pct(p):
        return xs[min(len(xs) - 1, int(round(p / 100.0 * len(xs) + 0.5)) - 1)]

    return {"p50_ms": pct(50), "p95_ms": pct(95), "p99_ms": pct(99)}


def main() -> int:
    import numpy as np

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E501
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (  # noqa: E501
        MsbfsClient,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.server import (  # noqa: E501
        MsbfsServer,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (  # noqa: E501
        save_graph_bin,
    )

    tmp = tempfile.TemporaryDirectory(prefix="msbfs_bench_serve_")
    gpath = os.path.join(tmp.name, "g.bin")
    n, edges = generators.gnm_edges(N_VERTICES, N_EDGES, seed=13)
    save_graph_bin(gpath, n, edges)
    addr = f"unix:{os.path.join(tmp.name, 'msbfs.sock')}"
    server = MsbfsServer(listen=addr, graphs={"bench": gpath})
    server.start()
    rng = np.random.default_rng(17)

    def fresh_query():
        return [[int(v) for v in rng.integers(0, n, size=S)] for _ in range(K)]

    try:
        with MsbfsClient(addr) as client:
            t0 = time.perf_counter()
            first = client.query(fresh_query(), graph="bench")
            cold_ms = (time.perf_counter() - t0) * 1e3
            assert first["compiled"], "first query must compile its bucket"

            warm_ms = []
            for _ in range(WARM_QUERIES):
                t0 = time.perf_counter()
                r = client.query(fresh_query(), graph="bench")
                warm_ms.append((time.perf_counter() - t0) * 1e3)
                assert not r["compiled"], "warm bucket must not recompile"

            repeat = fresh_query()
            client.query(repeat, graph="bench")  # populate the result cache
            cached_ms = []
            for _ in range(CACHED_QUERIES):
                t0 = time.perf_counter()
                r = client.query(repeat, graph="bench")
                cached_ms.append((time.perf_counter() - t0) * 1e3)
                assert r["cached"], "repeat payload must hit the result cache"

        # Closed-loop throughput: CLIENTS concurrent connections, each
        # issuing PER_CLIENT distinct queries back-to-back.  Concurrent
        # same-bucket arrivals coalesce inside the batching window.
        payloads = [[fresh_query() for _ in range(PER_CLIENT)]
                    for _ in range(CLIENTS)]
        batched_with = []
        errors = []

        def run_client(idx):
            try:
                with MsbfsClient(addr) as c:
                    for q in payloads[idx]:
                        batched_with.append(
                            c.query(q, graph="bench")["batched_with"]
                        )
            except Exception as exc:  # noqa: BLE001 — report, don't hang
                errors.append(repr(exc))

        threads = [
            threading.Thread(target=run_client, args=(i,), daemon=True)
            for i in range(CLIENTS)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0
        if errors:
            print(f"bench_serve: client errors: {errors[:3]}", file=sys.stderr)
            return 1
        qps = (CLIENTS * PER_CLIENT) / wall_s

        with MsbfsClient(addr) as client:
            stats = client.stats()
    finally:
        server.stop()
        tmp.cleanup()

    warm = _percentiles(warm_ms)
    cached = _percentiles(cached_ms)
    graph_tag = f"G(n={n}, m={len(edges)}), K={K}, S={S}"
    print(json.dumps({
        "metric": f"serve warm-bucket query latency p50, {graph_tag}",
        "value": round(warm["p50_ms"], 3),
        "unit": "ms",
        "vs_baseline": round(cold_ms / max(warm["p50_ms"], 1e-9), 4),
        "detail": {
            "baseline": "cold first query of the bucket (includes the XLA "
                        "compile the warm path amortises)",
            "cold_ms": round(cold_ms, 3),
            **{k: round(v, 3) for k, v in warm.items()},
            "queries": WARM_QUERIES,
        },
    }))
    print(json.dumps({
        "metric": f"serve result-cache hit latency p50, {graph_tag}",
        "value": round(cached["p50_ms"], 3),
        "unit": "ms",
        "vs_baseline": round(warm["p50_ms"] / max(cached["p50_ms"], 1e-9), 4),
        "detail": {
            "baseline": "warm-bucket executed query (p50)",
            **{k: round(v, 3) for k, v in cached.items()},
            "queries": CACHED_QUERIES,
        },
    }))
    print(json.dumps({
        "metric": f"serve closed-loop throughput, {CLIENTS} clients, "
                  f"{graph_tag}",
        "value": round(qps, 2),
        "unit": "queries/s",
        "detail": {
            "clients": CLIENTS,
            "queries": CLIENTS * PER_CLIENT,
            "wall_s": round(wall_s, 3),
            "coalesced_mean": round(
                sum(batched_with) / max(len(batched_with), 1), 3
            ),
            "compiles_total": stats["compiles_total"],
            "result_cache": stats["result_cache"],
            "queue_rejected": stats["queue"]["rejected"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
