"""Cross-replica graph sharding: the planner behind "Sharded graphs".

The fleet (serve/fleet.py) replicates WHOLE graphs onto single replicas;
the 2D mesh (parallel/partition2d.py) shards only within one process.
This module composes them at the fleet layer: a graph whose artifact
footprint exceeds ``MSBFS_SHARD_MAX_BYTES`` is planned into contiguous
ROW-RANGE shards — each an ordinary reference-format ``.bin`` artifact
(utils/io.py) carrying the full vertex space and exactly the adjacency
records of its own rows — placed on distinct fleet members through the
existing :class:`~.ring.PlacementRing` with ``MSBFS_SHARD_REPLICAS``
copies each.  The row split is edge-balanced via
:func:`~..parallel.partition2d.edge_balanced_row_splits` (the same
row-partition seam the 2D mesh tiler owns): a power-law graph split by
row COUNT would land the whole hub block in one shard, and a shard's
cost is its adjacency bytes, not its row count.

Because each shard is a plain registered graph under a derived name
(``<graph>#shard<i>``), every existing fleet mechanism applies verbatim:
rendezvous placement, digest-verified (re-)registration, journal replay
on replica restart, and the minimal-movement reheal when a member dies —
"re-replicate the lost shard" IS "reconcile the shard's ring owners",
recorded in the fleet manifest journal and epoch-bumped so in-flight
frames against the old placement are refusable (docs/SERVING.md
"Sharded graphs").

Failure posture: artifact writes hit the ``shard_write`` fault seam
(``disk_full:shard``, utils/faults.py) and convert ENOSPC/short-write
into the typed :class:`~..runtime.supervisor.StorageError` instead of
crashing the planner's daemon (docs/RESILIENCE.md "Disk exhaustion").
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..parallel.partition2d import edge_balanced_row_splits
from ..runtime.supervisor import InputError, StorageError
from ..utils import faults
from ..utils.io import GRAPH_HEADER, load_graph_bin, save_graph_bin

# Derived-name grammar: "<graph>#shard<i>".  '#' keeps shard names out
# of the ordinary registration namespace by convention (nothing stops an
# operator naming a whole graph this way, so the planner refuses parents
# containing the marker rather than trusting the convention blindly).
SHARD_SEP = "#shard"

# One reference-format edge record: two int32s (utils/io.py).
RECORD_BYTES = 8


def shard_name(graph: str, index: int) -> str:
    return f"{graph}{SHARD_SEP}{index}"


def is_shard_name(name: str) -> bool:
    return SHARD_SEP in name


def parent_of(name: str) -> str:
    return name.split(SHARD_SEP, 1)[0]


@dataclass(frozen=True)
class ShardInfo:
    """One row-range shard: a registered-artifact identity plus the
    global row interval [lo, hi) it owns complete adjacency for."""

    name: str  # derived registration name, "<graph>#shard<i>"
    index: int
    path: str  # artifact on disk (reference .bin format)
    digest: str  # content hash of the artifact (ring key + integrity)
    lo: int
    hi: int
    records: int  # directed edge records written

    def describe(self) -> dict:
        return {
            "name": self.name,
            "digest": self.digest,
            "rows": [self.lo, self.hi],
            "records": self.records,
        }


@dataclass
class ShardPlan:
    """A graph's complete shard topology: what the supervisor places,
    the router scatters over, and the manifest journal records."""

    graph: str
    digest: str  # parent artifact's content hash
    n: int  # full vertex space (every shard shares it)
    replicas: int  # copies wanted per shard (MSBFS_SHARD_REPLICAS)
    shards: List[ShardInfo]

    def shard_for_row(self, row: int) -> ShardInfo:
        for s in self.shards:
            if s.lo <= row < s.hi:
                return s
        raise InputError(
            f"row {row} outside graph {self.graph!r}'s vertex space "
            f"[0, {self.n})"
        )

    def to_record(self) -> dict:
        """The manifest journal record (serve/journal.py op "shard")."""
        return {
            "op": "shard",
            "name": self.graph,
            "hash": self.digest,
            "n": self.n,
            "replicas": self.replicas,
            "shards": [
                {
                    "name": s.name,
                    "path": s.path,
                    "hash": s.digest,
                    "lo": s.lo,
                    "hi": s.hi,
                }
                for s in self.shards
            ],
        }

    @classmethod
    def from_manifest(cls, graph: str, manifest: dict) -> "ShardPlan":
        """Rebuild a plan from a replayed manifest record (the shape
        :meth:`~.journal.StateJournal._apply` validated)."""
        shards = [
            ShardInfo(
                name=row["name"],
                index=i,
                path=row["path"],
                digest=row["hash"],
                lo=int(row["lo"]),
                hi=int(row["hi"]),
                records=0,  # not journaled; observability only
            )
            for i, row in enumerate(manifest["shards"])
        ]
        return cls(
            graph=graph,
            digest=manifest["hash"],
            n=int(manifest["n"]),
            replicas=int(manifest["replicas"]),
            shards=shards,
        )

    def describe(self) -> dict:
        return {
            "digest": self.digest,
            "n": self.n,
            "replicas": self.replicas,
            "shards": [s.describe() for s in self.shards],
        }


def artifact_footprint(path: str) -> int:
    """The planner's sharding gate: the registered artifact's on-disk
    bytes.  Deliberately the FILE size, not the in-memory CSR — the cap
    knob talks about what a replica must hold, and the artifact is the
    portable unit of placement and digest verification."""
    return os.path.getsize(path)


def plan_shards(
    graph: str,
    path: str,
    out_dir: str,
    max_bytes: int,
    replicas: int = 2,
    digest: Optional[str] = None,
) -> Optional[ShardPlan]:
    """Plan ``path`` into row-range shard artifacts under ``out_dir``
    when its footprint exceeds ``max_bytes``; None = serve whole (the
    default single-replica path).  Deterministic for a given artifact:
    same bytes -> same split -> same shard digests, which is what lets a
    resurrected supervisor re-plan instead of trusting a lost manifest.

    Shard i's artifact holds one directed record per adjacency entry of
    rows [lo_i, hi_i) — complete out-adjacency for its own rows.  The
    loader's undirected doubling re-inserts each record's reverse, so a
    loaded shard also carries PARTIAL adjacency for out-of-range rows;
    the ``shard_step`` verb refuses to expand those (serve/server.py).
    """
    from .registry import content_hash  # lazy: registry imports io too

    if max_bytes <= 0:
        return None
    if is_shard_name(graph):
        raise InputError(
            f"graph name {graph!r} contains the reserved shard marker "
            f"{SHARD_SEP!r}"
        )
    if replicas < 1:
        raise InputError(f"shard replicas must be >= 1, got {replicas}")
    if artifact_footprint(path) <= max_bytes:
        return None
    g = load_graph_bin(path, native=False)
    if getattr(g, "has_weights", False):
        raise InputError(
            f"graph {graph!r} carries a weight section; sharded serving "
            "is unit-cost only — raise MSBFS_SHARD_MAX_BYTES to serve "
            "it whole, or strip the weights"
        )
    directed = int(g.num_directed_edges)
    est_total = GRAPH_HEADER.size + RECORD_BYTES * directed
    num = max(2, -(-est_total // max_bytes))
    num = min(num, max(1, g.n))
    bounds = edge_balanced_row_splits(g.row_offsets, num)
    parent_digest = digest or content_hash(path)
    os.makedirs(out_dir, exist_ok=True)
    ro = np.asarray(g.row_offsets, dtype=np.int64)
    ci = np.asarray(g.col_indices, dtype=np.int64)
    shards: List[ShardInfo] = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        if lo >= hi:
            continue  # degenerate split tail (n < num)
        src = np.repeat(
            np.arange(lo, hi, dtype=np.int64), np.diff(ro[lo : hi + 1])
        )
        dst = ci[ro[lo] : ro[hi]]
        edges = np.stack([src, dst], axis=1).astype(np.int32)
        sname = shard_name(graph, len(shards))
        spath = os.path.join(out_dir, f"shard{len(shards):04d}.bin")
        try:
            faults.trip("shard_write")  # disk_full:shard (utils/faults)
            save_graph_bin(spath, g.n, edges)
        except OSError as exc:
            raise StorageError(
                f"shard artifact write to {spath} failed: {exc} — "
                f"graph {graph!r} stays unsharded and unregistered; "
                "free disk and re-register"
            ) from exc
        shards.append(
            ShardInfo(
                name=sname,
                index=len(shards),
                path=spath,
                digest=content_hash(spath),
                lo=int(lo),
                hi=int(hi),
                records=int(edges.shape[0]),
            )
        )
    if len(shards) < 2:
        # Everything collapsed into one range (tiny n, hub graph): a
        # single shard is just the whole graph with extra steps.
        return None
    return ShardPlan(
        graph=graph,
        digest=parent_digest,
        n=int(g.n),
        replicas=int(replicas),
        shards=shards,
    )


def scatter_frontier(
    plan: ShardPlan, frontier: Sequence[np.ndarray]
) -> Dict[int, List[List[int]]]:
    """Split per-query frontier vertex arrays by owning shard: the
    row-gather half of the 2D mesh's row-gather/OR-merge discipline,
    rebuilt over the wire.  Returns {shard index: per-query vertex
    lists}, with shards whose row range the frontier never touches
    absent (no fragment, no wire)."""
    out: Dict[int, List[List[int]]] = {}
    for si, s in enumerate(plan.shards):
        rows = [
            [int(v) for v in verts[(verts >= s.lo) & (verts < s.hi)]]
            for verts in frontier
        ]
        if any(rows):
            out[si] = rows
    return out


def or_merge_fragments(
    n: int, fragments: Sequence[Sequence[Sequence[int]]], k: int
) -> List[np.ndarray]:
    """OR-merge shard fragments into one per-query neighbor set: the
    merge half of the row-gather/OR-merge discipline.  Duplicate
    neighbors across fragments (a vertex adjacent to rows in two
    shards) collapse — the OR is idempotent, which is also why a
    hedged/duplicated fragment answer is safe to merge twice."""
    merged: List[np.ndarray] = []
    for q in range(k):
        parts = [
            np.asarray(frag[q], dtype=np.int64)
            for frag in fragments
            if len(frag) > q and len(frag[q])
        ]
        merged.append(
            np.unique(np.concatenate(parts))
            if parts
            else np.zeros(0, dtype=np.int64)
        )
    return merged


__all__ = [
    "SHARD_SEP",
    "ShardInfo",
    "ShardPlan",
    "artifact_footprint",
    "is_shard_name",
    "or_merge_fragments",
    "parent_of",
    "plan_shards",
    "scatter_frontier",
    "shard_name",
]
