#!/usr/bin/env python3
"""Host preprocessing wall time vs native thread count (round 4).

VERDICT r3 item 6: RMAT-25 end-to-end host build (generate + CSR + BELL
forest) was 9.1 min single-core, extrapolating to ~45+ min at RMAT-27 —
all before the device sees a byte.  The counting/placement/dedup/bucket
passes in runtime/loader.cpp are now threaded; this script measures the
whole pipeline at a given scale for a sweep of MSBFS_NATIVE_THREADS.

Run (CPU env, the host work is jax-free until the final device_put which
this script skips):
    env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python benchmarks/exp_host_build.py [scale] [threads,threads,...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_once(scale: int) -> dict:
    import numpy as np

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    m = 16 << scale
    t0 = time.perf_counter()
    edges = native_loader.rmat_edges(scale, m, 0.57, 0.19, 0.19, seed=42)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    n = 1 << scale
    row_offsets, col_indices = native_loader.csr_from_edges(n, edges)
    t_csr = time.perf_counter() - t0

    t0 = time.perf_counter()
    dst, deg = native_loader.dedup_rows(row_offsets, col_indices)
    t_dedup = time.perf_counter() - t0

    t0 = time.perf_counter()
    start = np.zeros(n, dtype=np.int64)
    np.cumsum(deg[:-1], out=start[1:])
    widths = [4, 8, 16, 32, 64, 128, 256, 512]
    native_loader.bell_level(start, deg, dst, widths, sentinel_value=-1)
    t_bell = time.perf_counter() - t0

    del edges, row_offsets, col_indices, dst, deg, start
    return {
        "gen_s": t_gen,
        "csr_s": t_csr,
        "dedup_s": t_dedup,
        "bell_s": t_bell,
        "total_s": t_gen + t_csr + t_dedup + t_bell,
    }


def main():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )

    if not native_loader.available():
        sys.exit("native loader not built (make native)")
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    threads = (
        [int(x) for x in sys.argv[2].split(",")]
        if len(sys.argv) > 2
        else [1, 8]
    )
    base = None
    for t in threads:
        os.environ["MSBFS_NATIVE_THREADS"] = str(t)
        r = build_once(scale)
        if base is None:
            base = r["total_s"]
        print(
            f"RMAT-{scale} threads={t:2d}: gen {r['gen_s']:6.1f}s  "
            f"csr {r['csr_s']:6.1f}s  dedup {r['dedup_s']:6.1f}s  "
            f"bell {r['bell_s']:6.1f}s  total {r['total_s']:6.1f}s  "
            f"speedup x{base / r['total_s']:.2f}",
            flush=True,
        )


if __name__ == "__main__":
    main()
