"""Stencil (banded-adjacency) engine: detection, oracle parity, and
bit-identity with the bitbell engine on lattice-class graphs."""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
    StencilEngine,
    StencilGraph,
    detect_stencil,
)

from oracle import oracle_bfs, oracle_f


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, np.asarray(edges, np.int64), q)) for q in queries]


LATTICES = {
    "road": generators.road_edges(24, 24, seed=921),
    "road_rect": generators.road_edges(13, 37, seed=922),
    "grid": generators.grid_edges(19, 7),
}


class TestDetection:
    def test_road_graph_detects(self):
        n, edges = LATTICES["road"]
        g = CSRGraph.from_edges(n, edges)
        dec = detect_stencil(g)
        assert dec is not None
        offsets, masks, res_src, res_dst = dec
        assert 0 not in offsets and len(offsets) <= 16
        assert masks.shape == (n, len(offsets))
        # Every directed edge is either a masked offset or a residual.
        deg = np.diff(np.asarray(g.row_offsets))
        src = np.repeat(np.arange(n), deg)
        dst = np.asarray(g.col_indices)
        nonloop = (src != dst).sum()
        assert int(masks.sum()) + len(res_src) >= nonloop - 0  # dups collapse
        assert len(res_src) <= 0.02 * g.num_directed_edges

    def test_random_graph_rejects(self):
        n, edges = generators.gnm_edges(300, 900, seed=923)
        g = CSRGraph.from_edges(n, edges)
        assert detect_stencil(g) is None
        with pytest.raises(ValueError, match="not banded"):
            StencilGraph.from_host(g)

    def test_hub_star_rejects(self):
        n = 200
        edges = np.stack(
            [np.zeros(n - 1, dtype=np.int64), np.arange(1, n, dtype=np.int64)],
            axis=1,
        )
        g = CSRGraph.from_edges(n, edges)
        assert detect_stencil(g) is None

    def test_self_loops_only(self):
        n = 16
        edges = np.stack([np.arange(n), np.arange(n)], axis=1).astype(np.int64)
        g = CSRGraph.from_edges(n, edges)
        dec = detect_stencil(g)
        assert dec is not None and dec[0] == ()

    def test_empty_graph(self):
        g = CSRGraph.from_edges(5, np.zeros((0, 2), dtype=np.int64))
        assert detect_stencil(g) is None


@pytest.mark.parametrize("name", sorted(LATTICES))
def test_stencil_matches_oracle(name):
    n, edges = LATTICES[name]
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 9, max_group=4, seed=924)
    queries[2] = np.zeros(0, dtype=np.int32)
    queries[4] = np.array([0, -1, n + 3], dtype=np.int32)  # bounds check
    padded = pad_queries(queries)
    eng = StencilEngine(StencilGraph.from_host(g))
    got = np.asarray(eng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_stencil_bit_identical_to_bitbell():
    n, edges = LATTICES["road"]
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 37, max_group=5, seed=925)
    )
    a = StencilEngine(StencilGraph.from_host(g)).query_stats(queries)
    b = BitBellEngine(BellGraph.from_host(g)).query_stats(queries)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_residual_edges_exact():
    """Grid + a few long random links: the links exceed the offset set and
    must ride the residual scatter, bit-exactly."""
    n, grid = generators.grid_edges(15, 11)
    rng = np.random.default_rng(926)
    extra = rng.integers(0, n, size=(6, 2)).astype(np.int64)
    edges = np.concatenate([grid, extra], axis=0)
    g = CSRGraph.from_edges(n, edges)
    import jax.numpy as jnp

    dec = detect_stencil(g, max_offsets=4, max_residual_frac=0.5)
    assert dec is not None and len(dec[2]) > 0  # residual in play
    sg = StencilGraph.from_decomposition(g.n, g.num_directed_edges, *dec)
    assert sg.res_src.shape[0] > 0
    queries = generators.random_queries(n, 7, max_group=3, seed=927)
    padded = pad_queries(queries)
    got = np.asarray(StencilEngine(sg).f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_sparse_offset_demotion_exact():
    """An offset whose mask covers < n/DEMOTE_DENSITY vertices must be
    demoted into the compact residual — with reachability bit-exact.
    Grid offsets stay plane passes; a handful of +17 edges (one distinct
    diff, far under the density cutoff) must ride the residual."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
        DEMOTE_DENSITY,
    )

    n, grid = generators.grid_edges(31, 17)
    sparse = np.array([[i * 50, i * 50 + 23] for i in range(5)], np.int64)
    edges = np.concatenate([grid, sparse], axis=0)
    g = CSRGraph.from_edges(n, edges)
    dec = detect_stencil(g, max_offsets=8, max_residual_frac=0.1)
    assert dec is not None
    assert 23 in dec[0]  # detection keeps the diff as an offset...
    assert sparse.shape[0] < n // DEMOTE_DENSITY
    sg = StencilGraph.from_decomposition(g.n, g.num_directed_edges, *dec)
    # ...and packing demotes it (plus its reverse) into the residual.
    assert 23 not in sg.offsets and -23 not in sg.offsets
    assert sg.res_src.shape[0] >= 2 * sparse.shape[0]
    assert len(sg.offsets) == len(dec[0]) - 2
    queries = generators.random_queries(n, 6, max_group=3, seed=931)
    padded = pad_queries(queries)
    got = np.asarray(StencilEngine(sg).f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_duplicate_and_self_loop_edges():
    n, grid = generators.grid_edges(9, 9)
    edges = np.concatenate(
        [grid, grid[:13], np.array([[4, 4], [7, 7]], dtype=np.int64)], axis=0
    )
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 5, max_group=3, seed=928)
    padded = pad_queries(queries)
    eng = StencilEngine(StencilGraph.from_host(g))
    np.testing.assert_array_equal(
        np.asarray(eng.f_values(padded)), oracle_f_values(n, edges, queries)
    )


def test_k_above_word_width_and_chunked():
    n, edges = LATTICES["road_rect"]
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 65, max_group=3, seed=929)
    )
    sg = StencilGraph.from_host(g)
    want = StencilEngine(sg).query_stats(queries)
    chunked = StencilEngine(sg, level_chunk=3).query_stats(queries)
    for x, y in zip(want, chunked):
        np.testing.assert_array_equal(x, y)


@pytest.mark.slow  # ~15 s (3 K widths x 2 chunk routes on a deep
# lattice); tier-1 keeps the stencil/bitbell bit-identity pin and the
# fused-best coverage in test_bitbell.py, `make test` runs this arm
def test_fused_best_matches_generic():
    """The r5 fused best() (loop + argmin in one program) must agree with
    the generic run-then-select path on chunked and unchunked routes —
    a deep lattice exercises several continuation dispatches — and the
    F=0 alignment-padding lanes must never win."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
        QueryEngineBase,
    )

    n, edges = LATTICES["road"]
    g = CSRGraph.from_edges(n, edges)
    sg = StencilGraph.from_host(g)
    for k in (1, 5, 33):
        queries = generators.random_queries(n, k, max_group=3, seed=940 + k)
        padded = pad_queries(queries)
        for level_chunk in (None, 4):
            eng = StencilEngine(sg, level_chunk=level_chunk)
            eng.compile(padded.shape)
            want = QueryEngineBase.best(eng, padded)
            assert eng.best(padded) == want
    # Padding lanes cannot win: a single real query with F > 0.
    one = pad_queries([np.array([0], dtype=np.int32)])
    for level_chunk in (None, 4):
        min_f, min_k = StencilEngine(sg, level_chunk=level_chunk).best(one)
        assert min_k == 0 and min_f > 0
    assert StencilEngine(sg).best(np.zeros((0, 2), np.int32)) == (-1, -1)


class TestActiveWindow:
    """Round-7 active-row-window lever: [lo, hi) band slicing must be
    byte-exact AND actually engaged (rows < n) on tall residual-free
    lattices with clustered sources."""

    def _tall_grid(self):
        # 200x8 grid: n=1600, offsets +-1/+-8 (max|d| = 8), residual-free
        # by construction — the window's engagement precondition.
        return generators.grid_edges(200, 8)

    def _corner_queries(self, n):
        rng = np.random.default_rng(933)
        return [
            rng.integers(0, 40, size=rng.integers(1, 4)).astype(np.int32)
            for _ in range(5)
        ]

    def test_window_engages_and_is_exact(self):
        n, edges = self._tall_grid()
        g = CSRGraph.from_edges(n, edges)
        sg = StencilGraph.from_host(g)
        queries = self._corner_queries(n)
        padded = pad_queries(queries)
        eng = StencilEngine(sg, level_chunk=8, megachunk=1, window=True)
        assert eng.window_active
        got = np.asarray(eng.f_values(padded))
        np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))
        trace = eng.last_window_trace
        assert trace, "chunked run must record window decisions"
        # Engagement: the early dispatches run on a sub-plane.
        assert trace[0][4] < n
        # Exactness bounds: every window covers the band grown by the
        # dispatch's step bound, stays in-plane, pow2-or-full rows.
        lo_prev, hi_prev = trace[0][1], trace[0][2]
        for _, band_lo, band_hi, wlo, rows in trace:
            assert 0 <= wlo and wlo + rows <= n
            assert rows == n or rows & (rows - 1) == 0  # pow2 slice
            assert wlo <= band_lo and band_hi <= wlo + rows
            # Monotone band: frontier support only ever widens.
            assert band_lo <= lo_prev and band_hi >= hi_prev
            lo_prev, hi_prev = band_lo, band_hi

    def test_window_best_and_plane_byte_diet(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.timing import (
            plane_pass_bytes,
            reset_plane_pass,
        )

        # 400x8 lattice, BFS depth capped at 64: the local-query regime
        # the window targets — the frontier band never nears the far end,
        # so the full-plane engine streams rows the window provably skips.
        # (A run to convergence ends with the band = the whole plane, so
        # its tail dispatches are full-width either way; there the window
        # saves ~1.5x, not 2x.)
        n, edges = generators.grid_edges(400, 8)
        g = CSRGraph.from_edges(n, edges)
        sg = StencilGraph.from_host(g)
        queries = self._corner_queries(n)
        padded = pad_queries(queries)
        windowed = StencilEngine(
            sg, max_levels=64, level_chunk=8, megachunk=1, window=True
        )
        full = StencilEngine(
            sg, max_levels=64, level_chunk=8, megachunk=1, window=False
        )
        assert windowed.window_active and not full.window_active
        reset_plane_pass()
        best_w = windowed.best(padded)
        bytes_w = plane_pass_bytes()
        reset_plane_pass()
        best_f = full.best(padded)
        bytes_f = plane_pass_bytes()
        assert best_w == best_f
        assert bytes_w > 0
        # The CI proxy for the roofline claim: corner sources on a tall
        # lattice must at least halve full-plane-equivalent stream bytes.
        assert bytes_w * 2 <= bytes_f, (bytes_w, bytes_f)

    def test_residual_graph_falls_back_to_full_plane(self):
        # Elevated shortcut_frac guarantees residual edges; a residual can
        # escape any row band, so the window must disengage — and results
        # stay oracle-exact through the full-plane path.
        n, edges = generators.road_edges(24, 24, seed=932, shortcut_frac=0.02)
        g = CSRGraph.from_edges(n, edges)
        sg = StencilGraph.from_host(g)
        assert int(sg.res_src.shape[0]) > 0
        queries = generators.random_queries(n, 5, max_group=3, seed=934)
        padded = pad_queries(queries)
        eng = StencilEngine(sg, level_chunk=4, window=True)
        assert not eng.window_active
        np.testing.assert_array_equal(
            np.asarray(eng.f_values(padded)),
            oracle_f_values(n, edges, queries),
        )
        assert all(t[4] == n for t in eng.last_window_trace)

    def test_unchunked_engine_never_windows(self):
        n, edges = self._tall_grid()
        sg = StencilGraph.from_host(CSRGraph.from_edges(n, edges))
        assert not StencilEngine(sg, window=True).window_active


@pytest.mark.parametrize(
    "name,block",
    [
        ("road", 2),
        # One lattice pins the blocked-wavefront parity in tier-1
        # (~6 s/arm); the other two ride in `make test`.
        pytest.param("road_rect", 3, marks=pytest.mark.slow),
        pytest.param("grid", 4, marks=pytest.mark.slow),
    ],
)
def test_wavefront_blocked_fuzz(name, block):
    """Wavefront blocking (2-4 levels per while-iteration) must be
    bit-identical to the unblocked loop, chunked and unchunked,
    including the fused best.  Each block size is fuzzed on one lattice
    (the full block x lattice product certified nothing extra and cost
    3x the wall-clock)."""
    n, edges = LATTICES[name]
    g = CSRGraph.from_edges(n, edges)
    sg = StencilGraph.from_host(g)
    queries = pad_queries(
        generators.random_queries(n, 7, max_group=4, seed=950 + block)
    )
    ref = StencilEngine(sg)
    base = ref.query_stats(queries)
    want_best = ref.best(queries)
    for kwargs in ({}, {"level_chunk": 3, "megachunk": 1}):
        eng = StencilEngine(sg, wavefront=block, **kwargs)
        got = eng.query_stats(queries)
        for x, y in zip(base, got):
            np.testing.assert_array_equal(x, y)
        assert eng.best(queries) == want_best


def test_pallas_chain_parity():
    """The chunked Pallas kernel chain (interpret mode off-TPU) must be
    bit-identical to the XLA masked-shift sweep; skips cleanly when the
    pallas import is unavailable on this host."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops import (
        stencil as stencil_mod,
    )

    if stencil_mod._pallas_hits is None:
        pytest.skip("pallas unavailable on this host")
    n, edges = LATTICES["road"]
    g = CSRGraph.from_edges(n, edges)
    sg = StencilGraph.from_host(g)
    queries = pad_queries(
        generators.random_queries(n, 6, max_group=3, seed=935)
    )
    ref = StencilEngine(sg)
    want = ref.query_stats(queries)
    want_best = ref.best(queries)
    for kwargs in ({}, {"level_chunk": 4}):
        eng = StencilEngine(sg, kernel=True, **kwargs)
        assert eng.kernel
        got = eng.query_stats(queries)
        for x, y in zip(want, got):
            np.testing.assert_array_equal(x, y)
        assert eng.best(queries) == want_best


def test_pallas_chain_multi_chunk_parity():
    """Force the chain to actually CHUNK (plane larger than one call's
    row budget) by shrinking the budget, and pin bit-identity of the raw
    hits path."""
    import jax.numpy as jnp

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops import (
        pallas_stencil,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
        _xla_shift_hits,
    )

    n, edges = LATTICES["road"]
    g = CSRGraph.from_edges(n, edges)
    sg = StencilGraph.from_host(g)
    frontier = jnp.zeros((n,), jnp.uint32).at[jnp.arange(0, n, 37)].set(1)
    want = np.asarray(_xla_shift_hits(frontier, sg, flat=True))
    old = pallas_stencil.MAX_TOTAL_ROWS
    try:
        pallas_stencil.MAX_TOTAL_ROWS = 4  # several chunks at n=576
        got = np.asarray(
            pallas_stencil.pallas_hits(frontier, sg.mask_bits, sg.offsets)
        )
    finally:
        pallas_stencil.MAX_TOTAL_ROWS = old
    np.testing.assert_array_equal(want, got)


def test_level_stats_parity():
    n, edges = LATTICES["grid"]
    g = CSRGraph.from_edges(n, edges)
    queries = pad_queries(
        generators.random_queries(n, 6, max_group=3, seed=930)
    )
    eng = StencilEngine(StencilGraph.from_host(g))
    levels, reached, f, lc, secs = eng.level_stats(queries)
    want = eng.query_stats(queries)
    np.testing.assert_array_equal(levels, want[0])
    np.testing.assert_array_equal(reached, want[1])
    np.testing.assert_array_equal(f, want[2])
    assert lc.shape[0] == len(secs)
