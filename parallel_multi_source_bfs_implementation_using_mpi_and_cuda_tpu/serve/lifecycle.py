"""Daemon lifecycle plumbing: signals and socket reclaim (docs/SERVING.md).

Two concerns that belong to the *process*, not the server object:

* :func:`install_signal_handlers` — SIGTERM/SIGINT ask the server for a
  graceful drain (stop accepting, finish in-flight work within the
  drain deadline, exit 0).  A second signal while draining forces an
  immediate stop: the operator escalating ``kill`` → ``kill`` again is
  telling us the deadline no longer matters.

* :func:`reclaim_stale_socket` — ``msbfs serve`` pointed at a unix
  socket path that already exists must decide between "another daemon
  owns this" (refuse, loudly, with its pid) and "a crashed daemon left
  this behind" (unlink and take over).  The probe is a real ``ping``
  round trip, not a connect test: a half-dead process can hold a
  connectable socket without answering anything.
"""

from __future__ import annotations

import os
import signal
import socket
from typing import Optional

from ..runtime.supervisor import InputError
from ..utils.telemetry import dump_flight, log_line
from . import protocol


def install_signal_handlers(server) -> None:
    """SIGTERM/SIGINT -> ``server.request_drain()``; a repeat signal ->
    ``server.stop()`` (immediate).  Main-thread only (CPython signal
    rule); the handlers just flip events, the drain itself runs on the
    thread parked in ``server.wait()``.  Each signal also dumps the
    flight recorder (utils/telemetry.py): the ring's last-N events are
    exactly the post-mortem an operator wants from a killed daemon."""

    def _handler(signum, frame):  # noqa: ARG001 — signal handler shape
        name = signal.Signals(signum).name
        dump_flight(f"sig{name}")
        if server.draining or server.stopping:
            log_line(
                f"msbfs serve: second {name} — stopping immediately",
                event="signal_stop", signal=name,
            )
            server.stop()
            return
        log_line(
            f"msbfs serve: {name} received — draining "
            f"(deadline {server.drain_deadline_s:g}s)",
            event="signal_drain", signal=name,
        )
        server.request_drain()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def probe_socket(path: str, timeout: float = 1.0) -> Optional[int]:
    """Ping the unix socket at ``path``.  Returns the answering daemon's
    pid (or -1 if it answered without one) when a live server responds;
    None when nothing usable is listening (connection refused, timeout,
    framing garbage — all read as "dead")."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    try:
        sock.connect(path)
        protocol.send_frame(sock, {"op": "ping"})
        response = protocol.recv_frame(sock)
    except (OSError, protocol.ProtocolError):
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if not isinstance(response, dict) or not response.get("ok"):
        return None
    return int(response.get("pid", -1))


def reclaim_stale_socket(listen: str) -> None:
    """Startup guard for unix addresses whose path already exists.

    Live daemon answering a ping -> :class:`InputError` naming its pid
    (exit code 1: the operator pointed two daemons at one socket).
    Anything else -> unlink the stale path so bind() can proceed.
    Non-unix addresses are a no-op (TCP rebinding is SO_REUSEADDR's
    problem, handled at bind time).
    """
    family, target = protocol.parse_address(listen)
    if family != socket.AF_UNIX or not isinstance(target, str):
        return
    if not os.path.exists(target):
        return
    pid = probe_socket(target)
    if pid is not None:
        who = f"pid {pid}" if pid > 0 else "unknown pid"
        raise InputError(
            f"a daemon is already running on {listen} ({who}); "
            "stop it first or choose another --listen path"
        )
    log_line(
        f"msbfs serve: removing stale socket {target} "
        "(no daemon answered)",
        event="stale_socket_reclaim", path=target,
    )
    try:
        os.unlink(target)
    except FileNotFoundError:
        pass
