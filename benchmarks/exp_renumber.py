"""Experiment: does vertex renumbering speed up the bitbell level loop?

Hypothesis (docs/PERF_NOTES.md): the per-level frontier gather is
row-latency-bound; on RMAT graphs most gather indices point at hub
vertices, so a degree-descending relabel concentrates the hot frontier
rows into a small contiguous HBM region and should raise the effective
row rate.  Renumbering cannot change results: sources are remapped and
F(U)/reached/levels are permutation-invariant aggregates.

Usage: python benchmarks/exp_renumber.py  [S=20 K=64 EF=16 ORDERS=...]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

S = int(os.environ.get("S", "20"))
K = int(os.environ.get("K", "64"))
EF = int(os.environ.get("EF", "16"))
ORDERS = os.environ.get("ORDERS", "identity,degree_desc,degree_asc,random").split(",")


def relabel(n, edges, order, degrees):
    rng = np.random.default_rng(7)
    if order == "identity":
        return np.arange(n, dtype=np.int64)
    if order == "degree_desc":
        return np.argsort(np.argsort(-degrees, kind="stable"), kind="stable")
    if order == "degree_asc":
        return np.argsort(np.argsort(degrees, kind="stable"), kind="stable")
    if order == "random":
        p = rng.permutation(n)
        return p
    raise ValueError(order)


def main():
    import jax

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
        CSRGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        pad_queries,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.xla_cache import (
        configure_compilation_cache,
    )

    configure_compilation_cache()
    n, edges = generators.rmat_edges(S, edge_factor=EF, seed=42)
    g0 = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, K, max_group=64, seed=43)
    e = g0.num_directed_edges
    degrees = np.asarray(g0.degrees)
    print(f"n={n} E={e} K={K} device={jax.devices()[0]}", flush=True)

    base = None
    for order in ORDERS:
        perm = relabel(n, edges, order, degrees)  # old id -> new id
        edges2 = perm[edges]
        queries2 = [perm[q].astype(np.int32) for q in queries]
        t0 = time.perf_counter()
        g = CSRGraph.from_edges(n, edges2)
        bg = BellGraph.from_host(g)
        eng = BitBellEngine(bg)
        build_s = time.perf_counter() - t0
        padded = pad_queries(queries2, pad_to=64)
        eng.compile(padded.shape)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            min_f, min_k = eng.best(padded)
            ts.append(time.perf_counter() - t0)
        t = min(ts)
        if base is None:
            base = (min_f, min_k)
        assert (min_f, min_k) == base, (order, min_f, min_k, base)
        print(
            f"{order:14s} comp={t:6.3f}s  TEPS={K*e/t/1e9:5.2f}G "
            f"fill={bg.fill:.3f} build={build_s:5.1f}s minF={min_f}",
            flush=True,
        )


if __name__ == "__main__":
    main()
