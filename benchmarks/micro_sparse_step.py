"""Microbenchmark the primitives for a frontier-sparse bitbell level.

Costs that decide between the two candidate scatter-OR formulations:
(a) byte-lane scatter-max of (M, K) uint8 rows (max on 0/1 bytes == OR,
    collision-safe with no preprocessing);
(b) sort edges by target + segmented OR-scan + collision-free row scatter
    of (M, W) uint32 words.

Amortization: every op repeats R times inside one jit (fori_loop) with a
varying input scalar (docs/PERF_NOTES.md "Measurement traps").  Each op's
output is consumed by a FULL reduction (a single-element read lets XLA
dead-code-eliminate most of the op); the reduction cost is measured
separately ("probe" rows) and should be subtracted mentally.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("N", str(1 << 20)))
K = int(os.environ.get("K", "64"))
R = int(os.environ.get("R", "20"))
W = K // 32


def bench(name, fn, *args, elems=1):
    """Time fn(seed, *args); seed varies per call so the tunnel's
    identical-execution result cache can never serve a repeat."""
    import jax
    import jax.numpy as jnp

    # int() forces a device->host transfer: through the axon tunnel,
    # block_until_ready alone does not reliably wait for remote execution.
    int(fn(jnp.int32(99), *args))
    ts = []
    for trial in range(3):
        t0 = time.perf_counter()
        int(fn(jnp.int32(trial), *args))
        ts.append(time.perf_counter() - t0)
    t = min(ts) / R
    print(f"{name:44s} {t * 1e3:9.3f} ms  ({elems / t / 1e6:10.1f} M/s)", flush=True)
    return t


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.xla_cache import (
        configure_compilation_cache,
    )

    configure_compilation_cache()
    print(f"N={N} K={K} R={R} dev={jax.devices()[0]}", flush=True)
    rng = np.random.default_rng(0)

    def rep(body):
        """Repeat body R times, consuming each output with a full sum; the
        per-call seed keys every iteration so neither XLA nor the tunnel's
        result cache can reuse work across timed calls."""

        def run(seed, *args):
            def one(i, acc):
                out = body(i + seed, *args)
                return acc + out.sum(dtype=jnp.uint32)

            return lax.fori_loop(0, R, one, jnp.uint32(0))

        return jax.jit(run)

    # Reduction-cost probes (subtract from same-shaped op rows).
    big_u8 = jnp.ones((N + 1, K), jnp.uint8)
    big_u32 = jnp.ones((N + 1, W), jnp.uint32)
    f = rep(lambda i, x: x + i.astype(jnp.uint8))
    bench(f"probe: sum (N+1,{K}) u8", f, big_u8, elems=(N + 1) * K)
    f = rep(lambda i, x: x + i)
    bench(f"probe: sum (N+1,{W}) u32", f, big_u32, elems=(N + 1) * W)

    for m_log in (18, 20, 21):
        m = 1 << m_log
        idx = jnp.asarray(rng.integers(0, N, size=m, dtype=np.int32))
        bytes_vals = jnp.asarray(
            rng.integers(0, 2, size=(m, K), dtype=np.uint8)
        )
        word_vals = jnp.asarray(
            rng.integers(0, 1 << 31, size=(m, W), dtype=np.uint32)
        )

        f = rep(lambda i, x: x + i.astype(jnp.uint8))
        bench(f"probe: sum (M={m},{K}) u8", f, bytes_vals, elems=m * K)

        # (a) byte-lane scatter-max rows (M, K) u8 into (N+1, K)
        f = rep(
            lambda i, idx, v: jnp.zeros((N + 1, K), jnp.uint8)
            .at[(idx + i) % N]
            .max(v)
        )
        bench(f"scatter-max rows u8 (M={m}, {K}B)", f, idx, bytes_vals, elems=m)

        # (b1) sort M by key with W u32 payload columns
        f = rep(
            lambda i, idx, v: lax.sort(
                ((idx + i) % N, *(v[:, c] for c in range(W))), num_keys=1
            )[1]
        )
        bench(f"sort M={m} key+{W}xu32 payload", f, idx, word_vals, elems=m)

        # (b2) segmented OR scan on (M, W) words (flags from sorted keys)
        def segscan(i, idx, v):
            keys = (idx + i) % N

            def comb(a, b):
                ka, va = a
                kb, vb = b
                same = (ka == kb)[:, None]
                return kb, jnp.where(same, va | vb, vb)

            _, out = lax.associative_scan(comb, (keys, v))
            return out

        f = rep(segscan)
        bench(f"assoc-scan seg-OR M={m} (W={W})", f, idx, word_vals, elems=m)

        # (b3) collision-free row scatter-set (M, W) u32 into (N+1, W)
        f = rep(
            lambda i, idx, v: jnp.zeros((N + 1, W), jnp.uint32)
            .at[(idx + i) % N]
            .set(v, mode="drop")
        )
        bench(f"scatter-set rows u32 (M={m}, {4 * W}B)", f, idx, word_vals, elems=m)

        # word scatter-max (WRONG for OR, cost probe only)
        f = rep(
            lambda i, idx, v: jnp.zeros((N + 1, W), jnp.uint32)
            .at[(idx + i) % N]
            .max(v)
        )
        bench(f"scatter-max rows u32 probe (M={m})", f, idx, word_vals, elems=m)

        # gather M rows from (N, W) u32 (the frontier-word gather)
        plane = jnp.asarray(
            rng.integers(0, 1 << 31, size=(N, W), dtype=np.uint32)
        )
        f = rep(lambda i, idx, p: jnp.take(p, (idx + i) % N, axis=0))
        bench(f"gather rows u32 (M={m})", f, idx, plane, elems=m)

        # searchsorted M into B=65536 (edge-slot -> owner mapping)
        offs = jnp.asarray(np.sort(rng.integers(0, m, size=1 << 16)).astype(np.int32))
        f = rep(
            lambda i, idx, o: jnp.searchsorted(
                o, (idx + i) % m, side="right"
            ).astype(jnp.uint32)
        )
        bench(f"searchsorted M={m} into 64k", f, idx, offs, elems=m)

    # bookkeeping at N: any-bit + degree-sum + cumsum-compact
    deg = jnp.asarray(rng.integers(1, 64, size=N + 1, dtype=np.int32))
    plane = jnp.asarray(rng.integers(0, 2, size=(N, W), dtype=np.uint32))

    def bookkeeping(i, p, d):
        active = (p != 0).any(axis=1)
        edges = jnp.where(active, d[:N], 0).sum()
        on = active.astype(jnp.int32)
        pos = jnp.cumsum(on) - on
        ids = (
            jnp.full((1 << 16,), N, jnp.int32)
            .at[jnp.where(active, pos, 1 << 16)]
            .set(jnp.arange(N, dtype=jnp.int32), mode="drop")
        )
        return ids.astype(jnp.uint32) + edges.astype(jnp.uint32) + i

    f = rep(bookkeeping)
    bench(f"bookkeeping at N={N} (any+sum+compact)", f, plane, deg, elems=N)


if __name__ == "__main__":
    main()
