"""On-chip per-level trace for the road-1024 config-4 workload (VERDICT r4
"What's weak" item 1): decompose the per-level floor that made config 4
11.94 s through the round-4 gather route.

Runs the config-4 grid (side 1024, K=16 query groups, max_s 8) through
BOTH routes' MSBFS_STATS=2 stepped traces:

  - stencil (the round-5 product route: masked flat-id shifts, no gathers)
  - bitbell (the round-4 gather route: hybrid pull/push + chunked loop)

and prints per-level wall-time statistics (median / p90 / max ms per
level, sum) plus a sub-op micro-decomposition of ONE mid-BFS level for
each engine, so the floor's composition (scatter vs full-plane merge vs
dispatch overhead) is measured, not inferred.  The stepped trace pays one
dispatch per level (~the tunnel floor) — the production path amortizes
that via level-chunking, so the interesting number here is the per-level
DEVICE time trend, read from the median of the steady levels.

Reference bar: the reference pays one kernel launch + two 1-byte memcpys
+ a sync per level (main.cu:61-71), tens of us on a modern GPU.
"""

import os
import time

import numpy as np

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
    CSRGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.xla_cache import (
    configure_compilation_cache,
)

configure_compilation_cache()

SIDE = int(os.environ.get("TRACE_SIDE", "1024"))
K = int(os.environ.get("TRACE_K", "16"))
MAX_S = int(os.environ.get("TRACE_MAX_S", "8"))

import jax  # noqa: E402  (after cache config)

print(f"devices: {jax.devices()}", flush=True)

t0 = time.perf_counter()
n, edges = generators.road_edges(SIDE, SIDE, seed=46)
g = CSRGraph.from_edges(n, edges)
queries = pad_queries(
    generators.random_queries(n, K, max_group=MAX_S, seed=43), pad_to=MAX_S
)
print(
    f"road-{SIDE}x{SIDE}: n={n} e_directed={g.num_directed_edges} "
    f"K={K} build_s={time.perf_counter() - t0:.1f}",
    flush=True,
)


def summarize(name, level_seconds, levels, f, extra=""):
    ls = np.asarray(level_seconds[1:])  # row 0 is source packing
    steady = ls[5:-5] if ls.size > 20 else ls
    print(
        f"[{name}] levels={int(levels.max())} sum={ls.sum():.3f}s "
        f"median={np.median(steady) * 1e3:.3f}ms "
        f"p90={np.percentile(steady, 90) * 1e3:.3f}ms "
        f"max={ls.max() * 1e3:.3f}ms "
        f"first10_ms={[round(x * 1e3, 2) for x in ls[:10].tolist()]} "
        f"F_sum={int(np.asarray(f).sum())} {extra}",
        flush=True,
    )
    return ls


def trace_stencil():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
        StencilEngine,
        StencilGraph,
    )

    t0 = time.perf_counter()
    sg = StencilGraph.from_host(g)
    eng = StencilEngine(sg)
    print(
        f"[stencil] offsets={len(sg.offsets)} residual={sg.res_src.shape[0]} "
        f"build_s={time.perf_counter() - t0:.1f}",
        flush=True,
    )
    levels, reached, f, lc, ls = eng.level_stats(queries)
    summarize("stencil stepped", ls, levels, f)
    return eng, f


def trace_bitbell():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )

    t0 = time.perf_counter()
    eng = BitBellEngine(BellGraph.from_host(g))
    print(f"[bitbell] build_s={time.perf_counter() - t0:.1f}", flush=True)
    levels, reached, f, lc, ls = eng.level_stats(queries)
    summarize("bitbell stepped", ls, levels, f)
    return eng, f


def micro_decompose_stencil(eng):
    """One mid-BFS stencil level, sub-op timed.  block_until_ready is
    UNRELIABLE through the axon tunnel (returns early; docs/PERF_NOTES.md
    "Measurement traps"), so every timed program is reduced to a scalar
    and fetched — each sample = floor + work; report the floor alongside
    and read the difference."""
    import jax.numpy as jnp

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        unpack_counts,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
        _shift_planes,
        _stencil_chunk,
        _stencil_init_carry,
        stencil_hits,
        stencil_step,
    )

    # Advance ~SIDE/2 levels via the chunked program (64 levels per
    # dispatch — NOT one dispatch per level) so the wavefront is a
    # full-width diagonal, then time single sub-ops on it.
    padded, _ = eng._pad_queries(queries)
    carry = _stencil_init_carry(eng.graph, padded)
    for _ in range(max(1, SIDE // 2 // 64)):
        carry = _stencil_chunk(eng.graph, carry, jnp.int32(64), None)
    visited, frontier = carry[0], carry[1]
    int(np.asarray(frontier[0, 0]))  # force completion

    def timeit(name, fn, *args):
        int(np.asarray(fn(*args)))  # warm/compile
        ts = []
        for _ in range(15):
            t0 = time.perf_counter()
            int(np.asarray(fn(*args)))
            ts.append(time.perf_counter() - t0)
        print(
            f"  micro[{name}] median={np.median(ts) * 1e3:.3f}ms "
            f"min={min(ts) * 1e3:.3f}ms  (floor included)",
            flush=True,
        )
        return float(np.median(ts))

    g = eng.graph
    timeit("floor (x+1)", jax.jit(lambda x: x + 1), jnp.int32(3))
    timeit(
        "stencil_hits (full level)",
        jax.jit(lambda fr: stencil_hits(fr, g).sum()),
        frontier,
    )
    timeit(
        "full stencil_step (hits+update+counts)",
        jax.jit(lambda v, fr: stencil_step(g, v, fr)[2].sum()),
        visited,
        frontier,
    )
    mb = g.mask_bits[:, None]
    timeit(
        "shifts+masks only (no residual)",
        jax.jit(
            lambda fr: sum(
                _shift_planes(
                    jnp.where(
                        (mb >> jnp.uint32(i)) & jnp.uint32(1) != 0,
                        fr,
                        jnp.uint32(0),
                    ),
                    d,
                )
                for i, d in enumerate(g.offsets)
            ).sum()
        ),
        frontier,
    )
    timeit(
        "unpack_counts",
        jax.jit(lambda fr: unpack_counts(fr).sum()),
        frontier,
    )
    if g.res_src.shape[0]:
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
            pack_byte_planes,
            unpack_byte_planes,
        )

        def residual_only(fr):
            src_words = jnp.take(fr, g.res_src, axis=0)
            src_bytes = unpack_byte_planes(src_words)
            seg = jax.ops.segment_max(
                src_bytes,
                g.res_seg,
                num_segments=g.res_dst_unique.shape[0],
                indices_are_sorted=True,
            )
            return pack_byte_planes(seg).sum()

        timeit("residual segment-OR only", jax.jit(residual_only), frontier)


def main():
    eng_s, f_s = trace_stencil()
    micro_decompose_stencil(eng_s)
    if os.environ.get("TRACE_SKIP_BITBELL", "") != "1":
        eng_b, f_b = trace_bitbell()
        assert np.array_equal(np.asarray(f_s), np.asarray(f_b)), (
            "stencil / bitbell F mismatch"
        )
        print("F parity: stencil == bitbell", flush=True)


if __name__ == "__main__":
    main()
