"""Dynamic micro-batching into power-of-two shape buckets.

Every distinct (K, S) query shape is a distinct XLA program; serving raw
request shapes would compile per request.  Instead (docs/SERVING.md):

* each request's group width S is padded to the next power of two
  (``s_pad``) — semantics-preserving, -1 padding is dropped by the BFS
  source init exactly like the reference's bounds check (main.cu:46-51);
* requests for the same (graph, s_pad) that arrive within the batching
  window coalesce into one batch; the combined row count K is padded to
  the next power of two (``k_exec``);
* the execution shape (k_exec, s_pad) is the *bucket* — a small,
  log-bounded set of shapes, each compiled once and reused
  (fixed-shape padded batching is the tensor-BFS playbook, BLEST-style;
  PAPERS.md).

Admission control: the queue is bounded (``MSBFS_SERVE_QUEUE``); a full
queue rejects immediately with the typed
:class:`~..runtime.supervisor.BackpressureError` rather than queueing
unboundedly — a loaded daemon degrades by shedding, not by growing
until the OOM killer picks a victim.

Adaptive overload control (docs/SERVING.md "Autoscaling & overload")
layers three finer levers on that blunt full-queue gate, so overload
sheds the *cheapest* work first instead of failing uniformly:

* **priority classes** — every request carries ``interactive`` (the
  default; a user is waiting) or ``batch`` (a pipeline will retry).
  Batch work is admitted only while the queue is below
  ``batch_admit_frac`` of capacity (``MSBFS_SERVE_BATCH_ADMIT``), so
  the last headroom is reserved for interactive traffic.
* **per-client token buckets** — with ``MSBFS_SERVE_CLIENT_RATE`` > 0,
  each distinct ``client_id`` refills at that rate (burst
  ``MSBFS_SERVE_CLIENT_BURST``); one stampeding client exhausts its own
  bucket and is rejected typed, instead of starving every other client
  through the shared queue.  Requests without a client id are exempt
  (backward compatible; the fleet router always forwards one).
* **CoDel-style queue shedding** — with ``MSBFS_SERVE_CODEL_TARGET_MS``
  > 0, the consumer watches the queue head's *sojourn time* (monotonic
  clock).  Once it has stayed above the target for a full interval
  (``MSBFS_SERVE_CODEL_INTERVAL_MS``), one victim is shed typed per
  interval — the oldest ``batch`` request if any, else the head — which
  keeps the queue short enough that admitted interactive work still
  meets its deadline, rather than serving everyone equally late
  (Nichols & Jacobson's CoDel insight, applied to an RPC queue).

All three levers default **off** (no batch traffic, no rate, target 0):
a stock daemon's admission behavior is bit-identical to PR 3.  Draining
suspends CoDel shedding — accepted work is finished, per the drain
contract.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from ..runtime.supervisor import BackpressureError, MsbfsError
from ..utils import knobs, telemetry
from ..utils.telemetry import record_flight, span

DEFAULT_QUEUE_CAPACITY = 64
DEFAULT_WINDOW_S = 0.002
# One execution's row bound: coalescing stops before k_exec would exceed
# this (the per-level intermediates are O(K * E); a runaway coalesce must
# not assemble a batch the chip cannot hold).
DEFAULT_MAX_ROWS = 1024
# Overload-control defaults: batch traffic keeps the last quarter of the
# queue free for interactive work; token buckets and CoDel are off until
# their knobs arm them (rate/target of 0 = disabled).
DEFAULT_BATCH_ADMIT_FRAC = 0.75
DEFAULT_CODEL_INTERVAL_S = 0.1

PRIORITIES = ("interactive", "batch")


class TokenBucket:
    """Classic leaky token bucket, monotonic-clock fed.  ``now`` is
    injectable so admission tests run sleepless."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.stamp = float(now)

    def take(self, now: float) -> bool:
        """Spend one token if available; refills ``rate`` tokens/second
        since the last call, capped at ``burst``."""
        elapsed = max(0.0, float(now) - self.stamp)
        self.stamp = float(now)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


def pow2_pad(x: int) -> int:
    """Smallest power of two >= max(x, 1)."""
    return 1 << max(0, (max(1, int(x)) - 1).bit_length())


def bucket_label(
    graph_key: str, k_exec: int, s_pad: int, weighted: bool = False
) -> str:
    """Stable stats key for one executable bucket.  Weighted batches
    (delta-stepping cost answers) get their own ``:w`` bucket: they run
    a different engine against the same graph, so their compile ledger,
    latency profile and stats must never blend with hop-count
    traffic."""
    stem = f"{graph_key}:{k_exec}x{s_pad}"
    return stem + ":w" if weighted else stem


@dataclass
class QueryRequest:
    """One admitted query batch: padded rows + a completion event.

    ``rows`` is the request's (K, s_pad) int32 -1-padded array; the
    batcher may execute it inside a larger coalesced batch.  Exactly one
    of ``result`` / ``error`` is set before ``done`` fires.
    """

    graph_key: str
    graph_name: str
    version: int
    rows: np.ndarray  # (K, s_pad) int32, -1 padded
    s_pad: int
    submitted: float
    # Absolute wall-clock time after which the client has given up; the
    # server sheds the request instead of computing an unwanted answer
    # (None = no client deadline on the wire).
    deadline: Optional[float] = None
    # Overload-control metadata: priority class ("interactive" is the
    # default — absent on the wire means a user is waiting) and the
    # caller's self-declared client id for per-client rate limiting.
    priority: str = "interactive"
    client_id: Optional[str] = None
    # Weighted (delta-stepping) query: routed to the entry's weighted
    # supervisor and NEVER coalesced with hop-count requests — the
    # answers come from different engines.
    weighted: bool = False
    # Monotonic admission stamp (set by submit()): sojourn time for the
    # CoDel controller and the health verb's queue-age gauge must not
    # jump when the wall clock steps.
    enqueued_mono: float = 0.0
    # The submitting query's TraceContext (utils/telemetry.py), if any:
    # the consumer thread re-installs it so batch/supervisor/engine
    # spans land on the originating trace despite the thread hop.
    trace: Optional[object] = None
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[dict] = None
    error: Optional[MsbfsError] = None

    @property
    def k(self) -> int:
        return int(self.rows.shape[0])


class MicroBatcher:
    """Single-consumer bounded queue with windowed same-bucket coalescing.

    ``execute(requests, k_exec, s_pad)`` is the server's dispatch
    callback; it must set result/error on every request and fire their
    events.  The worker is one thread by design: JAX dispatch is
    serialized per device anyway, and a single consumer makes the
    coalescing window deterministic.
    """

    def __init__(
        self,
        execute: Callable[[List[QueryRequest], int, int], None],
        capacity: Optional[int] = None,
        window_s: Optional[float] = None,
        max_rows: Optional[int] = None,
        batch_admit_frac: Optional[float] = None,
        client_rate: Optional[float] = None,
        client_burst: Optional[float] = None,
        codel_target_s: Optional[float] = None,
        codel_interval_s: Optional[float] = None,
    ):
        if capacity is None:
            capacity = _env_int("MSBFS_SERVE_QUEUE", DEFAULT_QUEUE_CAPACITY)
        if window_s is None:
            window_s = _env_float("MSBFS_SERVE_WINDOW", DEFAULT_WINDOW_S)
        if max_rows is None:
            max_rows = _env_int("MSBFS_SERVE_MAX_ROWS", DEFAULT_MAX_ROWS)
        if batch_admit_frac is None:
            batch_admit_frac = _env_float(
                "MSBFS_SERVE_BATCH_ADMIT", DEFAULT_BATCH_ADMIT_FRAC
            )
        if client_rate is None:
            client_rate = _env_float("MSBFS_SERVE_CLIENT_RATE", 0.0)
        if client_burst is None:
            client_burst = _env_float(
                "MSBFS_SERVE_CLIENT_BURST", max(8.0, 2.0 * client_rate)
            )
        if codel_target_s is None:
            codel_target_s = (
                _env_float("MSBFS_SERVE_CODEL_TARGET_MS", 0.0) / 1000.0
            )
        if codel_interval_s is None:
            codel_interval_s = (
                _env_float("MSBFS_SERVE_CODEL_INTERVAL_MS",
                           DEFAULT_CODEL_INTERVAL_S * 1000.0) / 1000.0
            )
        self.execute = execute
        self.capacity = max(1, int(capacity))
        self.window_s = max(0.0, float(window_s))
        self.max_rows = max(1, int(max_rows))
        self.batch_admit_frac = min(1.0, max(0.0, float(batch_admit_frac)))
        self.client_rate = max(0.0, float(client_rate))
        self.client_burst = max(1.0, float(client_burst))
        self.codel_target_s = max(0.0, float(codel_target_s))
        self.codel_interval_s = max(0.001, float(codel_interval_s))
        self.rejected = 0
        self.rejected_batch = 0
        self.rejected_client = 0
        self.shed_overload = 0
        self.batches = 0
        self.coalesced = 0
        self._buckets: dict = {}  # client_id -> TokenBucket
        self._first_above: Optional[float] = None  # CoDel state
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._gate = threading.Event()  # tests hold() this to fill the queue
        self._gate.set()
        self._stop = False
        self._draining = False
        self._busy = False  # worker is mid-execute (drain must wait it out)
        self._idle = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="msbfs-batcher", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._stop = True
            self._ready.notify_all()
        self._gate.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def hold(self) -> None:
        """Pause the consumer (tests: fill the queue deterministically to
        rehearse backpressure)."""
        self._gate.clear()

    def release(self) -> None:
        self._gate.set()

    # ---- graceful drain ----------------------------------------------------
    def begin_drain(self) -> None:
        """Refuse new admissions; already-queued and in-flight requests
        keep flowing (the drain's whole point: finish what we accepted)."""
        with self._lock:
            self._draining = True
            self._ready.notify_all()
        self._gate.set()  # a held gate must not deadlock a drain

    def drain(self, deadline_s: float) -> bool:
        """Block until the queue is empty and the worker is idle, or
        ``deadline_s`` elapses.  True = fully drained."""
        limit = time.time() + max(0.0, deadline_s)
        with self._lock:
            while self._queue or self._busy:
                if self._stop:  # forced stop outranks the drain deadline
                    return not (self._queue or self._busy)
                remaining = limit - time.time()
                if remaining <= 0:
                    return False
                self._idle.wait(min(remaining, 0.1))
        return True

    def fail_pending(self, error: MsbfsError) -> int:
        """Fail every still-queued request typed (drain deadline expired:
        the responses must go out before the process does)."""
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
            self._idle.notify_all()
        for req in pending:
            if not req.done.is_set():
                req.error = error
                req.done.set()
        return len(pending)

    # ---- producer side ----------------------------------------------------
    def submit(self, request: QueryRequest,
               now: Optional[float] = None) -> None:
        """Admit or reject-now.  Rejection is the typed BackpressureError
        (wire exit code 7) and counts in stats, split by cause
        (``rejected`` full queue / ``rejected_batch`` priority gate /
        ``rejected_client`` token bucket).  ``now`` is an injectable
        monotonic stamp for sleepless admission tests."""
        with span("batch.admit", priority=request.priority) as sp:
            self._admit(request, now)
            sp.set(depth=len(self._queue))

    def _admit(self, request: QueryRequest,
               now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._stop:
                raise MsbfsError("server is shutting down")
            if self._draining:
                from ..runtime.supervisor import TransientError

                raise TransientError(
                    "server is draining; retry against another instance"
                )
            if self.client_rate > 0.0 and request.client_id is not None:
                bucket = self._buckets.get(request.client_id)
                if bucket is None:
                    if len(self._buckets) > 4096:
                        # Full buckets are indistinguishable from fresh
                        # ones: drop them so one-shot client ids cannot
                        # grow the map without bound.
                        self._buckets = {
                            cid: b for cid, b in self._buckets.items()
                            if b.tokens < b.burst
                        }
                    bucket = TokenBucket(
                        self.client_rate, self.client_burst, now
                    )
                    self._buckets[request.client_id] = bucket
                if not bucket.take(now):
                    self.rejected_client += 1
                    record_flight("batch_shed", reason="client_rate",
                                  client_id=request.client_id)
                    raise BackpressureError(
                        f"client {request.client_id!r} over its "
                        f"{self.client_rate:g}/s admission rate; "
                        "retry with backoff"
                    )
            if (request.priority == "batch"
                    and len(self._queue)
                    >= self.batch_admit_frac * self.capacity):
                self.rejected_batch += 1
                record_flight("batch_shed", reason="batch_admit_frac",
                              depth=len(self._queue))
                raise BackpressureError(
                    "batch admission suspended above "
                    f"{self.batch_admit_frac:g} queue utilization; "
                    "retry with backoff"
                )
            if len(self._queue) >= self.capacity:
                self.rejected += 1
                record_flight("batch_shed", reason="queue_full",
                              depth=len(self._queue))
                raise BackpressureError(
                    f"admission queue full ({self.capacity} pending); "
                    "retry with backoff"
                )
            request.enqueued_mono = now
            self._queue.append(request)
            self._ready.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Monotonic age in seconds of the oldest *queued* request (0.0
        when the queue is empty).  The autoscaler's stuck-head signal
        and the health verb's gauge; monotonic-clock based, so a wall
        clock stepping backward can never read as a drained queue."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if not self._queue:
                return 0.0
            return max(0.0, now - self._queue[0].enqueued_mono)

    # ---- consumer side ----------------------------------------------------
    def _shed_overload_locked(self, now: float) -> List[QueryRequest]:
        """CoDel-style controller, lock held, run at every dequeue
        opportunity.  Head sojourn above target continuously for a full
        interval -> shed ONE victim (the oldest ``batch`` request if
        any, else the head) and restart the interval.  Disabled while
        draining: accepted work is finished, per the drain contract.
        Returns the victims; the caller completes them outside the
        execute path."""
        if self.codel_target_s <= 0.0 or self._draining or not self._queue:
            self._first_above = None
            return []
        sojourn = now - self._queue[0].enqueued_mono
        if sojourn <= self.codel_target_s:
            self._first_above = None
            return []
        if self._first_above is None:
            self._first_above = now
            return []
        if now - self._first_above < self.codel_interval_s:
            return []
        victim_i = 0
        for i, req in enumerate(self._queue):
            if req.priority == "batch":
                victim_i = i
                break
        victim = self._queue[victim_i]
        del self._queue[victim_i]
        self._first_above = now
        self.shed_overload += 1
        self._idle.notify_all()
        return [victim]

    def _pop_batch(self) -> Optional[List[QueryRequest]]:
        """Block for a first request, wait out the window, then drain
        every queued request in the same (graph key+version, s_pad)
        bucket up to the row bound.  FIFO across buckets: only requests
        *behind* a different-bucket head wait for its batch."""
        shed: List[QueryRequest] = []
        head: Optional[QueryRequest] = None
        with self._lock:
            # The hold() gate is honored HERE, before popping: the worker
            # parks inside this wait loop between batches, so a gate that
            # was only checked in _run would let one held request through
            # (tests fill the queue under hold() to rehearse
            # backpressure; 0.1 s polling bounds the release latency).
            while head is None:
                while (
                    not self._queue or not self._gate.is_set()
                ) and not self._stop:
                    self._ready.wait(0.1)
                if self._stop and not self._queue:
                    break
                shed.extend(self._shed_overload_locked(time.monotonic()))
                if self._queue:
                    head = self._queue.popleft()
                    self._busy = True  # drain() must wait out this batch
        for req in shed:
            if not req.done.is_set():
                record_flight("batch_shed", reason="codel_overload",
                              graph=req.graph_name, priority=req.priority)
                req.error = BackpressureError(
                    "shed by overload control: queue sojourn above "
                    f"{self.codel_target_s * 1000:g} ms for a full "
                    "interval; retry with backoff"
                )
                req.done.set()
        if head is None:
            return None
        if self.window_s:
            time.sleep(self.window_s)
        batch = [head]
        rows = head.k
        with self._lock:
            keep: deque = deque()
            while self._queue:
                req = self._queue.popleft()
                same = (
                    req.graph_key == head.graph_key
                    and req.s_pad == head.s_pad
                    and req.weighted == head.weighted
                )
                if same and rows + req.k <= self.max_rows:
                    batch.append(req)
                    rows += req.k
                else:
                    keep.append(req)
            # Preserve arrival order of everything not taken.
            self._queue.extendleft(reversed(keep))
        return batch

    def _run(self) -> None:
        while True:
            batch = self._pop_batch()
            if batch is None:
                return
            k_total = sum(r.k for r in batch)
            k_exec = pow2_pad(k_total)
            # Synthesize one queue-wait/coalesce span per traced request
            # from its own admission stamp: the consumer thread learns
            # which traces rode this batch only now, so the span is
            # backdated to wall-clock submission (epoch µs, the store's
            # native clock).
            now = time.time()
            for req in batch:
                if req.trace is not None:
                    telemetry.record_span_event(req.trace.trace_id, {
                        "name": "batch.queue_wait",
                        "ph": "X",
                        "ts": int(req.submitted * 1e6),
                        "dur": max(0, int((now - req.submitted) * 1e6)),
                        "pid": os.getpid(),
                        "tid": threading.get_ident(),
                        "args": {"coalesced": len(batch),
                                 "k_exec": k_exec},
                    })
            try:
                self.execute(batch, k_exec, batch[0].s_pad)
            except BaseException as exc:  # noqa: BLE001 — daemon must survive
                # The execute callback classifies and answers per-request
                # itself; anything escaping it is a server bug — fail the
                # batch typed rather than killing the consumer thread.
                from ..runtime.supervisor import classify

                err = classify(exc)
                for req in batch:
                    if not req.done.is_set():
                        req.error = err
                        req.done.set()
            finally:
                with self._lock:
                    self._busy = False
                    self._idle.notify_all()
            self.batches += 1
            self.coalesced += len(batch) - 1


def _env_int(name: str, default: int) -> int:
    return knobs.get_int(name, default)


def _env_float(name: str, default: float) -> float:
    return knobs.get_float(name, default)
