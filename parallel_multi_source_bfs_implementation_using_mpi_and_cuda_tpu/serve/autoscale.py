"""Elastic fleet autoscaling policy (docs/SERVING.md "Autoscaling &
overload").

A pure decision object: signals in, ``+k`` / ``-k`` / ``0`` out, no
threads, no sockets, no clocks.  The fleet supervisor feeds it one tick
per heartbeat from the signals the ``health`` verb already carries
(queue depth/capacity, oldest queued age) plus the shed counters from
``stats``; the stampede bench drives the *same* object against its
in-process fleet — one policy, two harnesses, so the reaction SLO the
bench pins is the reaction the real fleet has.

Three stabilizers keep the loop from flapping, each a knob:

hysteresis
    A scale decision needs ``up_after`` (resp. ``down_after``)
    *consecutive* hot (cold) ticks.  One hot heartbeat is noise; a
    stampede is hot on every tick.  ``down_after`` defaults much larger
    than ``up_after`` — adding capacity late costs latency, removing it
    early costs a re-add (and a reshard) when the load returns.

cooldown
    After any scale event the policy holds for ``cooldown_ticks`` ticks
    regardless of signals, long enough for the event's effect (a new
    replica warming, a victim draining) to show up in the signals it
    watches — the classic control-loop settle time.

churn budget
    At most ``churn_budget`` membership changes per ``churn_window``
    ticks, full stop.  A pathological signal (e.g. a flapping replica
    oscillating the mean) can exhaust the budget but never thrash the
    ring faster than graphs can reshard.

A tick is **hot** when mean queue utilization >= ``high_watermark``, or
anything was shed since the last tick, or the oldest queued request is
older than ``age_high_s`` — any one signal suffices, because each names
a different failure (full queues, admission collapse, a stuck head).
A tick is **cold** only when utilization <= ``low_watermark`` AND
nothing was shed AND the queue head is young: scale-down needs every
signal quiet.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Optional, Sequence


@dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    high_watermark: float = 0.75  # mean depth/capacity that reads as hot
    low_watermark: float = 0.15   # mean depth/capacity that reads as cold
    age_high_s: float = 1.0       # oldest queued request age that reads hot
    up_after: int = 2             # consecutive hot ticks before scale-up
    down_after: int = 8           # consecutive cold ticks before scale-down
    cooldown_ticks: int = 6       # post-event hold, either direction
    max_step: int = 1             # replicas added/removed per event
    churn_budget: int = 4         # membership changes allowed ...
    churn_window: int = 120       # ... per this many ticks

    def validate(self) -> "AutoscaleConfig":
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if not (0.0 <= self.low_watermark < self.high_watermark):
            raise ValueError(
                f"need 0 <= low_watermark < high_watermark, got "
                f"{self.low_watermark} / {self.high_watermark}"
            )
        for name in ("up_after", "down_after", "max_step", "churn_budget"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        return self


@dataclass
class ReplicaSignal:
    """One replica's slice of the fleet signal, as the supervisor reads
    it off the ``health`` verb.  ``utilization`` is queue depth over
    capacity (>= 0, may exceed 1 transiently); ``oldest_age_s`` is the
    monotonic age of the queue head (0 when empty)."""

    utilization: float = 0.0
    oldest_age_s: float = 0.0


class AutoscalePolicy:
    """Feed :meth:`tick` once per heartbeat; it returns the signed
    replica delta to apply (0 = hold).  The caller owns actually adding
    or removing replicas — and reports the applied change back via the
    return-value contract (a non-zero decision assumes it was applied;
    call :meth:`cancel` if it was not, to refund the churn budget)."""

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = (config or AutoscaleConfig()).validate()
        self.tick_index = 0
        self.hot_ticks = 0
        self.cold_ticks = 0
        self.cooldown_until = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_decision = 0
        self.last_reason = "init"
        self._events: Deque[int] = deque()  # tick index of each event

    # ---- signal classification ---------------------------------------
    def _classify(self, replicas: Sequence[ReplicaSignal],
                  shed_since_last: int) -> str:
        cfg = self.config
        if not replicas:
            return "hot"  # an empty fleet is maximally under-provisioned
        util = sum(r.utilization for r in replicas) / len(replicas)
        age = max(r.oldest_age_s for r in replicas)
        if (util >= cfg.high_watermark or shed_since_last > 0
                or age >= cfg.age_high_s):
            return "hot"
        if util <= cfg.low_watermark and shed_since_last == 0 \
                and age < cfg.age_high_s:
            return "cold"
        return "warm"

    def _churn_left(self) -> int:
        cfg = self.config
        floor = self.tick_index - cfg.churn_window
        while self._events and self._events[0] <= floor:
            self._events.popleft()
        return cfg.churn_budget - len(self._events)

    # ---- the control loop --------------------------------------------
    def tick(self, size: int, replicas: Sequence[ReplicaSignal],
             shed_since_last: int = 0) -> int:
        """One heartbeat: classify signals, update hysteresis counters,
        return the replica delta (+k to add, -k to remove, 0 to hold).
        ``size`` is the current replica count the delta applies to."""
        cfg = self.config
        self.tick_index += 1
        state = self._classify(replicas, shed_since_last)
        if state == "hot":
            self.hot_ticks += 1
            self.cold_ticks = 0
        elif state == "cold":
            self.cold_ticks += 1
            self.hot_ticks = 0
        else:
            self.hot_ticks = 0
            self.cold_ticks = 0
        if self.tick_index < self.cooldown_until:
            self.last_decision, self.last_reason = 0, "cooldown"
            return 0
        if self.hot_ticks >= cfg.up_after and size < cfg.max_replicas:
            if self._churn_left() < 1:
                self.last_decision, self.last_reason = 0, "churn-budget"
                return 0
            delta = min(cfg.max_step, cfg.max_replicas - size)
            self._commit(delta, "hot")
            return delta
        if self.cold_ticks >= cfg.down_after and size > cfg.min_replicas:
            if self._churn_left() < 1:
                self.last_decision, self.last_reason = 0, "churn-budget"
                return 0
            delta = -min(cfg.max_step, size - cfg.min_replicas)
            self._commit(delta, "cold")
            return delta
        self.last_decision, self.last_reason = 0, state
        return 0

    def _commit(self, delta: int, reason: str) -> None:
        self.hot_ticks = 0
        self.cold_ticks = 0
        self.cooldown_until = self.tick_index + self.config.cooldown_ticks
        self._events.append(self.tick_index)
        if delta > 0:
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        self.last_decision, self.last_reason = delta, reason

    def cancel(self) -> None:
        """The caller could not apply the last non-zero decision (spawn
        failed, victim refused to drain): refund the churn budget so the
        policy retries after its cooldown instead of starving."""
        if self._events:
            self._events.pop()

    def describe(self) -> dict:
        """Counters + config for the fleet ``stats`` roll-up."""
        cfg = self.config
        return {
            "tick": self.tick_index,
            "hot_ticks": self.hot_ticks,
            "cold_ticks": self.cold_ticks,
            "cooldown_until": self.cooldown_until,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "churn_left": self._churn_left(),
            "last_decision": self.last_decision,
            "last_reason": self.last_reason,
            "config": {
                "min_replicas": cfg.min_replicas,
                "max_replicas": cfg.max_replicas,
                "high_watermark": cfg.high_watermark,
                "low_watermark": cfg.low_watermark,
                "age_high_s": cfg.age_high_s,
                "up_after": cfg.up_after,
                "down_after": cfg.down_after,
                "cooldown_ticks": cfg.cooldown_ticks,
                "max_step": cfg.max_step,
                "churn_budget": cfg.churn_budget,
                "churn_window": cfg.churn_window,
            },
        }
