"""Binary format tests: byte-exact round trips and CSR build parity
(reference formats: main.cu:92-130 graph, main.cu:134-164 queries)."""

import struct

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
    CSRGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    load_graph_bin,
    load_query_bin,
    pad_queries,
    save_graph_bin,
    save_query_bin,
)

from oracle import oracle_csr


def test_graph_bytes_exact(tmp_path):
    # Hand-build the exact byte layout: int32 n, int64 m, m x (int32, int32).
    edges = [(0, 1), (1, 2), (2, 2), (0, 1)]  # self-loop + duplicate
    path = tmp_path / "g.bin"
    with open(path, "wb") as f:
        f.write(struct.pack("<iq", 4, len(edges)))
        for u, v in edges:
            f.write(struct.pack("<ii", u, v))
    g = load_graph_bin(path, native=False)
    assert g.n == 4 and g.m == 4
    ro, ci = oracle_csr(4, np.array(edges))
    np.testing.assert_array_equal(g.row_offsets, ro)
    np.testing.assert_array_equal(g.col_indices, ci)
    # Self-loop stored twice (main.cu:114-115): vertex 2 has [2, 2, 1].
    assert g.degrees[2] == 3


def test_graph_roundtrip(tmp_path):
    n, edges = generators.gnm_edges(100, 400, seed=3)
    path = tmp_path / "g.bin"
    save_graph_bin(path, n, edges)
    g = load_graph_bin(path, native=False)
    assert (g.n, g.m) == (n, 400)
    ro, ci = oracle_csr(n, edges)
    np.testing.assert_array_equal(g.row_offsets, ro)
    np.testing.assert_array_equal(g.col_indices, ci)


def test_graph_empty(tmp_path):
    path = tmp_path / "g.bin"
    save_graph_bin(path, 5, np.zeros((0, 2), dtype=np.int32))
    g = load_graph_bin(path, native=False)
    assert g.n == 5 and g.m == 0 and g.num_directed_edges == 0


def test_graph_truncated(tmp_path):
    path = tmp_path / "g.bin"
    with open(path, "wb") as f:
        f.write(struct.pack("<iq", 4, 10))  # header promises 10 edges, none given
    with pytest.raises(IOError):
        load_graph_bin(path, native=False)


def test_query_bytes_exact(tmp_path):
    path = tmp_path / "q.bin"
    # uint8 K=3; groups: [5], [], [7, 8, 9]
    with open(path, "wb") as f:
        f.write(bytes([3]))
        f.write(bytes([1]) + struct.pack("<i", 5))
        f.write(bytes([0]))
        f.write(bytes([3]) + struct.pack("<iii", 7, 8, 9))
    qs = load_query_bin(path)
    assert len(qs) == 3
    np.testing.assert_array_equal(qs[0], [5])
    assert qs[1].size == 0
    np.testing.assert_array_equal(qs[2], [7, 8, 9])


def test_query_roundtrip(tmp_path):
    queries = generators.random_queries(1000, 17, max_group=128, seed=5)
    queries.append(np.zeros(0, dtype=np.int32))  # empty group
    path = tmp_path / "q.bin"
    save_query_bin(path, queries)
    back = load_query_bin(path)
    assert len(back) == len(queries)
    for a, b in zip(queries, back):
        np.testing.assert_array_equal(a, b)


def test_query_limits(tmp_path):
    with pytest.raises(ValueError):
        save_query_bin(tmp_path / "q.bin", [[0]] * 256)  # K > uint8
    with pytest.raises(ValueError):
        save_query_bin(tmp_path / "q.bin", [list(range(256))])  # size > uint8


def test_pad_queries():
    qs = [np.array([1, 2]), np.array([], dtype=np.int32), np.array([3, 4, 5])]
    p = pad_queries(qs)
    assert p.shape == (3, 3) and p.dtype == np.int32
    np.testing.assert_array_equal(p[0], [1, 2, -1])
    np.testing.assert_array_equal(p[1], [-1, -1, -1])
    np.testing.assert_array_equal(p[2], [3, 4, 5])
    assert pad_queries([], pad_to=4).shape == (0, 4)
    with pytest.raises(ValueError):
        pad_queries(qs, pad_to=2)


def test_from_edges_matches_oracle_insertion_order():
    n, edges = generators.gnm_edges(50, 300, seed=9)
    g = CSRGraph.from_edges(n, edges)
    ro, ci = oracle_csr(n, edges)
    np.testing.assert_array_equal(g.row_offsets, ro)
    np.testing.assert_array_equal(g.col_indices, ci)
