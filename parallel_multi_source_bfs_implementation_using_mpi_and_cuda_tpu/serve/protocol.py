"""Wire protocol: length-prefixed JSON frames (docs/SERVING.md).

One frame = a 4-byte big-endian unsigned length followed by that many
bytes of UTF-8 JSON encoding a single object.  Both directions use the
same framing; a connection carries any number of request/response pairs
in order (no pipelining guarantees beyond FIFO per connection).

Requests are objects with an ``op`` field (``ping`` / ``health`` /
``load`` / ``reload`` / ``query`` / ``stats`` / ``shutdown``);
responses carry ``ok: true`` plus op-specific fields, or ``ok: false``
with a typed ``error`` object mirroring the supervisor taxonomy
(``{"type", "message", "exit_code"}`` — docs/RESILIENCE.md exit-code
table).  ``ping`` answers with the daemon's ``pid`` (the stale-socket
probe and "already running" diagnostics key on it); ``health`` is the
readiness report (docs/SERVING.md probe table).  ``query`` accepts an
optional ``deadline_s`` number — a client-relative budget the server
uses to shed requests whose caller has already given up.  Query ids
and F values are plain JSON numbers: F fits in int64 and JSON numbers
are exact through 2^53, far beyond any sum of n hop-distances this
system can hold in HBM.

The length prefix is bounded (:data:`MAX_FRAME_BYTES`,
``MSBFS_SERVE_MAX_FRAME`` overrides): a corrupt or hostile prefix must
never turn into a multi-GiB allocation — the same fail-before-allocate
posture as the binary graph loader (utils/io.py header checks).
"""

from __future__ import annotations

import json
import os
import socket
import struct
from typing import Optional

_LEN = struct.Struct("!I")

# 64 MiB default: a 255-group x 255-source query batch plus its response
# is < 1 MiB of JSON, so this bounds damage, not capability.
MAX_FRAME_BYTES = 64 << 20


class ProtocolError(ValueError):
    """Malformed frame (oversized prefix, truncated body, non-JSON,
    non-object payload).  Classified as InputError at the server seam."""


def max_frame_bytes() -> int:
    """The active bound (env-overridable, malformed values fall back —
    the repo-wide knob convention)."""
    raw = os.environ.get("MSBFS_SERVE_MAX_FRAME", "")
    if raw:
        try:
            v = int(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return MAX_FRAME_BYTES


def encode_frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > max_frame_bytes():
        raise ProtocolError(
            f"frame of {len(body)} bytes exceeds the "
            f"{max_frame_bytes()}-byte bound"
        )
    return _LEN.pack(len(body)) + body


def send_frame(sock: socket.socket, obj: dict) -> None:
    sock.sendall(encode_frame(obj))


def _read_exact(sock: socket.socket, count: int) -> Optional[bytes]:
    """Read exactly ``count`` bytes, or None on clean EOF at a frame
    boundary (mid-frame EOF is a ProtocolError: the peer vanished)."""
    chunks = []
    got = 0
    while got < count:
        chunk = sock.recv(min(count - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError(
                f"connection closed mid-frame ({got}/{count} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Optional[dict]:
    """One frame -> dict, or None on clean EOF (peer done)."""
    header = _read_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > max_frame_bytes():
        raise ProtocolError(
            f"frame prefix claims {length} bytes, bound is "
            f"{max_frame_bytes()}"
        )
    body = _read_exact(sock, length) if length else b""
    if body is None:
        raise ProtocolError("connection closed between prefix and body")
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ProtocolError(f"frame body is not JSON: {exc}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"frame body must be a JSON object, got {type(obj).__name__}"
        )
    return obj


def error_body(err) -> dict:
    """Typed error -> the wire's ``error`` object (taxonomy class name,
    message, documented exit code — docs/RESILIENCE.md)."""
    return {
        "ok": False,
        "error": {
            "type": type(err).__name__,
            "message": str(err),
            "exit_code": int(getattr(err, "exit_code", 6)),
        },
    }


def parse_address(addr: str):
    """``unix:<path>`` or ``<host>:<port>`` -> (family, target).

    The unix form is the default deployment (single host, no TCP
    exposure); TCP is opt-in for multi-host clients.
    """
    if addr.startswith("unix:"):
        path = addr[len("unix:"):]
        if not path:
            raise ValueError("unix: address needs a socket path")
        return socket.AF_UNIX, path
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"address {addr!r}: want unix:<path> or <host>:<port>"
        )
    try:
        return socket.AF_INET, (host, int(port))
    except ValueError:
        raise ValueError(f"address {addr!r}: port {port!r} is not an "
                         "integer") from None


def connect(addr: str, timeout: Optional[float] = None) -> socket.socket:
    family, target = parse_address(addr)
    sock = socket.socket(family, socket.SOCK_STREAM)
    if timeout is not None:
        sock.settimeout(timeout)
    sock.connect(target)
    return sock
