"""Front-end router: ring-placed forwarding, failover, hedging, shed.

The fleet's query path (docs/SERVING.md "Fleet"): a query for graph
``g`` goes to the first live ring owner of ``g``'s content digest; on a
connection error, an injected ``net_drop``, or a replica answering with
the transport-wrapped ``TransientError``, the router *fails over* to
the next ring member — same preference walk on every node, so there is
nothing to coordinate.  Stragglers are hedged through the existing
client hedge path (a second connection races the first; results are
deterministic, so either answer is THE answer).  Saturation is not
failure-masked: a replica answering ``BackpressureError`` is counted
and skipped, and only when EVERY live owner is saturated does the
router shed the query with the same typed ``BackpressureError`` — the
fleet-level admission contract (exit 7, docs/RESILIENCE.md).

Deterministic failure taxonomy is preserved through failover: an
``InputError`` or ``PoisonQueryError`` from a replica is the *query's*
fault and re-raising it from another replica would give the same
answer, so those propagate immediately without burning failover
attempts.

Chaos seam: every forwarding attempt to replica ``i`` trips fault site
``route<i>`` — ``net_drop`` kills the attempt before any bytes move
(failover rehearsal), ``replica_slow`` stalls it (hedge rehearsal).
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Set

from ..runtime.supervisor import (
    BackpressureError,
    InputError,
    MsbfsError,
    RetryPolicy,
    TransientError,
)
from ..utils import faults
from . import protocol
from .client import MsbfsClient, ServerError
from .ring import PlacementRing


class FleetRouter:
    """Stateless-per-query forwarding over a placement ring.

    ``addresses`` maps ring member name -> daemon address; ``digests``
    maps graph name -> content digest (the ring key); ``alive_fn``
    returns the currently-ready member set (None routes over full
    membership — static placement).  Each attempt uses a fresh
    connection with NO client-side reconnect retries: the ring walk IS
    the retry loop, and lockstep reconnect storms are the failure mode
    the fleet exists to avoid.
    """

    def __init__(
        self,
        ring: PlacementRing,
        addresses: Dict[str, str],
        digests: Dict[str, str],
        alive_fn=None,
        timeout: float = 300.0,
        hedge_after_s: Optional[float] = None,
    ):
        missing = [m for m in ring.members if m not in addresses]
        if missing:
            raise ValueError(f"ring members without addresses: {missing}")
        self.ring = ring
        self.addresses = dict(addresses)
        self.digests = dict(digests)
        self.alive_fn = alive_fn
        self.timeout = float(timeout)
        self.hedge_after_s = hedge_after_s
        self._index = {m: i for i, m in enumerate(ring.members)}
        self._lock = threading.Lock()
        self._stats = {
            "routed": 0,
            "failovers": 0,
            "net_drops": 0,
            "hedged": 0,
            "shed": 0,
            "per_replica": {m: 0 for m in ring.members},
        }

    @classmethod
    def for_fleet(cls, supervisor, **kw) -> "FleetRouter":
        """Router over a live :class:`~.fleet.FleetSupervisor`: shares
        its digest table (registrations made after construction are
        visible) and routes only to ready replicas."""
        router = cls(
            ring=supervisor.ring,
            addresses={r.name: r.address for r in supervisor.replicas},
            digests=supervisor.digests,
            alive_fn=supervisor.ready_names,
            **kw,
        )
        # The constructor snapshots its digests (static placement); a
        # fleet router must instead share the supervisor's table so
        # graphs registered after construction route immediately — the
        # `msbfs fleet` boot order is router first, -g registrations
        # second.
        router.digests = supervisor.digests
        return router

    def _bump(self, key: str, member: Optional[str] = None) -> None:
        with self._lock:
            self._stats[key] += 1
            if member is not None:
                self._stats["per_replica"][member] += 1

    # ---- query path -------------------------------------------------------
    def owners_for(self, graph: str) -> List[str]:
        digest = self.digests.get(graph)
        if digest is None:
            raise InputError(
                f"no graph registered as {graph!r} in the fleet "
                f"(have: {', '.join(sorted(self.digests)) or 'none'})"
            )
        alive = self.alive_fn() if self.alive_fn is not None else None
        return self.ring.owners(digest, alive=alive)

    def query(
        self,
        queries: Sequence[Sequence[int]],
        graph: str = "default",
        deadline_s: Optional[float] = None,
        hedge_after_s: Optional[float] = None,
    ) -> dict:
        """Forward one query batch; returns the replica's response dict
        plus routing metadata (``replica``, ``failovers``)."""
        owners = self.owners_for(graph)
        if not owners:
            raise TransientError(
                f"no live owner for graph {graph!r} "
                "(fleet booting or all owners down)"
            )
        if hedge_after_s is None:
            hedge_after_s = self.hedge_after_s
        start = time.monotonic()
        saturated = 0
        last_err: Optional[Exception] = None
        failovers = 0
        for member in owners:
            remaining = None
            if deadline_s is not None:
                remaining = deadline_s - (time.monotonic() - start)
                if remaining <= 0:
                    break  # out of budget: report shed/transient below
            try:
                faults.trip(f"route{self._index[member]}")
            except faults.SimulatedNetDrop as drop:
                self._bump("net_drops")
                failovers += 1
                last_err = drop
                continue
            try:
                with MsbfsClient(
                    self.addresses[member],
                    timeout=(
                        self.timeout if remaining is None
                        else min(self.timeout, remaining)
                    ),
                    retry=_NO_RETRY,
                ) as client:
                    out = client.query(
                        queries,
                        graph=graph,
                        deadline_s=remaining,
                        hedge_after_s=hedge_after_s,
                    )
            except ServerError as err:
                if err.type_name == "BackpressureError":
                    saturated += 1
                    failovers += 1
                    last_err = err
                    continue
                if err.type_name == "TransientError":
                    # Transport loss, drain refusal, injected transient:
                    # the next owner holds the same graph — walk on.
                    failovers += 1
                    last_err = err
                    continue
                raise  # deterministic failures belong to the query
            except (protocol.ProtocolError, OSError, socket.timeout) as exc:
                failovers += 1
                last_err = exc
                continue
            self._bump("routed", member)
            if failovers:
                with self._lock:
                    self._stats["failovers"] += failovers
            if out.get("hedged"):
                self._bump("hedged")
            out = dict(out)
            out["replica"] = member
            out["failovers"] = failovers
            return out
        if saturated and saturated >= failovers:
            # Every owner we reached said "queue full": the fleet is
            # saturated, and masking that as a retryable transient would
            # invite the retry storm backpressure exists to stop.
            self._bump("shed")
            raise BackpressureError(
                f"all {saturated} live owner(s) of graph {graph!r} are "
                "saturated; retry with backoff or grow the fleet"
            )
        raise TransientError(
            f"no owner of graph {graph!r} answered "
            f"({failovers} attempt(s); last: {last_err})"
        )

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["per_replica"] = dict(self._stats["per_replica"])
        return out


# Routed attempts never retry in place — the ring walk is the retry.
_NO_RETRY = RetryPolicy(max_retries=0)


class FleetFrontend:
    """The fleet's single client-facing socket: speaks the existing
    frame protocol, so the stock ``msbfs query`` client talks to a
    fleet exactly as it talks to one daemon.  Verbs: ``ping``,
    ``health`` (fleet topology + per-replica states), ``load``
    (ring-placed registration via the supervisor), ``query`` (routed),
    ``stats`` (router + fleet counters), ``shutdown``.

    Thread names use the ``msbfs-fleet-`` prefix (distinct from the
    single-daemon ledger in tests/conftest.py, which must keep failing
    on leaked *replica* threads, not the front end's).
    """

    def __init__(self, listen: str, router: FleetRouter, supervisor=None):
        self.listen = listen
        self.router = router
        self.supervisor = supervisor
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    def start(self) -> None:
        family, target = protocol.parse_address(self.listen)
        self._sock = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_INET:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if family == socket.AF_UNIX and isinstance(target, str):
            if os.path.exists(target):
                os.unlink(target)  # front end owns its path (no journal)
        self._sock.bind(target)
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="msbfs-fleet-accept", daemon=True
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10.0)
            self._accept_thread = None
        family, target = protocol.parse_address(self.listen)
        if family == socket.AF_UNIX and isinstance(target, str):
            try:
                os.unlink(target)
            except OSError:
                pass

    def __enter__(self) -> "FleetFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            threading.Thread(
                target=self._serve_connection,
                args=(conn,),
                name="msbfs-fleet-conn",
                daemon=True,
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopping.is_set():
                try:
                    request = protocol.recv_frame(conn)
                except (protocol.ProtocolError, OSError):
                    return
                if request is None:
                    return
                response = self.handle(request)
                try:
                    protocol.send_frame(conn, response)
                except OSError:
                    return
                if request.get("op") == "shutdown":
                    self.stop()
                    return

    def handle(self, request: dict) -> dict:
        op = request.get("op")
        try:
            if op == "ping":
                return {"ok": True, "op": "ping", "pid": os.getpid()}
            if op == "health":
                return self._op_health()
            if op == "stats":
                return {"ok": True, "op": "stats", "stats": self._op_stats()}
            if op == "query":
                out = self.router.query(
                    request.get("queries") or [],
                    graph=request.get("graph", "default"),
                    deadline_s=request.get("deadline_s"),
                    hedge_after_s=request.get("hedge_after_s"),
                )
                out["ok"] = True
                return out
            if op == "load":
                if self.supervisor is None:
                    raise InputError(
                        "this front end has no supervisor; register "
                        "graphs on the replicas directly"
                    )
                name = request.get("graph", "default")
                owners = self.supervisor.register(
                    name, request.get("path", "")
                )
                return {
                    "ok": True,
                    "op": "load",
                    "graph": {
                        "name": name,
                        "owners": owners,
                        "hash": self.supervisor.digests[name],
                    },
                }
            if op == "shutdown":
                return {"ok": True, "op": "shutdown"}
            raise InputError(f"unknown op {op!r}")
        except ServerError as err:
            # A replica's typed verdict passes through unchanged.
            return {
                "ok": False,
                "error": {
                    "type": err.type_name,
                    "message": str(err),
                    "exit_code": err.exit_code,
                },
            }
        except MsbfsError as err:
            return protocol.error_body(err)
        except Exception as err:  # noqa: BLE001 — front end must answer
            return protocol.error_body(MsbfsError(str(err)))

    def _op_health(self) -> dict:
        fleet = (
            self.supervisor.status() if self.supervisor is not None else {}
        )
        ready = bool(fleet.get("ready")) if fleet else True
        graphs = fleet.get("graphs", {})
        routable = all(g["live_owners"] for g in graphs.values())
        return {
            "ok": True,
            "op": "health",
            "pid": os.getpid(),
            "ready": ready and routable,
            "fleet": fleet,
        }

    def _op_stats(self) -> dict:
        out = {"router": self.router.stats()}
        if self.supervisor is not None:
            out["fleet"] = self.supervisor.status()
        return out


def fleet_main(argv: Optional[List[str]] = None) -> int:
    """``msbfs-tpu fleet`` / ``python main.py fleet`` entry point: boot
    N replicas + the front-end router on one command."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="msbfs-tpu fleet",
        description="Replicated msbfs serving fleet: N replica daemons, "
        "rendezvous placement, failover router (docs/SERVING.md)",
    )
    ap.add_argument(
        "--listen",
        default=os.environ.get(
            "MSBFS_FLEET_LISTEN", "unix:/tmp/msbfs-fleet.sock"
        ),
        help="front-end address (default unix:/tmp/msbfs-fleet.sock)",
    )
    ap.add_argument("--size", type=int, default=3,
                    help="replica count (default 3)")
    ap.add_argument("--replication", type=int, default=2,
                    help="owners per graph (default 2)")
    ap.add_argument(
        "--base-dir",
        default=None,
        help="directory for replica sockets/journals/logs "
        "(default MSBFS_FLEET_DIR or /tmp/msbfs-fleet)",
    )
    ap.add_argument(
        "-g", "--graph", action="append", default=[],
        metavar="[NAME=]PATH",
        help="register a graph at startup (repeatable)",
    )
    ap.add_argument("--heartbeat-ms", type=float, default=500.0,
                    help="replica heartbeat period (default 500)")
    ap.add_argument("--wait-ready-s", type=float, default=240.0,
                    help="block until all replicas are ready (0 skips)")
    args = ap.parse_args(argv)

    from .fleet import FleetSupervisor

    plan = faults.FaultPlan.from_env()
    faults.activate(plan)
    base_dir = args.base_dir or os.environ.get(
        "MSBFS_FLEET_DIR", "/tmp/msbfs-fleet"
    )
    try:
        supervisor = FleetSupervisor(
            size=args.size,
            base_dir=base_dir,
            replication=args.replication,
            heartbeat_s=args.heartbeat_ms / 1000.0,
        )
        supervisor.start(
            wait_ready_s=args.wait_ready_s or None
        )
    except (MsbfsError, OSError, ValueError) as err:
        print(f"msbfs fleet: {err}", file=sys.stderr)
        return getattr(err, "exit_code", 1)
    router = FleetRouter.for_fleet(supervisor)
    frontend = FleetFrontend(args.listen, router, supervisor=supervisor)
    try:
        for spec in args.graph:
            name, sep, path = spec.partition("=")
            if not sep:
                name, path = "default", spec
            supervisor.register(name, path)
        frontend.start()
    except (MsbfsError, OSError, ValueError) as err:
        print(f"msbfs fleet: {err}", file=sys.stderr)
        supervisor.stop()
        return getattr(err, "exit_code", 1)
    import signal as _signal

    def _on_signal(signum, frame):  # noqa: ARG001 — signal signature
        frontend.stop()

    _signal.signal(_signal.SIGTERM, _on_signal)
    _signal.signal(_signal.SIGINT, _on_signal)
    names = ", ".join(sorted(supervisor.graphs)) or "none (use load)"
    print(
        f"msbfs fleet: {args.size} replicas (replication "
        f"{supervisor.ring.replication}) under {base_dir}; front end on "
        f"{args.listen}; graphs: {names}",
        file=sys.stderr,
    )
    try:
        while not frontend._stopping.is_set():
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        supervisor.stop(drain=True)
    print("msbfs fleet: stopped", file=sys.stderr)
    return 0


__all__ = [
    "FleetFrontend",
    "FleetRouter",
    "fleet_main",
]
