"""Dense-adjacency frontier engine: BFS expansion on the MXU.

For graphs whose adjacency fits HBM densely (n up to ~16k), one BFS level is
a boolean-semiring mat-vec: reached = (frontier @ A) > 0.  Batched over K
queries the level becomes a (K, n) @ (n, n) matmul in bfloat16 — the frontier
expansion runs on the 128x128 systolic array instead of gather/scatter units,
which is the TPU-native answer to the reference's one-thread-per-vertex
kernel (main.cu:16-38) for small/medium graphs.  Exactness: entries are 0/1,
products are exact in bf16, and accumulation uses float32
(preferred_element_type), exact for any degree < 2^24; only the > 0 test is
consumed.

Semantics are identical to the CSR engine (same init, same level loop, same
convergence), so it plugs into :func:`..ops.bfs.multi_source_bfs` via the
``expand`` hook / ``graph.expand_frontier``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models.csr import CSRGraph

LANE = 128  # last-dim tile of the MXU/VPU


@jax.tree_util.register_pytree_node_class
class DenseGraph:
    """(n_pad, n_pad) bfloat16 0/1 adjacency, n_pad rounded up to 128.

    ``adjacency[u, v] == 1`` iff directed slot u->v exists in the CSR
    (duplicates/self-loops collapse — harmless for reachability).  Padding
    rows/cols are zero: padded vertices have no edges, are never sources,
    and their distance stays -1, so they never contribute to F(U).
    """

    def __init__(self, adjacency: jax.Array, n: int):
        self.adjacency = adjacency
        self.n = int(n)

    @property
    def n_pad(self) -> int:
        return self.adjacency.shape[0]

    @staticmethod
    def from_host(g: CSRGraph, sharding=None) -> "DenseGraph":
        n_pad = max(LANE, -(-g.n // LANE) * LANE)
        # Build directly in bf16 (ml_dtypes is numpy-compatible): no float32
        # intermediate, peak host memory = the n_pad^2 matrix itself.
        adj = np.zeros((n_pad, n_pad), dtype=jnp.bfloat16)
        src = np.repeat(np.arange(g.n, dtype=np.int64), g.degrees.astype(np.int64))
        adj[src, g.col_indices.astype(np.int64)] = 1.0
        put = (
            (lambda x: jax.device_put(x, sharding))
            if sharding is not None
            else jnp.asarray
        )
        return DenseGraph(put(adj), g.n)

    def expand_frontier(self, dist: jax.Array, level: jax.Array) -> jax.Array:
        """One level on the MXU; returns the newly-reached bool mask (n_pad,).

        Under vmap over queries the per-query mat-vec batches into a single
        (K, n_pad) @ (n_pad, n_pad) matmul per level.
        """
        frontier = (dist == level).astype(jnp.bfloat16)
        hits = jnp.matmul(
            frontier, self.adjacency, preferred_element_type=jnp.float32
        )
        return (dist == -1) & (hits > 0)

    def tree_flatten(self):
        return (self.adjacency,), (self.n,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    def __repr__(self):
        return f"DenseGraph(n={self.n}, n_pad={self.n_pad})"
