"""Single-device Engine: end-to-end query pipeline vs oracle, chunking."""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    Engine,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, edges, q)) for q in queries]


@pytest.fixture(scope="module")
def setup():
    n, edges = generators.gnm_edges(120, 420, seed=31)
    queries = generators.random_queries(n, 11, max_group=6, seed=32)
    queries[3] = np.zeros(0, dtype=np.int32)  # empty group -> F = 0, wins
    padded = pad_queries(queries)
    return n, edges, queries, padded


def test_f_values_match_oracle(setup):
    n, edges, queries, padded = setup
    eng = Engine(CSRGraph.from_edges(n, edges).to_device())
    got = np.asarray(eng.f_values(padded))
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("chunk", [1, 2, 4, 16])
def test_chunking_invariant(setup, chunk):
    n, edges, queries, padded = setup
    eng = Engine(CSRGraph.from_edges(n, edges).to_device(), query_chunk=chunk)
    got = np.asarray(eng.f_values(padded))
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(got, want)


def test_best_matches_oracle(setup):
    n, edges, queries, padded = setup
    eng = Engine(CSRGraph.from_edges(n, edges).to_device())
    min_f, min_k = eng.best(padded)
    assert (min_f, min_k) == oracle_best(oracle_f_values(n, edges, queries))
