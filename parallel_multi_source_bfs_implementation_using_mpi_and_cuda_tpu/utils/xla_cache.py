"""Persistent XLA compilation cache setup (shared by cli.py and bench.py).

Repeat runs skip the tens-of-seconds BFS program compile — the analog of
the reference's nvcc-precompiled kernels.  ``MSBFS_CACHE_DIR=`` (empty)
disables; unset uses ``~/.cache/msbfs_tpu/xla-<host fingerprint>``.

The fingerprint matters: XLA:CPU serializes AOT executables specialized to
the compiling machine's CPU features and will LOAD a mismatched entry with
only a warning — observed to SEGFAULT the process mid-suite when this
repo's cache dir was reused across differently-featured hosts (round 4;
the loader even warns "This could lead to execution errors such as
SIGILL").  Keying the directory by machine + CPU flags makes a foreign
entry unloadable by construction.
"""

from __future__ import annotations

import hashlib
import os
import platform
import sys

_noticed = False


def _notice(reason: str) -> None:
    """One line, once per process, on stderr: an operator debugging cold
    compiles on every daemon restart needs to SEE that the persistent
    cache is off and why (docs/SERVING.md ops runbook); repeating it per
    configure call would spam in-process test suites."""
    global _noticed
    if _noticed:
        return
    _noticed = True
    print(f"persistent XLA cache disabled: {reason}", file=sys.stderr)


def _host_fingerprint() -> str:
    bits = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    bits.append(line.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def configure_compilation_cache() -> None:
    import jax

    # CPU backends skip the persistent cache entirely: XLA:CPU AOT
    # executable (de)serialization SEGFAULTED mid-suite on the round-4
    # shard_map chunk programs (cache read on one host, cache write on
    # another), and the compiles it would save are TPU-sized (tens of
    # seconds), not CPU-sized.  The accelerator path keeps the cache —
    # that is where the reference's nvcc-precompiled analogy matters.
    if jax.default_backend() == "cpu":
        _notice(
            "cpu backend (AOT executable (de)serialization is unsafe "
            "here; compiles are per-process)"
        )
        return

    from . import knobs

    cache_dir = knobs.raw(
        "MSBFS_CACHE_DIR",
        os.path.join(
            os.path.expanduser("~"),
            ".cache",
            "msbfs_tpu",
            f"xla-{_host_fingerprint()}",
        ),
    )
    if not cache_dir:
        _notice("MSBFS_CACHE_DIR is set empty")
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except (OSError, AttributeError) as exc:
        # Unwritable cache dir or older jax: compile every run.
        _notice(f"{cache_dir} unusable ({exc})")
