#!/bin/bash
# Round-4 TPU measurement runbook — run the moment the axon tunnel is up.
# (Probe: timeout 110 python -c "import jax; print(jax.devices())".)
# Fired automatically by benchmarks/tpu_watcher.sh on first tunnel
# recovery (VERDICT r3 "Next round" item 1).  Every step tees its raw
# output into benchmarks/raw_r4/ so the numbers that land in BASELINE.md
# have committed artifacts behind them (VERDICT r3 "What's weak" item 1).
# Each step is independently restartable; the persistent XLA compilation
# cache makes repeats cheap.
set -uo pipefail
cd "$(dirname "$0")/.."
RAW=benchmarks/raw_r4
mkdir -p "$RAW"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
echo "runbook start $(stamp)" | tee "$RAW/runbook_meta.txt"
python -c "import jax; print('jax', jax.__version__)" 2>/dev/null \
    | tee -a "$RAW/runbook_meta.txt"
pip show libtpu libtpu-nightly 2>/dev/null | grep -E '^(Name|Version)' \
    | tee -a "$RAW/runbook_meta.txt"

echo "== 1. headline bench (the driver artifact twin; default = the"
echo "      2,2c,4,1 config sweep, each with its own value/error)"
python bench.py 2> "$RAW/bench_headline.stderr" \
    | tee "$RAW/bench_headline.json"

echo "== 2. RMAT-24 (the BASELINE.json target scale; single-config mode)"
BENCH_CONFIGS= BENCH_SCALE=24 BENCH_REPEATS=2 BENCH_EXTRA_KS= python bench.py \
    2> "$RAW/bench_rmat24.stderr" | tee "$RAW/bench_rmat24.json"

echo "== 3. estimate_hbm_bytes ground truth via memory_stats"
MSBFS_TEST_TPU=1 python -m pytest \
    tests/test_hbm_estimate.py::test_estimate_brackets_memory_stats -q \
    2>&1 | tee "$RAW/hbm_ground_truth.txt"

echo "== 4. Pallas/Mosaic gather re-probe (VERDICT item 4; version-stamped)"
timeout 600 python benchmarks/pallas_gather_probe.py \
    2>&1 | tee "$RAW/pallas_gather_probe.txt"

echo "== 5. road-class single chip (config 4, push engine)"
timeout 1800 python benchmarks/run_baseline.py --config 4 \
    2>&1 | tee "$RAW/config4_road.txt"

echo "== 6. chunked bitbell on a road graph (always-chunk cost check)"
timeout 1800 python benchmarks/exp_chunk_cost.py \
    2>&1 | tee "$RAW/chunk_cost.txt" || true

echo "== 7. config 6: vertex-sharded road — owner-partitioned push vs bitbell"
# Decides whether the round-4 auto-routing (road-class + vshard -> sharded
# push) holds on real ICI; on the CPU mesh the pull side wins because the
# 'collectives' are free there (docs/PERF_NOTES.md).
timeout 1800 python benchmarks/run_baseline.py --config 6 \
    2>&1 | tee "$RAW/config6_sharded.txt" || true

echo "runbook end $(stamp)" | tee -a "$RAW/runbook_meta.txt"
echo "== done; raw artifacts in $RAW — fold into BASELINE.md + PERF_NOTES"
