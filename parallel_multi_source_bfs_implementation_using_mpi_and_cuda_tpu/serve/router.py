"""Front-end router: ring-placed forwarding, failover, hedging, shed.

The fleet's query path (docs/SERVING.md "Fleet"): a query for graph
``g`` goes to the first live ring owner of ``g``'s content digest; on a
connection error, an injected ``net_drop``, or a replica answering with
the transport-wrapped ``TransientError``, the router *fails over* to
the next ring member — same preference walk on every node, so there is
nothing to coordinate.  Stragglers are hedged through the existing
client hedge path (a second connection races the first; results are
deterministic, so either answer is THE answer).  Saturation is not
failure-masked: a replica answering ``BackpressureError`` is counted
and skipped, and only when EVERY live owner is saturated does the
router shed the query with the same typed ``BackpressureError`` — the
fleet-level admission contract (exit 7, docs/RESILIENCE.md).

Deterministic failure taxonomy is preserved through failover: an
``InputError`` or ``PoisonQueryError`` from a replica is the *query's*
fault and re-raising it from another replica would give the same
answer, so those propagate immediately without burning failover
attempts.

Chaos seam: every forwarding attempt to replica ``i`` trips fault site
``route<i>`` — ``net_drop`` kills the attempt before any bytes move
(failover rehearsal), ``replica_slow`` stalls it (hedge rehearsal),
``wire_corrupt`` taints the next frame sent on the attempt's thread
(crc rehearsal: the replica's checksum rejects it, the failover walk
recovers).

Cross-replica voting (docs/RESILIENCE.md "Silent data corruption"): a
sampled fraction of answered queries (``MSBFS_VOTE`` / ``vote_rate``)
is shadow-routed to the NEXT live ring owner and the two answers'
:func:`~..ops.certify.fold_digest` fingerprints are compared.  The
graphs and query batches are identical and every engine is
deterministic, so the digests must agree; a mismatch means one replica
served a silently corrupt answer.  The router then recomputes on a
third owner to form a majority, quarantines the outvoted replica via
``quarantine_fn`` (the fleet supervisor's kill-and-let-heartbeat-heal
path), and returns the majority answer.  With no third opinion
available the vote is counted ``vote_unresolved``, the shadow replica
is quarantined (the ring-preferred primary is the better bet), and the
primary's answer stands.
"""

from __future__ import annotations

import os
import queue
import re
import secrets
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..ops.certify import fold_digest
from ..runtime.supervisor import (
    BackpressureError,
    FencedError,
    InputError,
    MsbfsError,
    RetryPolicy,
    ShardUnavailableError,
    TransientError,
)
from ..utils import faults, knobs
from ..utils.telemetry import (
    Histogram,
    TraceContext,
    instant,
    log_line,
    record_flight,
    span,
    use_trace,
)
from . import observe, protocol
from .client import MsbfsClient, ServerError
from .ring import PlacementRing
from .shards import ShardPlan, or_merge_fragments, scatter_frontier


def vote_rate_from_env() -> float:
    """``MSBFS_VOTE`` -> [0, 1] shadow-vote sampling rate.  Same parse
    convention as the server's ``MSBFS_AUDIT``: ``off``/``0``/unset
    disable, ``full``/``on``/``1`` vote every query, a float samples;
    malformed values fall back to off (the repo-wide knob convention).
    """
    raw = knobs.raw("MSBFS_VOTE", "").strip().lower()
    if raw in ("", "off", "0"):
        return 0.0
    if raw in ("full", "on", "1"):
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def _answer_digest(out: dict) -> int:
    """Fingerprint of the answer-bearing response fields.  Routing
    metadata (latency, bucket, replica) legitimately differs between
    replicas and is excluded; F values and the argmin selection must be
    bit-identical — the engines are deterministic functions of (graph
    digest, query batch)."""
    f = np.asarray(out.get("f_values", []), dtype=np.int64)
    best = np.asarray(
        [out.get("min_f", -1), out.get("min_k", -1),
         1 if out.get("weighted") else 0],
        dtype=np.int64,
    )
    return fold_digest(f, best)


class FleetRouter:
    """Stateless-per-query forwarding over a placement ring.

    ``addresses`` maps ring member name -> daemon address; ``digests``
    maps graph name -> content digest (the ring key); ``alive_fn``
    returns the currently-ready member set (None routes over full
    membership — static placement).  Each attempt uses a fresh
    connection with NO client-side reconnect retries: the ring walk IS
    the retry loop, and lockstep reconnect storms are the failure mode
    the fleet exists to avoid.
    """

    def __init__(
        self,
        ring: PlacementRing,
        addresses: Dict[str, str],
        digests: Dict[str, str],
        alive_fn=None,
        timeout: float = 300.0,
        hedge_after_s: Optional[float] = None,
        vote_rate: Optional[float] = None,
        quarantine_fn=None,
        brownout_fn=None,
        shard_plans: Optional[Dict[str, ShardPlan]] = None,
        shard_ring: Optional[PlacementRing] = None,
    ):
        missing = [m for m in ring.members if m not in addresses]
        if missing:
            raise ValueError(f"ring members without addresses: {missing}")
        self.ring = ring
        self.addresses = dict(addresses)
        self.digests = dict(digests)
        self.alive_fn = alive_fn
        self.timeout = float(timeout)
        self.hedge_after_s = hedge_after_s
        self.vote_rate = (
            vote_rate_from_env() if vote_rate is None
            else min(max(float(vote_rate), 0.0), 1.0)
        )
        self.quarantine_fn = quarantine_fn
        # Brownout hook (docs/SERVING.md "Autoscaling & overload"): a
        # callable answering "suppress voting right now?".  Rung 1 of
        # the ladder turns the vote's shadow traffic off router-side.
        self.brownout_fn = brownout_fn
        # Sharded graphs (serve/shards.py): parent name -> ShardPlan and
        # the shard-replication ring.  A fleet router shares the
        # supervisor's live tables (for_fleet below); both empty means
        # every graph routes whole — the scatter path never engages.
        self.shard_plans: Dict[str, ShardPlan] = (
            shard_plans if shard_plans is not None else {}
        )
        self.shard_ring = shard_ring
        self.shard_fragment_timeout_s = knobs.get_float(
            "MSBFS_SHARD_FRAGMENT_TIMEOUT_S", 30.0
        )
        self.shard_hedge_ms = knobs.get_float("MSBFS_SHARD_HEDGE_MS", 0.0)
        self._vote_acc = 0.0
        self._index = {m: i for i, m in enumerate(ring.members)}
        self._lock = threading.Lock()
        self._stats = {
            "routed": 0,
            "failovers": 0,
            "net_drops": 0,
            "fenced": 0,
            "mutations_routed": 0,
            "hedged": 0,
            "shed": 0,
            "votes": 0,
            "votes_suppressed": 0,
            "vote_mismatches": 0,
            "vote_unresolved": 0,
            "quarantined": 0,
            "scatter_queries": 0,
            "scatter_rounds": 0,
            "scatter_fragments": 0,
            "scatter_retries": 0,
            "scatter_degraded": 0,
            "scatter_shard_lost": 0,
            "per_replica": {m: 0 for m in ring.members},
        }

    @classmethod
    def for_fleet(cls, supervisor, **kw) -> "FleetRouter":
        """Router over a live :class:`~.fleet.FleetSupervisor`: shares
        its digest table (registrations made after construction are
        visible), routes only to ready replicas, and wires vote
        quarantine to the supervisor's kill-and-heal path (duck-typed
        like every other read here — a supervisor without one simply
        gets voting without quarantine)."""
        kw.setdefault(
            "quarantine_fn", getattr(supervisor, "quarantine", None)
        )
        ladder = getattr(supervisor, "brownout", None)
        if ladder is not None:
            kw.setdefault("brownout_fn", ladder.vote_suppressed)
        router = cls(
            ring=supervisor.ring,
            addresses={r.name: r.address for r in supervisor.replicas},
            digests=supervisor.digests,
            alive_fn=supervisor.ready_names,
            **kw,
        )
        # The constructor snapshots its digests and addresses (static
        # placement); a fleet router must instead share the supervisor's
        # live tables so graphs registered after construction route
        # immediately (the `msbfs fleet` boot order is router first,
        # -g registrations second) and replicas added or removed by the
        # autoscaler are routable the moment the ring knows them.
        router.digests = supervisor.digests
        addresses = getattr(supervisor, "addresses", None)
        if addresses is not None:
            router.addresses = addresses
        # Same live-share for shard topology: a graph sharded after
        # construction scatters immediately, and the shard ring tracks
        # elastic membership through the supervisor's mirroring.
        plans = getattr(supervisor, "shard_plans", None)
        if plans is not None:
            router.shard_plans = plans
        sring = getattr(supervisor, "shard_ring", None)
        if sring is not None:
            router.shard_ring = sring
        return router

    def _bump(self, key: str, member: Optional[str] = None) -> None:
        with self._lock:
            self._stats[key] += 1
            if member is not None:
                per = self._stats["per_replica"]
                per[member] = per.get(member, 0) + 1

    _SLOT_RE = re.compile(r"r(\d+)\Z")

    def _route_index(self, member: str) -> int:
        """Chaos-site index for a member.  Supervisor slot names encode
        their index (``r<i>`` -> ``route<i>``), which keeps fault sites
        stable across elastic membership churn; anything else gets the
        next free index on first sight."""
        with self._lock:
            i = self._index.get(member)
            if i is None:
                m = self._SLOT_RE.match(member)
                i = int(m.group(1)) if m else len(self._index)
                self._index[member] = i
                self._stats["per_replica"].setdefault(member, 0)
            return i

    def _epoch(self) -> Optional[int]:
        """The membership epoch every routed frame is stamped with
        (docs/SERVING.md "Cross-machine transport & fencing").  Rings
        predating the epoch field stamp nothing — tolerated-absent."""
        epoch = getattr(self.ring, "epoch", None)
        return None if epoch is None else int(epoch)

    # ---- query path -------------------------------------------------------
    def owners_for(self, graph: str) -> List[str]:
        digest = self.digests.get(graph)
        if digest is None:
            raise InputError(
                f"no graph registered as {graph!r} in the fleet "
                f"(have: {', '.join(sorted(self.digests)) or 'none'})"
            )
        alive = self.alive_fn() if self.alive_fn is not None else None
        return self.ring.owners(digest, alive=alive)

    def query(
        self,
        queries: Sequence[Sequence[int]],
        graph: str = "default",
        deadline_s: Optional[float] = None,
        hedge_after_s: Optional[float] = None,
        priority: Optional[str] = None,
        client_id: Optional[str] = None,
        weighted: bool = False,
        degraded: bool = False,
    ) -> dict:
        """Forward one query batch; returns the replica's response dict
        plus routing metadata (``replica``, ``failovers``).  The
        admission-control fields (``priority``, ``client_id``) and the
        ``weighted`` answer mode ride through unchanged — shedding
        decisions belong to the replica's batcher, not the router.

        A graph with a shard plan takes the scatter/gather path instead
        (docs/SERVING.md "Sharded graphs"); ``degraded`` is the client's
        opt-in to a *partial* answer when every copy of some shard is
        gone — without it, total shard loss is the typed
        :class:`~..runtime.supervisor.ShardUnavailableError` (exit 11),
        never a silently wrong F."""
        plan = self.shard_plans.get(graph)
        if plan is not None:
            with span("route.scatter", graph=graph) as sp:
                if weighted:
                    raise InputError(
                        f"graph {graph!r} is served sharded; weighted "
                        "distance-to-set is whole-graph only (raise "
                        "MSBFS_SHARD_MAX_BYTES to serve it whole)"
                    )
                out = self._scatter_query(
                    graph,
                    plan,
                    queries,
                    deadline_s=deadline_s,
                    degraded=degraded,
                )
                sp.set(
                    rounds=int(out.get("rounds", 0)),
                    degraded=bool(out.get("degraded")),
                )
                return out
        with span("route.query", graph=graph) as sp:
            out = self._query_walk(
                queries,
                graph=graph,
                deadline_s=deadline_s,
                hedge_after_s=hedge_after_s,
                priority=priority,
                client_id=client_id,
                weighted=weighted,
            )
            sp.set(
                replica=out.get("replica", ""),
                failovers=int(out.get("failovers", 0)),
                voted=bool(out.get("voted")),
            )
            return out

    def _query_walk(
        self,
        queries: Sequence[Sequence[int]],
        graph: str = "default",
        deadline_s: Optional[float] = None,
        hedge_after_s: Optional[float] = None,
        priority: Optional[str] = None,
        client_id: Optional[str] = None,
        weighted: bool = False,
    ) -> dict:
        owners = self.owners_for(graph)
        if not owners:
            raise TransientError(
                f"no live owner for graph {graph!r} "
                "(fleet booting or all owners down)"
            )
        if hedge_after_s is None:
            hedge_after_s = self.hedge_after_s
        start = time.monotonic()
        saturated = 0
        last_err: Optional[Exception] = None
        failovers = 0
        for member in owners:
            remaining = None
            if deadline_s is not None:
                remaining = deadline_s - (time.monotonic() - start)
                if remaining <= 0:
                    break  # out of budget: report shed/transient below
            try:
                faults.trip(f"route{self._route_index(member)}")
            except faults.SimulatedNetDrop as drop:
                self._bump("net_drops")
                failovers += 1
                last_err = drop
                continue
            address = self.addresses.get(member)
            if address is None:
                # Membership race: the member left (scale-down drain)
                # between the owners snapshot and this attempt.
                failovers += 1
                last_err = KeyError(member)
                continue
            try:
                with span(
                    "route.attempt", member=member, failover=failovers
                ), MsbfsClient(
                    address,
                    timeout=(
                        self.timeout if remaining is None
                        else min(self.timeout, remaining)
                    ),
                    retry=_NO_RETRY,
                    epoch=self._epoch(),
                ) as client:
                    out = client.query(
                        queries,
                        graph=graph,
                        deadline_s=remaining,
                        hedge_after_s=hedge_after_s,
                        priority=priority,
                        client_id=client_id,
                        weighted=weighted,
                    )
            except (faults.SimulatedNetDrop, faults.SimulatedHalfOpen) as nd:
                # Frame-level chaos fired at the protocol seam — a
                # partition cut dropped the frame mid-send, or a
                # half-open peer swallowed it and the read timed out.
                # Same failover semantics as the pre-wire drop above:
                # the replica never (usably) saw the query.
                self._bump("net_drops")
                failovers += 1
                last_err = nd
                continue
            except ServerError as err:
                if err.type_name == "BackpressureError":
                    saturated += 1
                    failovers += 1
                    last_err = err
                    continue
                if err.type_name == "TransientError":
                    # Transport loss, drain refusal, injected transient:
                    # the next owner holds the same graph — walk on.
                    failovers += 1
                    last_err = err
                    continue
                if err.type_name == "FencedError":
                    # The replica's membership view and ours disagree —
                    # usually a topology change mid-walk.  Count it and
                    # walk on: the next attempt re-reads the live ring
                    # epoch, so a healed view converges within the walk.
                    self._bump("fenced")
                    failovers += 1
                    last_err = err
                    continue
                raise  # deterministic failures belong to the query
            except (protocol.ProtocolError, OSError, socket.timeout) as exc:
                failovers += 1
                last_err = exc
                continue
            self._bump("routed", member)
            if failovers:
                with self._lock:
                    self._stats["failovers"] += failovers
            if out.get("hedged"):
                self._bump("hedged")
            out = dict(out)
            out["replica"] = member
            out["failovers"] = failovers
            if self._vote_due():
                if self._vote_suppressed():
                    # Brownout rung >= 1: the sample was due but the
                    # ladder says capacity beats redundancy right now.
                    self._bump("votes_suppressed")
                else:
                    deadline = (
                        None if deadline_s is None else start + deadline_s
                    )
                    out = self._vote(member, owners, queries, graph,
                                     deadline, out, weighted=weighted)
            return out
        if saturated and saturated >= failovers:
            # Every owner we reached said "queue full": the fleet is
            # saturated, and masking that as a retryable transient would
            # invite the retry storm backpressure exists to stop.
            self._bump("shed")
            raise BackpressureError(
                f"all {saturated} live owner(s) of graph {graph!r} are "
                "saturated; retry with backoff or grow the fleet"
            )
        raise TransientError(
            f"no owner of graph {graph!r} answered "
            f"({failovers} attempt(s); last: {last_err})"
        )

    # ---- sharded scatter/gather (docs/SERVING.md "Sharded graphs") --------
    def _scatter_query(
        self,
        graph: str,
        plan: ShardPlan,
        queries: Sequence[Sequence[int]],
        deadline_s: Optional[float] = None,
        degraded: bool = False,
    ) -> dict:
        """Level-synchronous distance-to-set over the shard fleet: each
        BFS round splits the frontier by owning shard
        (:func:`~.shards.scatter_frontier`), fans the fragments to their
        ring owners concurrently, and OR-merges the returned neighbor
        sets — the :class:`~..parallel.partition2d.Partition2D`
        row-gather/OR-merge discipline rebuilt over the wire.  Distances
        and the F objective are computed router-side exactly as the
        single daemon's engine computes them (sum of reached distances,
        lowest-index argmin tie-break), so the merged answer is
        bit-identical to the whole-graph oracle.

        A fragment whose every copy is gone raises the typed
        :class:`ShardUnavailableError` — unless the client opted into
        ``degraded``, in which case the shard is dropped for the REST of
        the query (its rows never expand), and the answer carries
        ``degraded: true`` plus ``missing_shards``: explicitly partial,
        never silently wrong."""
        if self.shard_ring is None:
            raise InputError(
                f"graph {graph!r} has a shard plan but this router has "
                "no shard ring; route through the fleet front end"
            )
        # Validation mirrors the daemon's _parse_queries bound for bound
        # so a malformed batch gets the SAME typed verdict whether the
        # graph happens to be sharded or whole.
        if not isinstance(queries, (list, tuple)) or not len(queries):
            raise InputError(
                "query needs 'queries': a non-empty list of vertex-id "
                "lists"
            )
        k = len(queries)
        n = plan.n
        start = time.monotonic()
        deadline = None if deadline_s is None else start + float(deadline_s)
        dist = np.full((k, n), -1, dtype=np.int64)
        frontier: List[np.ndarray] = []
        for qi, group in enumerate(queries):
            if not isinstance(group, (list, tuple)) or not len(group):
                raise InputError(
                    f"query group {qi} must be a non-empty list"
                )
            try:
                verts = np.unique(np.asarray(list(group), dtype=np.int64))
            except (TypeError, ValueError, OverflowError) as exc:
                raise InputError(
                    f"query group {qi}: source ids must be integers "
                    f"({exc})"
                ) from None
            if verts.min() < 0 or verts.max() >= n:
                raise InputError(
                    f"query group {qi}: source ids must be in [0, {n})"
                )
            dist[qi, verts] = 0
            frontier.append(verts)
        self._bump("scatter_queries")
        missing: Dict[int, str] = {}  # shard index -> name (degraded)
        rounds = 0
        fragments = 0
        while any(f.size for f in frontier):
            fan = {
                si: rows
                for si, rows in scatter_frontier(plan, frontier).items()
                if si not in missing
            }
            if not fan:
                break  # every live frontier row belongs to a lost shard
            results: "queue.Queue" = queue.Queue()

            def run(si: int, rows: List[List[int]], rq=results) -> None:
                try:
                    rq.put((si, "ok", self._fragment_call(
                        plan.shards[si], rows, deadline
                    )))
                except MsbfsError as err:
                    rq.put((si, "err", err))
                except Exception as err:  # noqa: BLE001 — typed or bust
                    rq.put((si, "err", MsbfsError(str(err))))

            for si, rows in sorted(fan.items()):
                threading.Thread(
                    target=run,
                    args=(si, rows),
                    name="msbfs-fleet-scatter",
                    daemon=True,
                ).start()
            outs: List[List[List[int]]] = []
            for _ in range(len(fan)):
                si, kind, payload = results.get()
                if kind == "ok":
                    outs.append(payload)
                    continue
                if isinstance(payload, ShardUnavailableError) and degraded:
                    missing[si] = plan.shards[si].name
                    self._bump("scatter_shard_lost")
                    continue
                raise payload
            fragments += len(fan)
            nxt: List[np.ndarray] = []
            for qi, cand in enumerate(or_merge_fragments(n, outs, k)):
                new = cand[dist[qi, cand] < 0] if cand.size else cand
                if new.size:
                    dist[qi, new] = rounds + 1
                nxt.append(new)
            frontier = nxt
            rounds += 1
            self._bump("scatter_rounds")
        # F and selection mirror the engine and the daemon's
        # _finish_batch exactly: f = sum of distances over REACHED
        # vertices (ops/objective.py f_of_u), argmin with the
        # lowest-index tie-break, (-1, -1) for an empty batch.
        f_vals = np.where(dist >= 0, dist, 0).sum(axis=1).astype(np.int64)
        if k:
            keyed = np.where(
                f_vals >= 0, f_vals, np.iinfo(np.int64).max
            )
            min_k = int(np.argmin(keyed))
            min_f = int(f_vals[min_k])
        else:
            min_f, min_k = -1, -1
        if missing:
            self._bump("scatter_degraded")
        return {
            "ok": True,
            "op": "query",
            "graph": graph,
            "n": int(n),
            "k": int(k),
            "f_values": [int(v) for v in f_vals],
            "min_f": min_f,
            "min_k": min_k,
            "weighted": False,
            "sharded": True,
            "shards": len(plan.shards),
            "rounds": rounds,
            "fragments": fragments,
            "degraded": bool(missing),
            "missing_shards": sorted(missing.values()),
            "latency_s": time.monotonic() - start,
        }

    def _fragment_call(self, shard, rows_frontier, deadline):
        """One shard fragment, delivered or typed: walk the shard's ring
        owners with the query walk's full failover taxonomy, one attempt
        thread at a time, racing a second copy after
        ``MSBFS_SHARD_HEDGE_MS`` when armed (the fragment analog of the
        client's straggler hedge — results are deterministic, either
        answer is THE answer, and the OR-merge is idempotent).
        ``deadline`` is absolute ``time.monotonic()``; spending it is a
        :class:`TransientError` (the copies may be fine — the budget is
        not), while exhausting every copy is the typed
        :class:`ShardUnavailableError` naming the shard."""
        alive = self.alive_fn() if self.alive_fn is not None else None
        owners = self.shard_ring.owners(shard.digest, alive=alive)
        if not owners:
            raise ShardUnavailableError(
                f"no live owner for shard {shard.name!r} (rows "
                f"[{shard.lo}, {shard.hi})): every copy is gone; "
                "re-replication converges when a member recovers",
                shards=(shard.name,),
            )
        hedge_s = (
            self.shard_hedge_ms / 1000.0 if self.shard_hedge_ms > 0 else None
        )
        results: "queue.Queue" = queue.Queue()

        def attempt(member: str) -> None:
            results.put(
                self._fragment_attempt(member, shard, rows_frontier, deadline)
            )

        launched = 0
        done = 0
        saturated = 0
        failures: List[str] = []
        while True:
            if launched < len(owners) and launched == done:
                # Walk: everything in flight has failed — next copy.
                threading.Thread(
                    target=attempt,
                    args=(owners[launched],),
                    name="msbfs-fleet-scatter",
                    daemon=True,
                ).start()
                launched += 1
            if done >= launched and launched >= len(owners):
                break
            wait = None
            if deadline is not None:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    raise TransientError(
                        f"deadline spent mid-scatter on shard "
                        f"{shard.name!r} ({done}/{launched} attempt(s) "
                        "returned)"
                    )
            if hedge_s is not None and launched < len(owners):
                wait = hedge_s if wait is None else min(wait, hedge_s)
            try:
                kind, member, payload = results.get(timeout=wait)
            except queue.Empty:
                if hedge_s is not None and launched < len(owners):
                    # Straggler: race the next copy WITHOUT abandoning
                    # the in-flight one; first success wins.
                    self._bump("hedged")
                    threading.Thread(
                        target=attempt,
                        args=(owners[launched],),
                        name="msbfs-fleet-scatter",
                        daemon=True,
                    ).start()
                    launched += 1
                continue
            done += 1
            if kind == "ok":
                if failures:
                    with self._lock:
                        self._stats["scatter_retries"] += len(failures)
                self._bump("scatter_fragments", member)
                return payload
            if kind == "raise":
                raise payload
            if kind == "backpressure":
                saturated += 1
            failures.append(member)
        if saturated and saturated >= len(failures):
            raise BackpressureError(
                f"all {saturated} live owner(s) of shard {shard.name!r} "
                "are saturated; retry with backoff or grow the fleet"
            )
        raise ShardUnavailableError(
            f"all {len(owners)} live owner(s) of shard {shard.name!r} "
            f"(rows [{shard.lo}, {shard.hi})) failed "
            f"({', '.join(failures)}): every copy is unreachable; "
            "re-replication converges when a member recovers",
            shards=(shard.name,),
        )

    def _fragment_attempt(self, member, shard, rows_frontier, deadline):
        """One owner, one wire call; never raises — the hedged walk in
        :meth:`_fragment_call` consumes ``(kind, member, payload)``
        verdicts from its attempt threads.  The taxonomy is the query
        walk's: drops/transients/fenced walk on, backpressure is
        counted, deterministic failures surface (``raise``) — except
        ``InputError``, which for ``shard_step`` can only mean the
        shard is not loaded on a freshly promoted stand-in yet
        (reconcile lag; the router validated the frontier against the
        plan before fanning out), so it walks to the surviving copy."""
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
            return ("fail", member, TransientError("deadline spent"))
        try:
            faults.trip(f"route{self._route_index(member)}")
        except faults.SimulatedNetDrop as drop:
            self._bump("net_drops")
            return ("fail", member, drop)
        address = self.addresses.get(member)
        if address is None:
            return ("fail", member, KeyError(member))
        timeout = min(self.timeout, self.shard_fragment_timeout_s)
        if remaining is not None:
            timeout = min(timeout, remaining)
        try:
            with span(
                "route.fragment", member=member, shard=shard.name
            ), MsbfsClient(
                address,
                timeout=timeout,
                retry=_NO_RETRY,
                epoch=self._epoch(),
            ) as client:
                out = client.shard_step(
                    shard.name, (shard.lo, shard.hi), rows_frontier
                )
        except (faults.SimulatedNetDrop, faults.SimulatedHalfOpen) as nd:
            self._bump("net_drops")
            return ("fail", member, nd)
        except ServerError as err:
            if err.type_name == "BackpressureError":
                return ("backpressure", member, err)
            if err.type_name == "FencedError":
                self._bump("fenced")
                return ("fail", member, err)
            if err.type_name in ("TransientError", "InputError"):
                return ("fail", member, err)
            return ("raise", member, err)
        except (protocol.ProtocolError, OSError, socket.timeout) as exc:
            return ("fail", member, exc)
        return ("ok", member, out.get("frontier_out") or [])

    # ---- mutation path ----------------------------------------------------
    def mutate(
        self,
        inserts: Sequence[Sequence[int]] = (),
        deletes: Sequence[Sequence[int]] = (),
        graph: str = "default",
        token: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> dict:
        """Replicated exactly-once mutate: apply one edge-delta batch to
        EVERY ring owner of ``graph``, in preference order, under one
        idempotency ``token`` (minted when None).  Unlike the query
        walk, failover is wrong here — a mutate must land on ALL owners
        or the replicas' version chains diverge — so an unreachable
        owner fails the call typed (TransientError) with the token in
        the message: retrying the SAME token converges, because owners
        that already applied re-ack from their dedup window while the
        missed ones apply for the first time.  Partial application is
        therefore a transient state, never a divergence."""
        if token is None:
            token = secrets.token_hex(16)
        owners = self.owners_for(graph)
        if not owners:
            raise TransientError(
                f"no live owner for graph {graph!r} "
                "(fleet booting or all owners down)"
            )
        start = time.monotonic()
        per_owner: Dict[str, dict] = {}
        with span("route.mutate", graph=graph, owners=len(owners)):
            for member in owners:
                remaining = None
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic() - start)
                    if remaining <= 0:
                        raise TransientError(
                            f"mutate deadline spent after "
                            f"{len(per_owner)}/{len(owners)} owner(s) of "
                            f"graph {graph!r}; retry with token {token!r} "
                            "to converge"
                        )
                address = self.addresses.get(member)
                if address is None:
                    raise TransientError(
                        f"owner {member} of graph {graph!r} left the "
                        f"fleet mid-mutate; retry with token {token!r}"
                    )
                try:
                    faults.trip(f"route{self._route_index(member)}")
                    with MsbfsClient(
                        address,
                        timeout=(
                            self.timeout if remaining is None
                            else min(self.timeout, remaining)
                        ),
                        retry=_NO_RETRY,
                        epoch=self._epoch(),
                    ) as client:
                        per_owner[member] = client.mutate(
                            inserts, deletes, graph=graph, token=token
                        )
                except (faults.SimulatedNetDrop,
                        faults.SimulatedHalfOpen) as drop:
                    # Trip-time drops AND frame-level chaos from the
                    # protocol seam (partition cut mid-send, half-open
                    # swallow) land here alike: the leg is lost, the
                    # token makes the retry safe.
                    self._bump("net_drops")
                    raise TransientError(
                        f"mutate to owner {member} of graph {graph!r} "
                        f"dropped ({drop}); applied to "
                        f"{sorted(per_owner)} so far — retry with token "
                        f"{token!r} to converge"
                    ) from drop
                except ServerError as err:
                    if err.type_name == "FencedError":
                        self._bump("fenced")
                    if err.type_name in ("TransientError", "FencedError",
                                         "BackpressureError"):
                        raise TransientError(
                            f"mutate to owner {member} of graph "
                            f"{graph!r} failed ({err}); applied to "
                            f"{sorted(per_owner)} so far — retry with "
                            f"token {token!r} to converge"
                        ) from err
                    raise  # InputError etc: the mutation itself is bad
                except (protocol.ProtocolError, OSError,
                        socket.timeout) as exc:
                    raise TransientError(
                        f"mutate to owner {member} of graph {graph!r} "
                        f"lost its transport ({exc}); applied to "
                        f"{sorted(per_owner)} so far — retry with token "
                        f"{token!r} to converge"
                    ) from exc
        self._bump("mutations_routed")
        primary = per_owner[owners[0]]
        return {
            "ok": True,
            "op": "mutate",
            "graph": primary.get("graph"),
            "token": token,
            "owners": owners,
            "version": primary.get("version"),
            "digest": primary.get("digest"),
            "applied": primary.get("applied"),
            "deduplicated": bool(primary.get("deduplicated")),
            "per_owner": {
                m: {
                    "version": r.get("version"),
                    "digest": r.get("digest"),
                    "deduplicated": bool(r.get("deduplicated")),
                }
                for m, r in per_owner.items()
            },
        }

    # ---- cross-replica voting ---------------------------------------------
    def _vote_suppressed(self) -> bool:
        """True while the brownout ladder (rung >= 1) says to skip the
        vote's shadow traffic.  A broken hook reads as not-suppressed:
        integrity redundancy only yields to an affirmative signal."""
        if self.brownout_fn is None:
            return False
        try:
            return bool(self.brownout_fn())
        except Exception:  # noqa: BLE001 — a signal, never a failure
            return False

    def _vote_due(self) -> bool:
        """Deterministic accumulator sampling (no RNG — two runs of the
        same query stream vote the same queries, which keeps chaos
        tests replayable), same scheme as the supervisor's audit
        sampler."""
        if self.vote_rate <= 0.0:
            return False
        with self._lock:
            self._vote_acc += self.vote_rate
            if self._vote_acc >= 1.0:
                self._vote_acc -= 1.0
                return True
        return False

    def _shadow_query(
        self,
        member: str,
        queries,
        graph: str,
        remaining: Optional[float],
        weighted: bool = False,
    ) -> Optional[dict]:
        """One best-effort vote leg to ``member``; None when the leg is
        unavailable (down, saturated, dropped, deadline spent).  An
        unavailable leg is NOT evidence of corruption — the vote simply
        doesn't happen, exactly like a dead owner in the main walk."""
        if remaining is not None and remaining <= 0:
            return None
        address = self.addresses.get(member)
        if address is None:
            return None
        try:
            faults.trip(f"route{self._route_index(member)}")
            with MsbfsClient(
                address,
                timeout=(
                    self.timeout if remaining is None
                    else min(self.timeout, remaining)
                ),
                retry=_NO_RETRY,
                epoch=self._epoch(),
            ) as client:
                return client.query(queries, graph=graph,
                                    deadline_s=remaining,
                                    weighted=weighted)
        except (
            faults.SimulatedNetDrop,
            faults.SimulatedHalfOpen,
            ServerError,
            protocol.ProtocolError,
            OSError,
            socket.timeout,
            ValueError,
        ):
            return None

    def _quarantine(self, member: str) -> None:
        if self.quarantine_fn is None:
            return
        try:
            self.quarantine_fn(member)
        except Exception:  # noqa: BLE001 — voting must not kill the query
            return
        self._bump("quarantined")

    def _vote(
        self,
        primary: str,
        owners: List[str],
        queries,
        graph: str,
        deadline: Optional[float],
        out: dict,
        weighted: bool = False,
    ) -> dict:
        """Shadow-route the answered batch to the next live owner and
        compare answer digests; on disagreement recompute on a third
        owner, quarantine the outvoted replica, and return the majority
        answer (docstring at module top).  ``deadline`` is an ABSOLUTE
        ``time.monotonic()`` instant: each vote leg re-derives its
        residual budget just before it starts, so a slow shadow leg
        shrinks (never resets) what the arbiter leg may spend and the
        whole vote stays inside the caller's deadline."""

        def remaining() -> Optional[float]:
            return None if deadline is None else deadline - time.monotonic()

        later = owners[owners.index(primary) + 1:]
        if not later:
            return out  # nobody to vote with (replication 1 / lone survivor)
        shadow_member = later[0]
        with span(
            "route.vote", graph=graph, primary=primary, shadow=shadow_member
        ) as sp:
            shadow = self._shadow_query(
                shadow_member, queries, graph, remaining(),
                weighted=weighted,
            )
            if shadow is None:
                return out
            self._bump("votes")
            out["voted"] = True
            d_primary = _answer_digest(out)
            if _answer_digest(shadow) == d_primary:
                sp.set(agreed=True)
                return out
            sp.set(agreed=False)
        self._bump("vote_mismatches")
        record_flight(
            "vote_mismatch", graph=graph, primary=primary,
            shadow=shadow_member,
        )
        instant(
            "route.vote_mismatch", graph=graph, primary=primary,
            shadow=shadow_member,
        )
        out["vote_mismatch"] = True
        arbiter_member, arbiter = None, None
        for m in later[1:]:
            arbiter = self._shadow_query(m, queries, graph, remaining(),
                                         weighted=weighted)
            if arbiter is not None:
                arbiter_member = m
                break
        if arbiter is None:
            # Two opinions, no tiebreak: keep the ring-preferred
            # primary's answer, but take the disagreeing shadow out of
            # rotation — one of the two IS corrupt, and a quarantined
            # healthy replica merely restarts while a corrupt answer
            # left standing keeps lying.
            self._bump("vote_unresolved")
            self._quarantine(shadow_member)
            return out
        d_arbiter = _answer_digest(arbiter)
        if d_arbiter == d_primary:
            self._quarantine(shadow_member)
            return out
        shadow = dict(shadow)
        shadow["replica"] = shadow_member
        shadow["failovers"] = out.get("failovers", 0)
        shadow["voted"] = True
        shadow["vote_mismatch"] = True
        if d_arbiter == _answer_digest(shadow):
            # Majority against the primary: ITS answer was the corrupt
            # one — quarantine it and serve the agreeing pair's answer.
            self._quarantine(primary)
            return shadow
        # Three-way disagreement: at least two corrupt answers.  Trust
        # nothing we cannot certify here — quarantine both vote legs and
        # serve the arbiter's answer (the only one not yet outvoted).
        self._bump("vote_unresolved")
        self._quarantine(primary)
        self._quarantine(shadow_member)
        arbiter = dict(arbiter)
        arbiter["replica"] = arbiter_member
        arbiter["failovers"] = out.get("failovers", 0)
        arbiter["voted"] = True
        arbiter["vote_mismatch"] = True
        return arbiter

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["per_replica"] = dict(self._stats["per_replica"])
        return out


# Routed attempts never retry in place — the ring walk is the retry.
_NO_RETRY = RetryPolicy(max_retries=0)


class FleetFrontend:
    """The fleet's single client-facing socket: speaks the existing
    frame protocol, so the stock ``msbfs query`` client talks to a
    fleet exactly as it talks to one daemon.  Verbs: ``ping``,
    ``health`` (fleet topology + per-replica states), ``load``
    (ring-placed registration via the supervisor), ``query`` (routed),
    ``mutate`` (token-fenced, applied to every ring owner —
    :meth:`FleetRouter.mutate`), ``stats`` (router + fleet counters),
    ``trace`` (per-query trace events, fanned out to the replicas and
    merged), ``metrics`` (Prometheus text exposition of the fleet
    roll-up), ``shutdown``.

    Thread names use the ``msbfs-fleet-`` prefix (distinct from the
    single-daemon ledger in tests/conftest.py, which must keep failing
    on leaked *replica* threads, not the front end's).
    """

    def __init__(self, listen: str, router: FleetRouter, supervisor=None):
        self.listen = listen
        self.router = router
        self.supervisor = supervisor
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    def start(self) -> None:
        family, target = protocol.parse_address(self.listen)
        self._sock = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_INET:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if family == socket.AF_UNIX and isinstance(target, str):
            if os.path.exists(target):
                os.unlink(target)  # front end owns its path (no journal)
        self._sock.bind(target)
        # Deep backlog, same reasoning as MsbfsServer.start(): stampede
        # dials must park in the queue while the acceptor is GIL-starved
        # rather than time out at the client.
        self._sock.listen(512)
        self._sock.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="msbfs-fleet-accept", daemon=True
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10.0)
            self._accept_thread = None
        family, target = protocol.parse_address(self.listen)
        if family == socket.AF_UNIX and isinstance(target, str):
            try:
                os.unlink(target)
            except OSError:
                pass

    def __enter__(self) -> "FleetFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            threading.Thread(
                target=self._serve_connection,
                args=(conn,),
                name="msbfs-fleet-conn",
                daemon=True,
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopping.is_set():
                try:
                    request = protocol.recv_frame(conn)
                except (protocol.ProtocolError, OSError):
                    return
                if request is None:
                    return
                response = self.handle(request)
                try:
                    protocol.send_frame(conn, response)
                except OSError:
                    return
                if request.get("op") == "shutdown":
                    self.stop()
                    return

    def handle(self, request: dict) -> dict:
        # Adopt the caller's trace context (if any) for the whole verb:
        # the router's forwarding legs use MsbfsClient.query, which
        # re-injects the thread's current trace into the replica-bound
        # frame — that is how one trace_id survives the extra hop.
        ctx = TraceContext.from_wire(request.get("trace"))
        if ctx is None:
            return self._handle(request)
        with use_trace(ctx):
            return self._handle(request)

    def _check_epoch(self, frame_epoch) -> None:
        """Fence an incoming frame's membership view against the live
        ring (docs/SERVING.md "Cross-machine transport & fencing") —
        the front end refuses stale views exactly like a replica, so a
        partition-healed peer holding an old topology cannot route
        through us under it.  Frames without an epoch pass."""
        try:
            frame_epoch = int(frame_epoch)
        except (TypeError, ValueError):
            raise InputError(
                f"frame 'epoch' must be an integer, got {frame_epoch!r}"
            ) from None
        local = int(getattr(self.router.ring, "epoch", 0) or 0)
        if frame_epoch == local:
            return
        self.router._bump("fenced")
        direction = "stale behind" if frame_epoch < local else "ahead of"
        raise FencedError(
            f"frame epoch {frame_epoch} is {direction} the fleet's "
            f"membership epoch {local}; refresh the view and resend",
            frame_epoch=frame_epoch, local_epoch=local,
        )

    def _handle(self, request: dict) -> dict:
        op = request.get("op")
        try:
            if "epoch" in request and request["epoch"] is not None:
                self._check_epoch(request["epoch"])
            if op == "ping":
                return {"ok": True, "op": "ping", "pid": os.getpid()}
            if op == "health":
                return self._op_health()
            if op == "stats":
                return {"ok": True, "op": "stats", "stats": self._op_stats()}
            if op == "trace":
                return self._op_trace(request)
            if op == "metrics":
                return {
                    "ok": True,
                    "op": "metrics",
                    "text": observe.fleet_metrics_text(self),
                }
            if op == "query":
                out = self.router.query(
                    request.get("queries") or [],
                    graph=request.get("graph", "default"),
                    deadline_s=request.get("deadline_s"),
                    hedge_after_s=request.get("hedge_after_s"),
                    priority=request.get("priority"),
                    client_id=request.get("client_id"),
                    weighted=bool(request.get("weighted", False)),
                    degraded=bool(request.get("degraded", False)),
                )
                out["ok"] = True
                return out
            if op == "mutate":
                return self.router.mutate(
                    request.get("inserts") or [],
                    request.get("deletes") or [],
                    graph=request.get("graph", "default"),
                    token=request.get("token"),
                    deadline_s=request.get("deadline_s"),
                )
            if op == "load":
                if self.supervisor is None:
                    raise InputError(
                        "this front end has no supervisor; register "
                        "graphs on the replicas directly"
                    )
                name = request.get("graph", "default")
                owners = self.supervisor.register(
                    name, request.get("path", "")
                )
                return {
                    "ok": True,
                    "op": "load",
                    "graph": {
                        "name": name,
                        "owners": owners,
                        "hash": self.supervisor.digests[name],
                    },
                }
            if op == "shutdown":
                return {"ok": True, "op": "shutdown"}
            raise InputError(f"unknown op {op!r}")
        except ServerError as err:
            # A replica's typed verdict passes through unchanged.
            return {
                "ok": False,
                "error": {
                    "type": err.type_name,
                    "message": str(err),
                    "exit_code": err.exit_code,
                },
            }
        except MsbfsError as err:
            return protocol.error_body(err)
        except Exception as err:  # noqa: BLE001 — front end must answer
            return protocol.error_body(MsbfsError(str(err)))

    def _op_health(self) -> dict:
        from .server import _pkg_version  # lazy: avoid module cycle

        fleet = (
            self.supervisor.status() if self.supervisor is not None else {}
        )
        ready = bool(fleet.get("ready")) if fleet else True
        graphs = fleet.get("graphs", {})
        routable = all(g["live_owners"] for g in graphs.values())
        return {
            "ok": True,
            "op": "health",
            "pid": os.getpid(),
            "version": _pkg_version(),
            "ready": ready and routable,
            "fleet": fleet,
        }

    def _op_trace(self, request: dict) -> dict:
        """One trace, fleet-wide: the front end's own span events (route
        legs, votes) merged with each ready replica's events for the
        same trace_id — the replica fan-out is best-effort, exactly like
        the stats roll-up (a silent replica leaves a hole, not an
        error)."""
        from ..utils import telemetry

        known = telemetry.known_traces()
        trace_id = request.get("trace_id")
        if trace_id is None and known:
            trace_id = known[-1]
        if not trace_id:
            return {
                "ok": True, "op": "trace", "trace_id": None,
                "events": [], "traces": known,
            }
        local = telemetry.trace_events(trace_id)
        remote_batches = []
        if self.supervisor is not None:
            with getattr(self.supervisor, "_lock", threading.Lock()):
                targets = [
                    r.address
                    for r in self.supervisor.replicas
                    if r.state == "ready"
                ]
            for address in targets:
                try:
                    with MsbfsClient(
                        address, timeout=10.0, retry=_NO_RETRY
                    ) as c:
                        resp = c.trace(trace_id)
                except (ServerError, protocol.ProtocolError, OSError,
                        socket.timeout, ValueError):
                    continue
                remote_batches.append(resp.get("events") or [])
        events = observe.merge_trace_events(local, remote_batches)
        return {
            "ok": True, "op": "trace", "trace_id": trace_id,
            "events": events, "traces": known,
        }

    def _op_stats(self) -> dict:
        out = {"router": self.router.stats()}
        if self.supervisor is not None:
            out["fleet"] = self.supervisor.status()
            per, totals = self._rollup()
            out["replicas"] = per
            out["totals"] = totals
            # Shard topology, surfaced top-level so an operator's first
            # `stats` answers "how is this graph cut, where do the
            # pieces live, is anything under-replicated" without
            # spelunking the fleet blob.
            shards = out["fleet"].get("shards") or {}
            if shards:
                out["shards"] = shards
                totals["under_replicated_shards"] = sum(
                    g.get("under_replicated", 0) for g in shards.values()
                )
        return out

    # Per-replica stats fields summed into the fleet-wide roll-up; the
    # queue gauge keys live under each replica's "queue" section.
    _ROLLUP_KEYS = (
        "requests_total",
        "requests_failed",
        "requests_shed",
        "requests_quarantined",
        "audited",
        "audit_failures",
        "journal_bytes",
        "shard_steps",
    )
    _ROLLUP_QUEUE_KEYS = (
        "depth",
        "rejected",
        "rejected_batch",
        "rejected_client",
        "shed_overload",
    )

    def _rollup(self):
        """Fleet-wide observability in one verb: fetch each ready
        replica's ``stats`` and sum the load/shed/integrity counters.
        Best-effort per replica — a replica that does not answer is
        listed with an ``error`` and skipped from the totals (the
        operator sees the hole, the verb still answers)."""
        per: Dict[str, dict] = {}
        totals = {k: 0 for k in self._ROLLUP_KEYS}
        totals.update({f"queue_{k}": 0 for k in self._ROLLUP_QUEUE_KEYS})
        totals["shed_brownout"] = 0
        totals["replicas_reporting"] = 0
        # Fleet-wide latency distribution: per-bucket histograms share
        # fixed log2 bounds exactly so they can be SUMMED across
        # replicas (utils/telemetry.py) — percentiles of percentiles
        # would be wrong; merged counts are not.  A replica predating
        # the hist field contributes nothing (from_snapshot -> None).
        hist_total = Histogram()
        with getattr(self.supervisor, "_lock", threading.Lock()):
            targets = [
                (r.name, r.address)
                for r in self.supervisor.replicas
                if r.state == "ready"
            ]
        for name, address in targets:
            try:
                with MsbfsClient(
                    address, timeout=10.0, retry=_NO_RETRY
                ) as c:
                    s = c.stats()
            except (ServerError, protocol.ProtocolError, OSError,
                    socket.timeout, ValueError) as exc:
                per[name] = {"error": str(exc)}
                continue
            queue = s.get("queue") or {}
            posture = s.get("posture") or {}
            row = {k: int(s.get(k, 0) or 0) for k in self._ROLLUP_KEYS}
            row.update(
                {
                    f"queue_{k}": int(queue.get(k, 0) or 0)
                    for k in self._ROLLUP_QUEUE_KEYS
                }
            )
            row["queue_oldest_age_s"] = float(
                queue.get("oldest_age_s", 0.0) or 0.0
            )
            row["shed_brownout"] = int(
                posture.get("shed_brownout", 0) or 0
            )
            for b in (s.get("buckets") or {}).values():
                h = Histogram.from_snapshot((b or {}).get("hist"))
                if h is not None:
                    try:
                        hist_total.merge(h)
                    except ValueError:
                        pass  # foreign bounds: skip, never poison totals
            per[name] = row
            totals["replicas_reporting"] += 1
            for k, v in row.items():
                if k in totals and k != "replicas_reporting":
                    totals[k] += v
        totals["latency_hist"] = hist_total.snapshot()
        totals["latency_p99_ms"] = hist_total.percentile(0.99)
        return per, totals


def fleet_main(argv: Optional[List[str]] = None) -> int:
    """``msbfs-tpu fleet`` / ``python main.py fleet`` entry point: boot
    N replicas + the front-end router on one command."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="msbfs-tpu fleet",
        description="Replicated msbfs serving fleet: N replica daemons, "
        "rendezvous placement, failover router (docs/SERVING.md)",
    )
    ap.add_argument(
        "--listen",
        default=knobs.raw(
            "MSBFS_FLEET_LISTEN", "unix:/tmp/msbfs-fleet.sock"
        ),
        help="front-end address (default unix:/tmp/msbfs-fleet.sock)",
    )
    ap.add_argument("--size", type=int, default=3,
                    help="replica count (default 3)")
    ap.add_argument("--replication", type=int, default=2,
                    help="owners per graph (default 2)")
    ap.add_argument(
        "--base-dir",
        default=None,
        help="directory for replica sockets/journals/logs "
        "(default MSBFS_FLEET_DIR or /tmp/msbfs-fleet)",
    )
    ap.add_argument(
        "-g", "--graph", action="append", default=[],
        metavar="[NAME=]PATH",
        help="register a graph at startup (repeatable)",
    )
    ap.add_argument("--heartbeat-ms", type=float, default=500.0,
                    help="replica heartbeat period (default 500)")
    ap.add_argument("--wait-ready-s", type=float, default=240.0,
                    help="block until all replicas are ready (0 skips)")
    ap.add_argument(
        "--transport", choices=("unix", "tcp"), default="unix",
        help="replica listener transport; tcp advertises host:port "
        "addresses for cross-host fleets (default unix)",
    )
    ap.add_argument(
        "--hosts", default="", metavar="LABEL[,LABEL...]",
        help="comma-separated host labels round-robined over replicas; "
        "the ring then spreads each graph's owners across labels",
    )
    ap.add_argument(
        "--shard-max-bytes", type=int, default=None, metavar="BYTES",
        help="shard graphs whose artifact exceeds BYTES across the "
        "fleet (default MSBFS_SHARD_MAX_BYTES; 0 = serve whole)",
    )
    ap.add_argument(
        "--shard-replicas", type=int, default=None, metavar="N",
        help="copies per shard (default MSBFS_SHARD_REPLICAS, 2)",
    )
    ap.add_argument(
        "--autoscale-max", type=int, default=0, metavar="N",
        help="arm the autoscaler: grow from --size up to N replicas "
        "under load, shrink back when quiet (0 = fixed size)",
    )
    ap.add_argument(
        "--brownout", action="store_true",
        help="arm the brownout ladder (vote -> audit -> cache-only "
        "quality step-down under sustained saturation)",
    )
    args = ap.parse_args(argv)

    from .autoscale import AutoscaleConfig, AutoscalePolicy
    from .brownout import BrownoutLadder
    from .fleet import FleetSupervisor

    plan = faults.FaultPlan.from_env()
    faults.activate(plan)
    base_dir = args.base_dir or knobs.raw(
        "MSBFS_FLEET_DIR", "/tmp/msbfs-fleet"
    )
    autoscale = None
    if args.autoscale_max:
        autoscale = AutoscalePolicy(
            AutoscaleConfig(
                min_replicas=args.size,
                max_replicas=max(args.size, args.autoscale_max),
            )
        )
    brownout = None
    if args.brownout:
        brownout = BrownoutLadder(
            journal_path=os.path.join(base_dir, "brownout.jsonl")
        )
    host_pool = [h.strip() for h in args.hosts.split(",") if h.strip()]
    try:
        supervisor = FleetSupervisor(
            size=args.size,
            base_dir=base_dir,
            replication=args.replication,
            heartbeat_s=args.heartbeat_ms / 1000.0,
            transport=args.transport,
            host_pool=host_pool or None,
            autoscale=autoscale,
            brownout=brownout,
            shard_max_bytes=args.shard_max_bytes,
            shard_replicas=args.shard_replicas,
        )
        supervisor.start(
            wait_ready_s=args.wait_ready_s or None
        )
    except (MsbfsError, OSError, ValueError) as err:
        print(f"msbfs fleet: {err}", file=sys.stderr)
        return getattr(err, "exit_code", 1)
    router = FleetRouter.for_fleet(supervisor)
    # The autoscaler's "admission collapse" signal is the router's shed
    # counter: fleet-level backpressure is what capacity must answer.
    supervisor.shed_fn = lambda: router.stats().get("shed", 0)
    frontend = FleetFrontend(args.listen, router, supervisor=supervisor)
    try:
        for spec in args.graph:
            name, sep, path = spec.partition("=")
            if not sep:
                name, path = "default", spec
            supervisor.register(name, path)
        frontend.start()
    except (MsbfsError, OSError, ValueError) as err:
        print(f"msbfs fleet: {err}", file=sys.stderr)
        supervisor.stop()
        return getattr(err, "exit_code", 1)
    import signal as _signal

    def _on_signal(signum, frame):  # noqa: ARG001 — signal signature
        frontend.stop()

    _signal.signal(_signal.SIGTERM, _on_signal)
    _signal.signal(_signal.SIGINT, _on_signal)
    names = ", ".join(sorted(supervisor.graphs)) or "none (use load)"
    log_line(
        f"msbfs fleet: {args.size} replicas (replication "
        f"{supervisor.ring.replication}) under {base_dir}; front end on "
        f"{args.listen}; graphs: {names}",
        event="fleet_start",
        size=args.size,
        listen=args.listen,
        graphs=sorted(supervisor.graphs),
    )
    try:
        while not frontend._stopping.is_set():
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        supervisor.stop(drain=True)
    log_line("msbfs fleet: stopped", event="fleet_stop")
    return 0


__all__ = [
    "FleetFrontend",
    "FleetRouter",
    "fleet_main",
]
