"""Scatter-free packed BFS over the BELL layout (models.bell).

The coalesced packed engine (ops.packed) spends most of each level in
``segment_max`` — an XLA scatter that runs ~two orders of magnitude below
HBM bandwidth on TPU (measured ~5-10 ns/row on v5e).  This engine replaces
the whole per-level neighbor reduce with the BELL reduction forest:

    level l:   hits_b = max over W_b of  V_{l-1}[cols_b]     (per bucket b)
    final:     H      = V_cat[final_slot]                    (per vertex)

— nothing but row gathers and dense fixed-width maxima, both of which the
TPU executes at full throughput.  Distances stay query-minor (n, K) exactly
as in ops.packed, so objective/stats plumbing is shared.

Semantics are the reference's (main.cu:16-73): level-synchronous expansion
to unvisited (-1) vertices until a level discovers nothing.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..models.bell import BellGraph
from ..utils.donation import donating_jit
from .bfs import distance_chunk, host_chunked_loop, validate_level_chunk
from .objective import f_of_u
from .packed import (
    K_ALIGN,
    PackedEngineBase,
    packed_carry_init,
    packed_init,
)

HIT = jnp.uint8


def _slot_segments(shapes, slot_budget: int):
    """Partition a level's bucket layout into contiguous segments of at
    most ``slot_budget`` slots (static, trace-time).  Buckets are stored
    consecutively row-major, so a segment is a contiguous slot range;
    oversized buckets split at row boundaries (a single row wider than
    the budget stays whole — rows are the atomic reduce unit).  Returns
    [[(slot_offset, rows, width), ...], ...] with pieces in layout order.
    """
    pieces = []
    off = 0
    for r_b, w_b in shapes:
        if r_b == 0:
            continue
        rows_per = max(1, slot_budget // w_b)
        r0 = 0
        while r0 < r_b:
            rc = min(rows_per, r_b - r0)
            pieces.append((off + r0 * w_b, rc, w_b))
            r0 += rc
        off += r_b * w_b
    segments, cur, cur_slots = [], [], 0
    for p in pieces:
        s = p[1] * p[2]
        if cur and cur_slots + s > slot_budget:
            segments.append(cur)
            cur, cur_slots = [], 0
        cur.append(p)
        cur_slots += s
    if cur:
        segments.append(cur)
    return segments


def forest_hits(
    frontier: jax.Array,
    graph: BellGraph,
    reduce_fn,
    slot_budget: "int | None" = None,
) -> jax.Array:
    """Shared BELL reduction-forest traversal.

    ``frontier`` is (n, C) of any dtype whose zero value means "not in
    frontier"; ``reduce_fn(vals (R, W, C)) -> (R, C)`` collapses the width
    axis (max for flag columns, bitwise-OR for packed bit planes).  Returns
    the (n, C) per-vertex hit array via the final per-vertex slot gather.

    All of a forest level's buckets share ONE gather over the level's
    flat cols array (``BellGraph.level_cols`` stores exactly that): the
    HBM row-gather unit runs measurably faster on big index vectors
    (v5e: ~165 M rows/s at 256k rows vs ~254 M at 2M,
    benchmarks/micro_sparse_step.py), so 20+ small per-bucket takes leave
    throughput on the table.  The per-bucket reduces then slice the
    gathered block by the recorded shapes.

    ``slot_budget`` bounds the gathered intermediate: a level whose slot
    count exceeds it is gathered in contiguous <=budget-slot segments,
    each reduced before the next streams in — so the live intermediate is
    budget*C words instead of slots*C.  This is what lets wide-plane
    (large C) runs fit one chip: RMAT-24 at K=256 materializes a
    (557M, 8) u32 gather = 17.8 GB > v5e HBM unchunked (measured OOM,
    benchmarks/raw_r4/bench_rmat24_k256.json's first attempt) but runs
    inside the budget.  None = the single merged gather per level.
    """
    c = frontier.shape[1]
    zero_row = jnp.zeros((1, c), dtype=frontier.dtype)
    v_prev = jnp.concatenate([frontier, zero_row], axis=0)  # sentinel row n
    outs = []
    for flat, shapes in zip(graph.level_cols, graph.level_shapes):
        if flat.shape[-1] == 0:
            out = jnp.zeros((0, c), dtype=frontier.dtype)
        elif slot_budget is None or flat.shape[-1] <= slot_budget:
            g = jnp.take(v_prev, flat, axis=0)
            parts = []
            off = 0
            for r_b, w_b in shapes:
                if r_b == 0:
                    continue
                seg = lax.slice_in_dim(g, off, off + r_b * w_b, axis=0)
                parts.append(reduce_fn(seg.reshape(r_b, w_b, c)))
                off += r_b * w_b
            out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        else:
            parts = []
            for seg_pieces in _slot_segments(shapes, slot_budget):
                a = seg_pieces[0][0]
                last = seg_pieces[-1]
                b = last[0] + last[1] * last[2]
                g = jnp.take(
                    v_prev, lax.slice_in_dim(flat, a, b, axis=0), axis=0
                )
                o = 0
                for _, rc, w_b in seg_pieces:
                    seg = lax.slice_in_dim(g, o, o + rc * w_b, axis=0)
                    parts.append(reduce_fn(seg.reshape(rc, w_b, c)))
                    o += rc * w_b
            out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        outs.append(out)
        v_prev = jnp.concatenate([out, zero_row], axis=0)
    v_cat = jnp.concatenate(outs + [zero_row], axis=0)
    return jnp.take(v_cat, graph.final_slot, axis=0)


def bell_hits_packed(frontier: jax.Array, graph: BellGraph) -> jax.Array:
    """(n, K) uint8 frontier indicator -> (n, K) uint8 per-vertex hit flags."""
    return forest_hits(frontier, graph, lambda g: jnp.max(g, axis=1))


def bell_expand_packed(
    dist: jax.Array, level: jax.Array, graph: BellGraph
) -> jax.Array:
    """One level for all K queries; (n, K) bool newly-reached mask."""
    frontier = (dist == level).astype(HIT)
    hits = bell_hits_packed(frontier, graph)
    return (dist == -1) & (hits > 0)


def bell_expand(dist: jax.Array, level: jax.Array, graph: BellGraph) -> jax.Array:
    """Single-query expansion hook matching the ops.bfs ``expand`` contract
    ((n,) distances), so BellGraph also plugs into the generic vmap Engine."""
    return bell_expand_packed(dist[:, None], level, graph)[:, 0]


@partial(jax.jit, static_argnames=("max_levels",))
def bell_distances(
    graph: BellGraph,
    queries: jax.Array,
    max_levels: Optional[int] = None,
) -> jax.Array:
    """(K, S) -1-padded queries -> (n, K) int32 distances."""

    def cond(carry):
        _, level, updated = carry
        go = updated
        if max_levels is not None:
            go = jnp.logical_and(go, level < max_levels)
        return go

    def body(carry):
        dist, level, _ = carry
        new = bell_expand_packed(dist, level, graph)
        dist = jnp.where(new, level + 1, dist)
        return (dist, level + 1, jnp.any(new))

    dist0 = packed_init(graph.n, queries)
    dist, _, _ = lax.while_loop(
        cond, body, (dist0, jnp.int32(0), jnp.any(dist0 == 0))
    )
    return dist


@donating_jit(donate_argnums=(1,), static_argnames=("chunk", "max_levels"))
def _bell_chunk(graph, carry, chunk, max_levels):
    """Carry DONATED: the host driver rebinds it every step, so the
    (n, K) distance state is updated in place (utils.donation)."""
    return distance_chunk(
        carry,
        lambda d, lvl: bell_expand_packed(d, lvl, graph),
        chunk,
        max_levels,
    )


def bell_distances_chunked(
    graph: BellGraph,
    queries: jax.Array,
    level_chunk: int,
    max_levels: Optional[int] = None,
) -> jax.Array:
    """:func:`bell_distances` with per-dispatch work bounded to
    ``level_chunk`` BFS levels (ops.bfs.host_chunked_loop)."""
    carry = host_chunked_loop(
        packed_carry_init(graph, queries),
        lambda c: _bell_chunk(graph, c, level_chunk, max_levels),
        max_levels,
    )
    return carry[0]


@partial(jax.jit, static_argnames=("max_levels",))
def bell_f_values(
    graph: BellGraph,
    queries: jax.Array,
    max_levels: Optional[int] = None,
) -> jax.Array:
    """(K, S) queries -> (K,) int64 F values (objective main.cu:75-89)."""
    dist = bell_distances(graph, queries, max_levels)
    return jax.vmap(f_of_u)(dist.T)


class BellEngine(PackedEngineBase):
    """All-queries-at-once scatter-free engine over a BellGraph."""

    # Lattice axes (ops.engine.resolve_axes): word distances over the
    # bucketed-ELL forest (the bit-plane variant is ops.bitbell).
    CAPABILITIES = frozenset(
        {"plane:word", "residency:hbm", "partition:single", "kernel:xla"}
    )

    def __init__(
        self,
        graph: BellGraph,
        max_levels: Optional[int] = None,
        k_align: int = K_ALIGN,
        level_chunk: Optional[int] = None,
    ):
        self.graph = graph
        self.max_levels = max_levels
        self.k_align = k_align
        self.level_chunk = validate_level_chunk(level_chunk)

    def _distances(self, queries) -> jax.Array:
        if self.level_chunk:
            return bell_distances_chunked(
                self.graph, queries, self.level_chunk, self.max_levels
            )
        return bell_distances(self.graph, queries, self.max_levels)

    def f_values(self, queries) -> jax.Array:
        queries, k = self._pad_queries(queries)
        if self.level_chunk:
            from .packed import _f_from_packed_distances

            return _f_from_packed_distances(self._distances(queries))[:k]
        return bell_f_values(self.graph, queries, self.max_levels)[:k]
