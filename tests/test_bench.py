"""bench.py contract tests: the driver must ALWAYS get one parsable JSON
line — a result when the backend works, an error record when it doesn't
(round-3 hardening after BENCH_r02 recorded rc=124 with parsed: null).

All cases run bench.py as a subprocess from the repo root, exactly like
the driver does, against the virtual CPU platform."""

import json
import os
import subprocess
import sys

import pytest

from virtual_cpu import virtual_cpu_env

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")


def run_bench(extra_env, timeout=600):
    env = virtual_cpu_env(8)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, BENCH],
        env=env,
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def last_json_line(stdout: str) -> dict:
    lines = [l for l in stdout.strip().splitlines() if l.lstrip().startswith("{")]
    assert lines, f"no JSON line in stdout: {stdout!r}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_success_emits_metric_and_extras():
    proc = run_bench(
        {
            "BENCH_CONFIGS": "",
            "BENCH_SCALE": "10",
            "BENCH_K": "32",
            "BENCH_MAX_S": "8",
            "BENCH_REPEATS": "1",
            "BENCH_EXTRA_KS": "64",
            "BENCH_WAIT_S": "120",
            "BENCH_RUN_S": "540",
        }
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = last_json_line(proc.stdout)
    assert rec["unit"] == "TEPS"
    assert rec["value"] and rec["value"] > 0
    assert rec["vs_baseline"] is not None
    extras = rec["detail"]["extra_metrics"]
    assert len(extras) == 1 and extras[0]["value"] > 0
    assert "64-query" in extras[0]["metric"]
    # Per-config reference model fields (r5): modeled denominator + the
    # dispatch-floor split + gather utilization.
    d = rec["detail"]
    assert d["levels_sum"] and d["levels_sum"] >= d["levels_max"] > 0
    assert d["ref_model"]["teps"] > 0 and d["ref_model"]["t_s"] > 0
    assert rec["vs_baseline"] == pytest.approx(
        rec["value"] / d["ref_model"]["teps"], rel=0.01
    )
    assert d["vs_flat_1g5"] is not None
    assert d["dispatch"]["floor_s"] > 0
    # Fused best (r5): the whole unchunked run + argmin is ONE program.
    assert d["dispatch"]["n_dispatches"] == 1
    assert d["gather_rows_per_s"] > 0 and d["pct_of_roofline"] > 0


def test_stencil_config_reports_stream_utilization():
    """A road/stencil run must carry the stream-bytes utilization fields
    (the stencil analog of gather_rows_per_s, VERDICT r4 item 6)."""
    proc = run_bench(
        {
            "BENCH_CONFIGS": "",
            "BENCH_GRAPH": "road",
            "BENCH_ENGINE": "stencil",
            "BENCH_SCALE": "10",
            "BENCH_K": "4",
            "BENCH_MAX_S": "4",
            "BENCH_REPEATS": "1",
            "BENCH_EXTRA_KS": "",
            "BENCH_LEVEL_CHUNK": "auto",
            "BENCH_WAIT_S": "120",
            "BENCH_RUN_S": "540",
        }
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = last_json_line(proc.stdout)
    d = rec["detail"]
    assert rec["value"] and rec["value"] > 0
    assert d["gather_rows_per_s"] is None  # no gather in this engine
    assert d["stream_bytes_per_s"] > 0
    assert 0 < d["pct_of_hbm_roofline"]
    assert d["levels_max"] > 0 and rec["vs_baseline"] is not None


@pytest.mark.slow  # ~30 s (a full bench subprocess boot against a
# bogus backend); harness behavior, not engine correctness — tier-1
# keeps the in-process bench rule tests, `make test` runs this arm
def test_outage_fast_parsable_failure():
    """A dead backend must produce an error JSON line within the
    BENCH_WAIT_S budget — not a hang into the driver's kill timeout."""
    proc = run_bench(
        {"BENCH_CONFIGS": "", "JAX_PLATFORMS": "bogus_platform", "BENCH_WAIT_S": "1"},
        timeout=180,
    )
    assert proc.returncode == 2
    rec = last_json_line(proc.stdout)
    assert rec["value"] is None
    assert "device unavailable" in rec["error"]
    assert rec["vs_baseline"] is None
    assert rec["metric"].startswith("TEPS")


@pytest.mark.slow
def test_configs_sweep_partial_failure_keeps_partial_results(tmp_path):
    """BENCH_CONFIGS (round 4): one capture certifies several configs,
    each with its own value/error — an unknown config cannot zero the
    ones that measured."""
    proc = run_bench(
        {
            "BENCH_CONFIGS": "1,zz,4",
            "BENCH_SCALE_CAP": "8",
            "BENCH_REPEATS": "1",
            "BENCH_MAX_S": "8",
            "BENCH_WAIT_S": "120",
            "BENCH_RUN_S": "540",
            "BENCH_DETAIL_PATH": str(tmp_path / "sweep_detail.json"),
        },
        timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = last_json_line(proc.stdout)
    sweep = rec["detail"]["sweep"]
    assert rec["detail"]["configs_requested"] == ["1", "zz", "4"]
    assert sweep["1"]["value"] and sweep["1"]["value"] > 0
    assert "RMAT-8" in sweep["1"]["metric"]
    assert sweep["zz"]["value"] is None and "unknown" in sweep["zz"]["error"]
    assert sweep["4"]["value"] and sweep["4"]["value"] > 0
    assert "road-16x16" in sweep["4"]["metric"]
    # Headline falls back to the first valued config (no "2" requested).
    assert rec["value"] == sweep["1"]["value"]
    # The cumulative record was re-emitted after every config.
    lines = [
        l for l in proc.stdout.strip().splitlines()
        if l.lstrip().startswith("{")
    ]
    assert len(lines) == 3
    # VERDICT r4 item 2: the stdout record is COMPACT — the driver's tail
    # window must always contain one complete JSON line.  The full sweep
    # detail lives in the sidecar (detail_path).
    assert all(len(l) < 4096 for l in lines), max(map(len, lines))
    dp = rec["detail"]["detail_path"]
    assert dp and os.path.exists(os.path.join(REPO_ROOT, dp))
    with open(os.path.join(REPO_ROOT, dp)) as fh:
        full = json.load(fh)
    full_sweep = full["detail"]["sweep"]
    assert full_sweep["1"]["detail"]["computation_s"] > 0
    assert full_sweep["1"]["detail"]["ref_model"]["teps"] > 0


@pytest.mark.slow  # ~31 s: full configs sweep around the outage; the
# single-config outage contract stays in tier-1 just above
def test_configs_sweep_outage_is_one_parsable_record(tmp_path):
    proc = run_bench(
        {
            "BENCH_CONFIGS": "1,2",
            "JAX_PLATFORMS": "bogus_platform",
            "BENCH_WAIT_S": "1",
            "BENCH_DETAIL_PATH": str(tmp_path / "sweep_detail.json"),
        },
        timeout=180,
    )
    assert proc.returncode == 2
    rec = last_json_line(proc.stdout)
    assert rec["value"] is None and "no config has produced" in rec["error"]
    sweep = rec["detail"]["sweep"]
    for c in ("1", "2"):
        assert sweep[c]["value"] is None
        assert "device unavailable" in sweep[c]["error"]


def test_midrun_stall_hits_hard_deadline():
    """BENCH_RUN_S bounds the workload: a child that cannot finish in time
    is killed and reported, again as parsable JSON."""
    proc = run_bench(
        {
            "BENCH_CONFIGS": "",
            "BENCH_SCALE": "10",
            "BENCH_WAIT_S": "120",
            "BENCH_RUN_S": "1",
        },
        timeout=300,
    )
    assert proc.returncode == 3
    rec = last_json_line(proc.stdout)
    assert rec["value"] is None
    assert "hard deadline" in rec["error"]


# --- round-7 fixture rule: headline queries reach the giant component --------
#
# These are in-process unit tests (no subprocess): the rule itself lives in
# models.generators and bench.measure applies it to every headline fixture,
# so a degenerate minF=0 "win" can never be published again.


def test_component_labels_on_known_graph():
    import numpy as np

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E501
        generators,
    )

    # Two triangles + one isolate: labels must partition exactly.
    edges = np.array(
        [[0, 1], [1, 2], [2, 0], [3, 4], [4, 5], [5, 3]], dtype=np.int64
    )
    label = generators.component_labels(7, edges)
    assert len(set(label[[0, 1, 2]])) == 1
    assert len(set(label[[3, 4, 5]])) == 1
    assert label[0] != label[3]
    assert label[6] not in (label[0], label[3])


def test_ensure_giant_sources_fixes_stranded_groups():
    import numpy as np

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E501
        generators,
    )

    # Path 0-1-2-3-4 (giant) + edge 5-6 (minor) + isolate 7.
    edges = np.array(
        [[0, 1], [1, 2], [2, 3], [3, 4], [5, 6]], dtype=np.int64
    )
    n = 8
    queries = [
        np.array([2, 7], dtype=np.int32),  # already reaches giant: untouched
        np.array([5, 6], dtype=np.int32),  # stranded in the minor component
        np.array([7], dtype=np.int32),  # stranded isolate
        np.array([-1, 9], dtype=np.int32),  # all-invalid group
    ]
    before = [q.copy() for q in queries]
    fixed = generators.ensure_giant_sources(queries, n, edges, seed=7)
    label = generators.component_labels(n, edges)
    giant = label[0]
    for q in fixed:
        valid = q[(q >= 0) & (q < n)]
        assert valid.size and (label[valid] == giant).any()
    # The compliant group is returned as-is; inputs are never mutated.
    np.testing.assert_array_equal(fixed[0], before[0])
    for q, b in zip(queries, before):
        np.testing.assert_array_equal(q, b)


def test_reference_model_range_brackets_point_model():
    import bench

    n, e, k, levels = 1 << 16, 1 << 20, 32, 400
    _, point = bench.reference_model(n, e, k, levels)
    fast, slow = bench.reference_model_range(n, e, k, levels)
    # vs_baseline_range corners: value/fast <= value/point <= value/slow.
    assert slow <= point <= fast
    assert bench.REF_EDGE_TEPS_RANGE[0] <= bench.REF_EDGE_TEPS <= (
        bench.REF_EDGE_TEPS_RANGE[1]
    )
    assert bench.REF_LAUNCH_RANGE_S[0] <= bench.REF_LAUNCH_S <= (
        bench.REF_LAUNCH_RANGE_S[1]
    )
