"""Two-span wall-clock timing, mirroring the reference's report (SURVEY C11).

The reference times exactly two spans with ``chrono::high_resolution_clock``:
preprocessing = load + broadcast + H2D upload (main.cu:235-298) and
computation = all BFS runs + gather + argmin (main.cu:301-400).  Here the
spans keep the same boundaries, with jit compilation counted as
preprocessing (the CUDA reference's kernels are compiled offline by nvcc, so
charging XLA compilation to the compute span would mis-compare).  Callers
must ``block_until_ready`` before closing a span — XLA dispatch is async.
"""

from __future__ import annotations

import time


class Span:
    """``with Span() as s: ...`` then ``s.seconds``."""

    def __init__(self):
        self.seconds = 0.0
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False
