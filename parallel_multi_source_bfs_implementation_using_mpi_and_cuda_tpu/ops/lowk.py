"""Low-K fast path: byte-flag BFS for tiny query batches (round 7).

The bit-plane engines pad K up to the 32-bit word (ops.bitbell.WORD_BITS),
so the flagship single-query benchmark shape (BASELINE config 1: K = 1)
streams a (n, 1) uint32 plane with 31 of its 32 lanes dead — every level
pays 4 bytes/vertex to move one bit.  This engine keeps K AS IS
(``k_align = 1``) and runs the level loop on an (n, K) uint8 0/1 flag
matrix: at K = 1 that is a boolean (n,) frontier costing 1 byte/vertex,
and the reduction-forest gather moves K bytes per slot instead of
ceil(K/32) words.

Everything else is shared machinery, deliberately: the 7-tuple carry,
counters and chunk drivers come from ops.bitbell (bit_level_init /
bit_level_chunk with a byte ``counts_of``), the pull side is the BELL
reduction forest (ops.bell.forest_hits — max over bytes), and the push
side is a byte-lane twin of ops.bitbell.sparse_hits_or: enumerate the
<= budget edges leaving the frontier and scatter-max the source flags
into their neighbors (elementwise max on 0/1 bytes IS the OR, and XLA's
scatter-max absorbs colliding writes exactly like the reference kernel's
benign race, main.cu:30-33).  Per level a ``lax.cond`` routes thin
frontiers through the push and the rest through the forest — Beamer's
direction optimization, byte-flag edition.  ``best()`` fuses packing +
init + level loop + argmin into one program (FusedBestEngine), so the
config-1 shape pays one dispatch unchunked.

Bit-identity: pinned against the oracle and the bitbell engine by
tests/test_lowk.py and the engines-agree matrix.  The CLI routes here
automatically for K <= LOWK_MAX_K host queries (MSBFS_LOWK=0 disables);
the engine itself is correct for any K — the cap is a routing policy,
not a correctness bound (wide K wants bit planes, 8x denser).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.bell import BellGraph
from ..utils.donation import donating_jit
from .bell import bell_hits_packed
from .bfs import host_chunked_loop, validate_level_chunk
from .bitbell import (
    FusedBestEngine,
    _pack_status,
    bit_level_chunk,
    bit_level_init,
    bit_level_loop,
    default_sparse_budget,
    fused_select,
    resolve_megachunk,
)
from .engine import frontier_activity
from .push import compact_indices

# Routing cap for the CLI/serve auto-route: below this many queries the
# byte-flag layout beats the padded bit plane (<= 4 bytes/vertex vs the
# word's fixed 4); at K > 4 the bit plane is already denser per query.
LOWK_MAX_K = 4


def lowk_pack(n: int, queries: jax.Array) -> jax.Array:
    """(K, S) -1-padded queries -> (n, K) uint8 source flags, reference
    bounds-check semantics (sources outside [0, n) dropped, main.cu:46-51)
    via one sentinel-row scatter-max."""
    k, s = queries.shape
    valid = (queries >= 0) & (queries < n)
    safe = jnp.where(valid, queries, n).astype(jnp.int32)  # sentinel row n
    cols = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[:, None], (k, s))
    flags = (
        jnp.zeros((n + 1, k), jnp.uint8)
        .at[safe.reshape(-1), cols.reshape(-1)]
        .max(jnp.uint8(1), mode="drop")
    )
    return flags[:n]


def _lowk_counts(new: jax.Array) -> jax.Array:
    """(n, K) uint8 0/1 newly-reached flags -> (K,) int32 counts."""
    return jnp.sum(new, axis=0, dtype=jnp.int32)


def sparse_hits_flags(
    frontier: jax.Array, graph: BellGraph, budget: int
) -> jax.Array:
    """Byte-flag twin of ops.bitbell.sparse_hits_or: (n, K) uint8 frontier
    -> (n, K) uint8 hit flags by pushing the <= ``budget`` edges leaving
    the frontier (cost budget-proportional, independent of |E|)."""
    n = graph.n
    start, count, vals = graph.sparse
    if vals.shape[0] == 0:
        return jnp.zeros_like(frontier)
    active = (frontier != jnp.uint8(0)).any(axis=1)  # (n,)
    ids = compact_indices(active, budget, fill_value=n)  # (B,) ascending
    valid_id = ids < n
    safe_ids = jnp.minimum(ids, n - 1)
    deg = jnp.where(valid_id, jnp.take(count, safe_ids), 0)
    st = jnp.where(valid_id, jnp.take(start, safe_ids), 0)
    pos = jnp.cumsum(deg) - deg  # exclusive: edge range start per owner
    total = pos[-1] + deg[-1]
    own = (
        jnp.zeros((budget,), jnp.int32)
        .at[jnp.where(deg > 0, pos, budget)]
        .max(jnp.arange(budget, dtype=jnp.int32), mode="drop")
    )
    own = lax.cummax(own)
    j = jnp.arange(budget, dtype=jnp.int32)
    within = j - jnp.take(pos, own)
    valid_e = j < total
    eidx = jnp.clip(jnp.take(st, own) + within, 0, vals.shape[0] - 1)
    nbr = jnp.where(valid_e, jnp.take(vals, eidx), n)  # sentinel row n
    src_rows = jnp.where(
        valid_id[:, None],
        jnp.take(frontier, safe_ids, axis=0),
        jnp.uint8(0),
    )
    rows = jnp.take(src_rows, own, axis=0)  # (budget, K)
    hit = jnp.zeros((n + 1, rows.shape[1]), jnp.uint8).at[nbr].max(rows)
    return hit[:n]


def lowk_expand(graph: BellGraph, budget: int):
    """Hybrid pull/push expansion hook over byte flags (the
    ops.bitbell.hybrid_expand routing, byte-lane edition)."""
    if budget:
        _, count, _ = graph.sparse

    def expand(visited, frontier):
        if not budget:
            hits = bell_hits_packed(frontier, graph)
        else:
            _, cnt, edges = frontier_activity(frontier, count)
            pred = (cnt <= budget) & (edges <= budget)
            hits = lax.cond(
                pred,
                lambda fr: sparse_hits_flags(fr, graph, budget),
                lambda fr: bell_hits_packed(fr, graph),
                frontier,
            )
        return jnp.where(visited > jnp.uint8(0), jnp.uint8(0), hits)

    return expand


@partial(jax.jit, static_argnames=("max_levels", "budget"))
def lowk_run(
    graph: BellGraph,
    queries: jax.Array,
    max_levels: Optional[int] = None,
    budget: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(K, S) queries -> per-query (f, levels, reached), whole BFS in one
    dispatch (shared 7-tuple loop, byte counts)."""
    frontier0 = lowk_pack(graph.n, queries)
    return bit_level_loop(
        frontier0,
        _lowk_counts(frontier0),
        lowk_expand(graph, budget),
        max_levels,
        counts_of=_lowk_counts,
    )


@jax.jit
def _lowk_init_carry(graph: BellGraph, queries: jax.Array):
    frontier0 = lowk_pack(graph.n, queries)
    return bit_level_init(frontier0, _lowk_counts(frontier0))


@donating_jit(donate_argnums=(1,), static_argnames=("max_levels", "budget"))
def _lowk_chunk(graph, carry, chunk, max_levels, budget):
    """One bounded dispatch; carry DONATED (driver rebinds it)."""
    return bit_level_chunk(
        carry,
        lowk_expand(graph, budget),
        chunk,
        max_levels,
        counts_of=_lowk_counts,
    )


@partial(jax.jit, static_argnames=("max_levels", "budget"))
def lowk_best_fused(graph, queries, k, max_levels=None, budget=0):
    """Whole byte-flag BFS + (minF, minK) selection in one XLA program
    returning one (2,) int64 buffer (``k`` traced; see
    ops.bitbell.bitbell_best_fused)."""
    f, _, _ = lowk_run(graph, queries, max_levels, budget)
    min_f, min_k = fused_select(f, k)
    return jnp.stack([min_f, min_k.astype(jnp.int64)])


def _lowk_best_tail(graph, carry, k, chunk, max_levels, budget):
    carry = bit_level_chunk(
        carry,
        lowk_expand(graph, budget),
        chunk,
        max_levels,
        counts_of=_lowk_counts,
    )
    return carry + (_pack_status(carry, k),)


@partial(jax.jit, static_argnames=("max_levels", "budget"))
def _lowk_start_chunk_best(graph, queries, k, chunk, max_levels, budget):
    """Packing + init + first chunk + selection, one dispatch (NOT
    donated: argnum 1 is the caller's query array)."""
    return _lowk_best_tail(
        graph, _lowk_init_carry(graph, queries), k, chunk, max_levels, budget
    )


@donating_jit(donate_argnums=(1,), static_argnames=("max_levels", "budget"))
def _lowk_chunk_best(graph, carry, k, chunk, max_levels, budget):
    """Continuation dispatch (7-tuple carry DONATED)."""
    return _lowk_best_tail(graph, carry, k, chunk, max_levels, budget)


class LowKEngine(FusedBestEngine):
    """Byte-flag all-queries-at-once engine over a BellGraph with NO
    query-axis padding (``k_align = 1``): the K <= 4 fast path.

    ``sparse_budget``: hybrid push threshold in edge slots (None
    auto-sizes from the dedup CSR like BitBellEngine; 0 = pure forest
    pulls).  ``level_chunk``/``megachunk``: per-dispatch level bound and
    fusion factor, same contract as the other bit-plane engines."""

    # Lattice axes (ops.engine.resolve_axes): the low-K byte-plane point.
    CAPABILITIES = frozenset(
        {"plane:byte", "residency:hbm", "partition:single", "kernel:xla"}
    )

    k_align = 1

    def __init__(
        self,
        graph: BellGraph,
        max_levels: Optional[int] = None,
        sparse_budget: Optional[int] = None,
        level_chunk: Optional[int] = None,
        megachunk: Optional[int] = None,
    ):
        self.graph = graph
        self.max_levels = max_levels
        if sparse_budget is None:
            e = graph.sparse[2].shape[0] if graph.sparse is not None else 0
            sparse_budget = default_sparse_budget(e) if e else 0
        if sparse_budget and graph.sparse is None:
            raise ValueError(
                "sparse_budget > 0 needs the BellGraph's dedup CSR "
                "(BellGraph.from_host(..., keep_sparse=True))"
            )
        self.sparse_budget = int(sparse_budget)
        self.level_chunk = validate_level_chunk(level_chunk)
        self.megachunk = resolve_megachunk(megachunk, self.level_chunk)

    def _run(self, queries):
        if self.level_chunk:
            # np.int32 traced bound: rides the dispatch (an eager jnp
            # scalar would be its own device commit).
            bound = np.int32(self.level_chunk * self.megachunk)
            carry = host_chunked_loop(
                _lowk_init_carry(self.graph, queries),
                lambda c: _lowk_chunk(
                    self.graph, c, bound, self.max_levels, self.sparse_budget
                ),
                self.max_levels,
                level_ix=5,
                updated_ix=6,
            )
            return carry[2], carry[3], carry[4]
        return lowk_run(
            self.graph, queries, self.max_levels, self.sparse_budget
        )

    def _fused_full(self, queries, k):
        return lowk_best_fused(
            self.graph, queries, k, self.max_levels, self.sparse_budget
        )

    def _fused_chunk(self, state, k, first):
        fn = _lowk_start_chunk_best if first else _lowk_chunk_best
        return fn(
            self.graph,
            state,
            k,
            np.int32(self.level_chunk * self.megachunk),
            self.max_levels,
            self.sparse_budget,
        )

    def f_values(self, queries) -> jax.Array:
        queries, k = self._pad_queries(queries)
        f, _, _ = self._run(queries)
        return f[:k]

    def query_stats(self, queries):
        queries, k = self._pad_queries(queries)
        f, levels, reached = self._run(queries)
        return (
            np.asarray(levels)[:k],
            np.asarray(reached)[:k],
            np.asarray(f)[:k],
        )
