"""Bit-packed BELL engine: oracle parity, packing helpers, stats parity."""

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
    pack_queries,
    unpack_counts,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, edges, q)) for q in queries]


GRAPHS = {
    "gnm": generators.gnm_edges(140, 460, seed=301),
    "grid": generators.grid_edges(19, 7),
    "rmat": generators.rmat_edges(8, edge_factor=8, seed=302),
    "sparse_disconnected": generators.gnm_edges(180, 70, seed=303),
}


def test_pack_unpack_roundtrip():
    n, k = 50, 64
    rng = np.random.default_rng(304)
    queries = np.full((k, 4), -1, dtype=np.int32)
    for i in range(k):
        g = rng.choice(n, size=rng.integers(0, 5), replace=False)
        queries[i, : len(g)] = g
    planes = np.asarray(pack_queries(n, queries))
    assert planes.shape == (n, k // 32) and planes.dtype == np.uint32
    counts = np.asarray(unpack_counts(planes))
    want = [len({s for s in q if 0 <= s < n}) for q in queries]
    np.testing.assert_array_equal(counts, want)
    # bit identity: query i's bit set exactly at its source rows
    for i in range(k):
        rows = np.nonzero((planes[:, i // 32] >> (i % 32)) & 1)[0]
        assert set(rows) == {s for s in queries[i] if 0 <= s < n}


@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_bitbell_matches_oracle(name):
    n, edges = GRAPHS[name]
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 11, max_group=5, seed=305)
    queries[2] = np.zeros(0, dtype=np.int32)
    padded = pad_queries(queries)
    eng = BitBellEngine(BellGraph.from_host(g))
    got = np.asarray(eng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_bitbell_k_not_multiple_of_32():
    n, edges = GRAPHS["gnm"]
    g = CSRGraph.from_edges(n, edges)
    bg = BellGraph.from_host(g)
    for k in (1, 31, 32, 33, 64):
        queries = generators.random_queries(n, k, max_group=3, seed=306 + k)
        padded = pad_queries(queries)
        got = np.asarray(BitBellEngine(bg).f_values(padded))
        np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))
        assert got.shape == (k,)


def test_bitbell_best_and_out_of_range():
    n, edges = GRAPHS["sparse_disconnected"]
    g = CSRGraph.from_edges(n, edges)
    queries = [
        np.array([0, -1, n + 5], dtype=np.int32),
        np.array([n - 1], dtype=np.int32),
        np.zeros(0, dtype=np.int32),
    ]
    padded = pad_queries(queries)
    eng = BitBellEngine(BellGraph.from_host(g))
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(np.asarray(eng.f_values(padded)), want)
    assert eng.best(padded) == oracle_best(want)


def test_bitbell_stats_match_packed():
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.packed import (
        PackedEngine,
    )

    n, edges = GRAPHS["grid"]
    g = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 7, max_group=3, seed=307)
    queries[3] = np.zeros(0, dtype=np.int32)  # levels=0 lane
    padded = pad_queries(queries)
    a = BitBellEngine(BellGraph.from_host(g)).query_stats(padded)
    b = PackedEngine(g.to_device()).query_stats(padded)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_bitbell_hub_star():
    n_leaves = 500
    n = n_leaves + 1
    edges = np.stack(
        [np.zeros(n_leaves, dtype=np.int64), np.arange(1, n, dtype=np.int64)],
        axis=1,
    )
    g = CSRGraph.from_edges(n, edges)
    queries = [np.array([0], dtype=np.int32), np.array([5], dtype=np.int32)]
    padded = pad_queries(queries)
    for widths in ((2, 8), (2, 8, 32, 128)):
        eng = BitBellEngine(BellGraph.from_host(g, widths=widths))
        got = np.asarray(eng.f_values(padded))
        np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


class TestHybridSparse:
    """Hybrid pull/push levels (sparse_hits_or / hybrid_expand) must be
    bit-exact with the pure forest path on every graph shape."""

    def _graphs(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
            generators,
        )

        yield "rmat_hubs", generators.rmat_edges(9, edge_factor=12, seed=901)
        yield "grid", generators.grid_edges(17, 13)
        yield "road", generators.road_edges(24, 24, seed=902)
        yield "gnm", generators.gnm_edges(150, 450, seed=903)
        n = 40  # star: one hub adjacent to everything (max-degree stress)
        hub = np.stack(
            [np.zeros(n - 1, dtype=np.int64), np.arange(1, n, dtype=np.int64)],
            axis=1,
        )
        yield "star", (n, hub)

    # The huge budget (~12 s: every level takes the sparse path) is
    # slow-marked out of tier-1 for wall-clock budget; 64 and 7 keep
    # the hybrid cutover parity covered, full set in `make test`.
    @pytest.mark.parametrize(
        "budget", [pytest.param(1 << 14, marks=pytest.mark.slow), 64, 7]
    )
    def test_hybrid_matches_dense(self, budget):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
            generators,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
            BellGraph,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
            BitBellEngine,
        )

        for name, (n, edges) in self._graphs():
            g = CSRGraph.from_edges(n, edges)
            queries = generators.random_queries(n, 5, max_group=4, seed=904)
            queries[1] = np.zeros(0, dtype=np.int32)
            padded = pad_queries(queries)
            bg = BellGraph.from_host(g)
            assert bg.sparse is not None
            dense = BitBellEngine(bg, sparse_budget=0)
            hybrid = BitBellEngine(bg, sparse_budget=budget)
            for a, b in zip(
                dense.query_stats(padded), hybrid.query_stats(padded)
            ):
                np.testing.assert_array_equal(a, b, err_msg=f"{name}/{budget}")

    def test_auto_budget_and_keep_sparse_flag(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
            generators,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
            BellGraph,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
            BitBellEngine,
            default_sparse_budget,
        )

        n, edges = generators.gnm_edges(100, 300, seed=905)
        g = CSRGraph.from_edges(n, edges)
        bg = BellGraph.from_host(g)
        eng = BitBellEngine(bg)
        assert eng.sparse_budget == default_sparse_budget(bg.sparse[2].shape[0])
        lean = BellGraph.from_host(g, keep_sparse=False)
        assert lean.sparse is None
        assert BitBellEngine(lean).sparse_budget == 0  # silently dense

    def test_byte_plane_roundtrip(self):
        import jax.numpy as jnp

        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
            pack_byte_planes,
            unpack_byte_planes,
        )

        rng = np.random.default_rng(906)
        words = jnp.asarray(
            rng.integers(0, 1 << 32, size=(13, 2), dtype=np.uint32)
        )
        np.testing.assert_array_equal(
            np.asarray(pack_byte_planes(unpack_byte_planes(words))),
            np.asarray(words),
        )

    def test_hybrid_matches_oracle_on_road(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
            generators,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
            BellGraph,
        )
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
            BitBellEngine,
        )

        from oracle import oracle_bfs, oracle_f

        n, edges = generators.road_edges(20, 20, seed=907)
        g = CSRGraph.from_edges(n, edges)
        queries = generators.random_queries(n, 6, max_group=3, seed=908)
        padded = pad_queries(queries)
        eng = BitBellEngine(BellGraph.from_host(g), sparse_budget=256)
        got = np.asarray(eng.f_values(padded))
        want = [oracle_f(oracle_bfs(n, edges.astype(np.int64), q)) for q in queries]
        np.testing.assert_array_equal(got, want)


def test_estimate_hbm_bytes_routing_properties():
    """The CLI's HBM routing relies on: K padding to word multiples, only
    edge-proportional terms shrinking with vertex shards, and
    monotonicity in n/e."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )

    est = BellGraph.estimate_hbm_bytes
    # K in (32, 64] pads to 64: estimates must match K=64, not K=32.
    assert est(1 << 20, 1 << 25, 40) == est(1 << 20, 1 << 25, 64)
    assert est(1 << 20, 1 << 25, 40) > est(1 << 20, 1 << 25, 32)
    # Sharding divides the edge terms (and drops the single-chip hybrid
    # CSR + byte scratch: the sharded loop is pull-only) but NOT the
    # plane terms.
    one = est(1 << 20, 1 << 25, 64)
    two = est(1 << 20, 1 << 25, 64, vertex_shards=2)
    assert two < one
    # More shards keep shrinking toward the unsharded plane floor.
    eight = est(1 << 20, 1 << 25, 64, vertex_shards=8)
    assert eight < two
    assert eight > 16 * 2 * (1 << 20)  # plane floor: 16 B * words * n
    assert est(1 << 21, 1 << 25, 64) > one  # monotone in n
    assert est(1 << 20, 1 << 26, 64) > one  # monotone in e


class TestSlotBudget:
    """Gather-segment streaming (forest_hits slot_budget) must be bit-exact
    with the single merged gather (slot_budget=0) at ANY budget — including
    budgets far below every bucket width, where each row becomes its own
    segment.  This is the path that keeps wide-plane runs (RMAT-24 x K=256)
    inside HBM; CI never reaches that regime organically, so we force it
    (ADVICE r4, medium)."""

    def _graphs(self):
        # Multi-bucket level layouts: rmat (power-law -> several widths),
        # road/grid (uniform low degree), star (one max-width bucket).
        yield "rmat", generators.rmat_edges(9, edge_factor=12, seed=911)
        yield "road", generators.road_edges(24, 24, seed=912)
        n = 40
        hub = np.stack(
            [np.zeros(n - 1, dtype=np.int64), np.arange(1, n, dtype=np.int64)],
            axis=1,
        )
        yield "star", (n, hub)

    # budget=1 (~33 s: maximal segmentation, every slot its own gather)
    # and budget=7 (~34 s) are slow-marked out of tier-1 for wall-clock
    # budget; 64 keeps the segmented-parity coverage, and `make test`
    # runs the full set.
    @pytest.mark.parametrize(
        "budget",
        [
            pytest.param(1, marks=pytest.mark.slow),
            pytest.param(7, marks=pytest.mark.slow),
            64,
        ],
    )
    def test_slot_budget_matches_unsegmented(self, budget):
        for name, (n, edges) in self._graphs():
            g = CSRGraph.from_edges(n, edges)
            bg = BellGraph.from_host(g)
            queries = generators.random_queries(n, 37, max_group=4, seed=913)
            queries[1] = np.zeros(0, dtype=np.int32)
            padded = pad_queries(queries)
            base = BitBellEngine(bg, sparse_budget=0, slot_budget=0)
            want = base.query_stats(padded)
            seg = BitBellEngine(bg, sparse_budget=0, slot_budget=budget)
            for a, b in zip(want, seg.query_stats(padded)):
                np.testing.assert_array_equal(a, b, err_msg=f"{name}/{budget}")

    # Both budgets (~30 s each) slow-marked out of tier-1 for wall-clock
    # budget: segmented-gather parity stays covered by
    # test_slot_budget_matches_unsegmented[64] and the stats-trace pin;
    # the hybrid+chunked composition runs in `make test`.
    @pytest.mark.parametrize(
        "budget",
        [
            pytest.param(7, marks=pytest.mark.slow),
            pytest.param(64, marks=pytest.mark.slow),
        ],
    )
    def test_slot_budget_hybrid_and_chunked(self, budget):
        for name, (n, edges) in self._graphs():
            g = CSRGraph.from_edges(n, edges)
            bg = BellGraph.from_host(g)
            queries = generators.random_queries(n, 33, max_group=4, seed=914)
            padded = pad_queries(queries)
            want = BitBellEngine(bg, sparse_budget=0, slot_budget=0).query_stats(
                padded
            )
            # Hybrid pull/push: dense levels stream within budget, thin
            # levels take the push scatter — same counters either way.
            hyb = BitBellEngine(bg, sparse_budget=32, slot_budget=budget)
            for a, b in zip(want, hyb.query_stats(padded)):
                np.testing.assert_array_equal(
                    a, b, err_msg=f"hybrid {name}/{budget}"
                )
            # Host-chunked dispatch loop on top of segmented gathers.
            chk = BitBellEngine(
                bg, sparse_budget=0, slot_budget=budget, level_chunk=2
            )
            for a, b in zip(want, chk.query_stats(padded)):
                np.testing.assert_array_equal(
                    a, b, err_msg=f"chunked {name}/{budget}"
                )

    def test_slot_budget_level_stats_parity(self):
        """MSBFS_STATS=2's stepped trace honors the budget (ADVICE r4, low):
        stats from the traced loop must match the production loop when a
        tiny budget forces segmentation in both."""
        n, edges = generators.road_edges(16, 16, seed=915)
        g = CSRGraph.from_edges(n, edges)
        bg = BellGraph.from_host(g)
        queries = pad_queries(
            generators.random_queries(n, 5, max_group=3, seed=916)
        )
        eng = BitBellEngine(bg, sparse_budget=0, slot_budget=13)
        levels, reached, f, lc, secs = eng.level_stats(queries)
        want = eng.query_stats(queries)
        np.testing.assert_array_equal(levels, want[0])
        np.testing.assert_array_equal(reached, want[1])
        np.testing.assert_array_equal(f, want[2])
        assert lc.shape[0] == len(secs)

    def test_msbfs_slot_budget_env(self, monkeypatch):
        n, edges = GRAPHS["gnm"]
        g = CSRGraph.from_edges(n, edges)
        bg = BellGraph.from_host(g)
        monkeypatch.setenv("MSBFS_SLOT_BUDGET", "17")
        eng = BitBellEngine(bg)
        assert eng._slot_budget_arg == 17
        assert eng._slot_budget_for(2) == 17
        # 0 = never segment, even where auto would engage.
        monkeypatch.setenv("MSBFS_SLOT_BUDGET", "0")
        assert BitBellEngine(bg)._slot_budget_for(2) is None
        # Malformed value falls back to auto (None arg), like every other
        # env knob in the package.
        monkeypatch.setenv("MSBFS_SLOT_BUDGET", "banana")
        assert BitBellEngine(bg)._slot_budget_arg is None
        # Constructor arg wins over env.
        monkeypatch.setenv("MSBFS_SLOT_BUDGET", "99")
        assert BitBellEngine(bg, slot_budget=5)._slot_budget_arg == 5
        # Env parse happens at construction: results must match the
        # unsegmented engine bit-for-bit.
        monkeypatch.setenv("MSBFS_SLOT_BUDGET", "9")
        queries = pad_queries(
            generators.random_queries(n, 6, max_group=3, seed=917)
        )
        a = BitBellEngine(bg, sparse_budget=0).query_stats(queries)
        monkeypatch.delenv("MSBFS_SLOT_BUDGET")
        b = BitBellEngine(bg, sparse_budget=0, slot_budget=0).query_stats(
            queries
        )
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestFusedBest:
    """The r5 fused best() (one program: pack + init + level loop +
    argmin) must agree with the generic run-then-select path everywhere —
    including the alignment-padding lanes, whose F=0 empty-group results
    would tie-win over every real query if the fused selection failed to
    mask them (fused_select)."""

    # Two arms (~7 s each) pin the fused/generic parity in tier-1 — one
    # unchunked power-law, one chunked grid; the remaining 6 of the 4x2
    # graph x level_chunk matrix are slow-marked for wall-clock budget
    # and ride in `make test`.
    @pytest.mark.parametrize(
        "name,level_chunk",
        [
            ("rmat", None),
            ("grid", 3),
            pytest.param("rmat", 3, marks=pytest.mark.slow),
            pytest.param("grid", None, marks=pytest.mark.slow),
            pytest.param("gnm", None, marks=pytest.mark.slow),
            pytest.param("gnm", 3, marks=pytest.mark.slow),
            pytest.param(
                "sparse_disconnected", None, marks=pytest.mark.slow
            ),
            pytest.param("sparse_disconnected", 3, marks=pytest.mark.slow),
        ],
    )
    def test_matches_generic_best(self, name, level_chunk):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
            QueryEngineBase,
        )

        n, edges = GRAPHS[name]
        g = CSRGraph.from_edges(n, edges)
        bg = BellGraph.from_host(g)
        for k in (1, 5, 31, 33):
            queries = generators.random_queries(
                n, k, max_group=4, seed=500 + k
            )
            padded = pad_queries(queries)
            eng = BitBellEngine(bg, level_chunk=level_chunk)
            # The generic path: f_values (trimmed to k) + select_best.
            want = QueryEngineBase.best(eng, padded)
            assert eng.best(padded) == want
            assert want == oracle_best(oracle_f_values(n, edges, queries))

    def test_padding_lane_cannot_win(self):
        # Every real query has F > 0, so an unmasked padding lane (F=0)
        # would win the argmin; the fused path must return the real one.
        n, edges = GRAPHS["grid"]
        g = CSRGraph.from_edges(n, edges)
        queries = [np.array([0], dtype=np.int32)]  # k=1 -> 31 pad lanes
        padded = pad_queries(queries)
        for level_chunk in (None, 4):
            eng = BitBellEngine(
                BellGraph.from_host(g), level_chunk=level_chunk
            )
            min_f, min_k = eng.best(padded)
            assert min_k == 0 and min_f > 0

    def test_k_zero_and_max_levels(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.engine import (
            QueryEngineBase,
        )

        n, edges = GRAPHS["gnm"]
        g = CSRGraph.from_edges(n, edges)
        bg = BellGraph.from_host(g)
        empty = np.zeros((0, 3), dtype=np.int32)
        for level_chunk in (None, 2):
            eng = BitBellEngine(bg, level_chunk=level_chunk)
            assert eng.best(empty) == (-1, -1)
            capped = BitBellEngine(bg, max_levels=2, level_chunk=level_chunk)
            queries = generators.random_queries(n, 7, max_group=3, seed=507)
            padded = pad_queries(queries)
            assert capped.best(padded) == QueryEngineBase.best(capped, padded)

    def test_compile_warms_continuation(self):
        # compile() must pre-trace BOTH chunked programs; afterwards a
        # deep run introduces no new compilation (smoke: it just works and
        # agrees with the oracle).
        n, edges = GRAPHS["grid"]
        g = CSRGraph.from_edges(n, edges)
        queries = generators.random_queries(n, 3, max_group=2, seed=509)
        padded = pad_queries(queries)
        eng = BitBellEngine(BellGraph.from_host(g), level_chunk=2)
        eng.compile(padded.shape)
        want = oracle_best(oracle_f_values(n, edges, queries))
        assert eng.best(padded) == want


def test_sparse_hits_or_edgeless_graph():
    """Forcing a sparse budget on an edgeless graph must be well-defined:
    the dedup CSR is empty, and the general path's index arithmetic would
    clip into inverted bounds (advisor r2).  Sources are reached, nothing
    else; a direct sparse_hits_or call returns all-zero hit planes."""
    import jax.numpy as jnp

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        pack_queries,
        sparse_hits_or,
    )

    n = 16
    g = CSRGraph.from_edges(n, np.zeros((0, 2), dtype=np.int64))
    bg = BellGraph.from_host(g)
    assert bg.sparse is not None and bg.sparse[2].shape[0] == 0
    queries = pad_queries([np.array([3], dtype=np.int32)], pad_to=4)
    frontier = pack_queries(n, jnp.asarray(np.tile(queries, (32, 1))))
    hits = np.asarray(sparse_hits_or(frontier, bg, budget=8))
    assert (hits == 0).all()
    eng = BitBellEngine(bg, sparse_budget=8)
    levels, reached, f = eng.query_stats(np.tile(queries, (32, 1)))
    assert (reached == 1).all() and (f == 0).all() and (levels == 1).all()
