"""Gridless Pallas tile-matmul chain for the MXU frontier expansion.

The mxu engine's dense level is a batch of per-tile products: for every
nonzero adjacency tile ``A[b]`` (T, T) the hit counts of its destination
rows gain ``A[b] @ F[col(b)]`` where ``F[col(b)]`` is the (T, K) byte
view of the source block's frontier (ops/mxu.py).  That product is the
canonical MXU shape — contraction 128-wide at the default tile — so this
module expresses it as ``jnp.dot(..., preferred_element_type=f32)``
inside a Pallas kernel, the one matmul form the Mosaic path accepts
(/opt guide; ops/dense.py uses the same via XLA).

Production constraint carried over from the stencil chain
(docs/PALLAS_LOG.md round 5): ONLY gridless whole-VMEM ``pallas_call``s
compile on this stack — every gridded variant crashes the remote AOT
compile helper.  So the batch dimension is chunked MANUALLY in XLA glue:
tiles are row-stacked into a 2-D (B*T, T) operand (3-D refs are another
Mosaic gamble this stack doesn't need), cut into batches whose f32
product chunk fits the ~2 MB single-VMEM-block budget, and each batch
runs one gridless call.  ``lru_cache`` keeps at most two compiled
programs per (T, K) shape (body batch + tail batch) — the
ops/pallas_stencil.py chain discipline.

Exactness: the 0/1 int8 operands cast to bf16 inside the kernel (exact
for 0/1), and the f32 ``preferred_element_type`` accumulates integer
counts exactly below 2^24 — per-tile sums are <= T, far inside.  Off-TPU
the chain runs ``interpret=True`` so CPU CI pins bit-identity against
the XLA einsum formulation (tests/test_mxu.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# One gridless call's f32 product chunk budget: (B*T, K) * 4 bytes <= 2 MB
# — the output dominates the int8 inputs 4:1 (T=128, K=256: B <= 16).
MAX_OUT_BYTES = 2 << 20


def tile_batch(t: int, k: int) -> int:
    """Tiles per gridless call under the VMEM product budget."""
    return max(1, MAX_OUT_BYTES // (t * k * 4))


def make_tile_kernel(batch, t):
    """Fused one-VMEM-pass tile-product batch: read the row-stacked
    adjacency tiles and frontier blocks once, emit every per-tile MXU
    product once.  ``batch`` is a static python int, so the per-tile loop
    unrolls into static row slices."""

    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[...]  # (batch*t, t) int8 row-stacked adjacency tiles
        b = b_ref[...]  # (batch*t, k) int8 row-stacked frontier blocks
        outs = []
        for i in range(batch):
            ab = a[i * t : (i + 1) * t].astype(jnp.bfloat16)
            fb = b[i * t : (i + 1) * t].astype(jnp.bfloat16)
            outs.append(
                jnp.dot(ab, fb, preferred_element_type=jnp.float32)
            )
        o_ref[...] = outs[0] if batch == 1 else jnp.concatenate(outs, 0)

    return kernel


@functools.lru_cache(maxsize=None)
def _tile_call(batch, t, k, interpret):
    """One gridless whole-VMEM pallas_call per (batch, tile, K) — cached
    so the chain compiles at most two programs per plane shape (body
    batch + tail batch)."""
    import jax.experimental.pallas as pl

    kwargs = {}
    if not interpret:
        import jax.experimental.pallas.tpu as pltpu

        kwargs = dict(
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
    return pl.pallas_call(
        make_tile_kernel(batch, t),
        out_shape=jax.ShapeDtypeStruct((batch * t, k), jnp.float32),
        interpret=interpret,
        **kwargs,
    )


def pallas_tile_products(tiles: jax.Array, rhs: jax.Array) -> jax.Array:
    """(nt, T, T) int8 tiles x (nt, T, K) int8 frontier blocks ->
    (nt, T, K) f32 per-tile products, as a chain of gridless Pallas calls
    (interpreter mode off-TPU, so CPU CI pins bit-identity)."""
    from ..utils.platform import is_tpu_backend

    nt, t, _ = tiles.shape
    k = rhs.shape[2]
    interpret = not is_tpu_backend()
    batch = tile_batch(t, k)
    a2 = tiles.reshape(nt * t, t)
    b2 = rhs.reshape(nt * t, k)
    parts = []
    for cs in range(0, nt, batch):
        ce = min(cs + batch, nt)
        a_c = lax.slice_in_dim(a2, cs * t, ce * t, axis=0)
        b_c = lax.slice_in_dim(b2, cs * t, ce * t, axis=0)
        parts.append(_tile_call(ce - cs, t, k, interpret)(a_c, b_c))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
    return out.reshape(nt, t, k)
