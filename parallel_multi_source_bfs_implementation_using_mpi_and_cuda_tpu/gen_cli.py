"""Fixture generator CLI: fabricate reference-format graph/query binaries.

The reference consumes opaque ``graph.bin``/``query.bin`` files (formats at
main.cu:92-130 and 134-164) but ships no tool to create them; this generator
fills that gap so a user can produce workloads end to end:

    python -m parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.gen_cli \
        --kind rmat --scale 16 --edge-factor 16 --graph g.bin \
        --queries 64 --max-group 64 --query-file q.bin --seed 42

Kinds: ``rmat`` (power-law, Graph500-style), ``grid`` (side x side
4-neighbor lattice), ``road`` (calibrated road-network stand-in: sparse
irregular grid + diagonals + regional shortcuts, see
models.generators.road_edges), ``gnm`` (uniform random).

Dynamic fixtures: ``--deltas <file>`` additionally emits a binary
edge-delta file against the generated graph (insert/delete batches with
a seeded ``--delta-locality`` knob, ``dynamic.delta`` format) — the one
fixture format the dynamic tests, bench config 8 and ``make perf-smoke``
all share.

Real datasets: ``--convert <file>`` ingests a public graph instead of
generating one — DIMACS ``.gr`` (USA-road-d family, ``--informat dimacs``)
or SNAP whitespace edge lists (``--informat snap``), .gz transparently —
and writes it in the reference binary format.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--kind", choices=("rmat", "grid", "road", "gnm"), default="rmat")
    ap.add_argument(
        "--convert",
        default=None,
        metavar="FILE",
        help="convert a real dataset instead of generating (--informat)",
    )
    ap.add_argument(
        "--informat",
        choices=("dimacs", "snap"),
        default="dimacs",
        help="--convert input format: DIMACS .gr or SNAP edge list",
    )
    ap.add_argument("--scale", type=int, default=16, help="log2(n) for rmat; grid/road side = 2^(scale/2)")
    ap.add_argument("--edge-factor", type=int, default=16, help="edges per vertex (rmat/gnm)")
    ap.add_argument("--graph", required=True, help="output graph .bin path")
    ap.add_argument("--queries", type=int, default=0, help="number of query groups (0: no query file)")
    ap.add_argument("--max-group", type=int, default=64, help="max sources per group (<= 128)")
    ap.add_argument("--query-file", default=None)
    ap.add_argument(
        "--deltas",
        default=None,
        metavar="FILE",
        help="also emit a binary edge-delta file against the generated "
        "graph (dynamic.delta format; docs/SERVING.md 'Mutations & "
        "versions')",
    )
    ap.add_argument(
        "--delta-batches", type=int, default=1, help="batches in --deltas"
    )
    ap.add_argument(
        "--delta-size",
        type=int,
        default=16,
        help="mutations per batch (half inserts, half deletes)",
    )
    ap.add_argument(
        "--delta-locality",
        type=float,
        default=0.9,
        help="0..1: 1 = street-closure-sized patch, 0 = whole-graph churn",
    )
    ap.add_argument(
        "--weights",
        choices=("uniform", "zipf"),
        default=None,
        metavar="DIST",
        help="also emit a trailing integer edge-cost section (uniform or "
        "zipf, seeded; the weighted/ subsystem's artifact)",
    )
    ap.add_argument(
        "--max-cost",
        type=int,
        default=16,
        help="--weights cost ceiling (costs drawn in [1, max-cost])",
    )
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)

    # Validate the query flags BEFORE the (potentially minutes-long) graph
    # generation, so bad flags fail instantly and side-effect-free.
    if args.queries and not args.query_file:
        print("--queries given without --query-file", file=sys.stderr)
        return 2
    if args.query_file and not args.queries:
        print("--query-file given without --queries", file=sys.stderr)
        return 2
    if args.queries and (
        not 0 < args.queries <= 255 or not 0 < args.max_group <= 128
    ):
        # uint8 K / uint8 set_size wire format (main.cu:143-152)
        print("--queries must be 1..255, --max-group 1..128", file=sys.stderr)
        return 2
    if args.deltas and (
        args.delta_batches < 1
        or args.delta_size < 1
        or not 0.0 <= args.delta_locality <= 1.0
    ):
        print(
            "--delta-batches/--delta-size must be >= 1, "
            "--delta-locality in [0, 1]",
            file=sys.stderr,
        )
        return 2
    if args.weights and args.max_cost < 1:
        print("--max-cost must be >= 1", file=sys.stderr)
        return 2

    from .models import generators
    from .utils.io import (
        load_dimacs_gr,
        load_edgelist,
        save_graph_bin,
        save_query_bin,
    )

    if args.convert:
        defaults = {"kind": "rmat", "scale": 16, "edge_factor": 16}
        ignored = [
            f"--{k.replace('_', '-')}"
            for k, d in defaults.items()
            if getattr(args, k) != d
        ]
        if ignored:
            print(
                f"--convert takes the graph from {args.convert}; "
                f"ignoring generation flags: {', '.join(ignored)}",
                file=sys.stderr,
            )
        try:
            if args.informat == "dimacs":
                n, edges = load_dimacs_gr(args.convert)
            else:
                n, edges = load_edgelist(args.convert)
        except (IOError, OSError, ValueError, OverflowError) as exc:
            # OverflowError: vertex id beyond int32 (loaders fail loud).
            print(f"convert failed: {exc}", file=sys.stderr)
            return 1
    elif args.kind == "rmat":
        n, edges = generators.rmat_edges(
            args.scale, edge_factor=args.edge_factor, seed=args.seed
        )
    elif args.kind == "grid":
        side = 1 << (args.scale // 2)
        n, edges = generators.grid_edges(side, side)
    elif args.kind == "road":
        side = 1 << (args.scale // 2)
        n, edges = generators.road_edges(side, side, seed=args.seed)
    else:
        n = 1 << args.scale
        n, edges = generators.gnm_edges(
            n, args.edge_factor * n, seed=args.seed
        )
    weights = None
    if args.weights:
        # Cost stream is seeded off --seed + 3 so adding --weights to an
        # existing fixture recipe keeps the graph/query/delta streams
        # byte-identical (same convention as the +1/+2 offsets below).
        weights = generators.edge_costs(
            len(edges), dist=args.weights, max_cost=args.max_cost,
            seed=args.seed + 3,
        )
    save_graph_bin(args.graph, n, edges, weights=weights)
    wnote = f" weights={args.weights}[1,{args.max_cost}]" if args.weights else ""
    print(f"wrote {args.graph}: n={n} m={len(edges)}{wnote}", file=sys.stderr)

    if args.queries:
        qs = generators.random_queries(
            n, args.queries, max_group=args.max_group, seed=args.seed + 1
        )
        save_query_bin(args.query_file, qs)
        print(
            f"wrote {args.query_file}: K={len(qs)} sizes="
            f"{[len(q) for q in qs[:8]]}{'...' if len(qs) > 8 else ''}",
            file=sys.stderr,
        )

    if args.deltas:
        from .dynamic.delta import save_delta_bin

        batches = generators.delta_batches(
            n,
            edges,
            batches=args.delta_batches,
            batch_size=args.delta_size,
            locality=args.delta_locality,
            seed=args.seed + 2,
        )
        save_delta_bin(args.deltas, n, batches)
        sizes = [(len(i), len(d)) for i, d in batches[:8]]
        print(
            f"wrote {args.deltas}: batches={len(batches)} "
            f"(ins, del)={sizes}{'...' if len(batches) > 8 else ''}",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
