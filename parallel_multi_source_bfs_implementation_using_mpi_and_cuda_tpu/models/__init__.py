"""Graph representations: host CSR, device-resident CSR, dense adjacency."""

from .csr import CSRGraph, DeviceCSR
from . import generators

__all__ = ["CSRGraph", "DeviceCSR", "generators"]
