"""CLI driver — the public entry point, preserving the reference contract.

Reference (main.cu:195-422): ``mpirun -np <ranks> ./main -g <graph.bin>
-q <query.bin> -gn <numGPU>``.  Here: ``python main.py -g <graph.bin>
-q <query.bin> -gn <numChips>`` (no mpirun; the mesh covers all chips in
one process per host).  Contract kept exactly:

* hand-rolled argv scan for -g/-q/-gn, unknown flags silently ignored,
  ``-gn`` defaults to 1 (main.cu:214-224);
* fewer than 4 post-program args -> usage on stderr, exit code -1
  (main.cu:204-212);
* two timing spans and the 7-line rank-0 report, 9-decimal fixed times,
  1-based winning query (main.cu:403-414).

``-gn`` maps to the number of mesh devices used for query sharding (the
reference's GPUs-per-node device binding, main.cu:227-228); it is clamped to
the available chips but *reported* as given, like the reference reports the
flag value (main.cu:411).
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

import numpy as np


from .utils import knobs


def _env_int(name: str, default: int) -> int:
    """Integer env knob via the central registry (utils/knobs.py):
    malformed values fall back to the default rather than crash."""
    return knobs.get_int(name, default)


def _env_float(name: str, default: float) -> float:
    """Float env knob, same malformed-falls-back convention."""
    return knobs.get_float(name, default)


def parse_args(argv: List[str]):
    """Linear argv scan, reference-exact (main.cu:216-224)."""
    graph_file: Optional[str] = None
    query_file: Optional[str] = None
    num_gpu = 1
    i = 1
    while i < len(argv):
        if argv[i] == "-g" and i + 1 < len(argv):
            i += 1
            graph_file = argv[i]
        elif argv[i] == "-q" and i + 1 < len(argv):
            i += 1
            query_file = argv[i]
        elif argv[i] == "-gn" and i + 1 < len(argv):
            i += 1
            try:
                num_gpu = int(argv[i])
            except ValueError:
                num_gpu = 0  # atoi semantics: non-numeric -> 0
        i += 1
    return graph_file, query_file, num_gpu


# Levels per dispatch for the auto bound.  Retuned 32 -> 128 after the
# first on-chip deep-graph measurement (road-1024/K=16, TPU v5e, raw in
# benchmarks/raw_r4/road_single_shootout3.txt): the ~100 ms tunnel
# dispatch floor makes 66 chunk-32 dispatches cost 18% of the whole
# computation span, vs 4.6% at 128.  128 thin levels remain orders of
# magnitude below the per-dispatch work that crashed the TPU worker
# (docs/PERF_NOTES.md "Push-engine TPU status"), and shallow power-law
# BFS exits the in-dispatch loop on convergence either way.
_AUTO_LEVEL_CHUNK = 128

# Backends with no 1D-distributed variant: at -gn > 1 WITHOUT a 2D mesh
# they warn and fall back to the distributed bitbell.  ("csr"/"vmap" map
# to the per-query pull and "push" to real multi-chip routes, so they are
# absent here.)  The MSBFS_MESH route does NOT consult this list — it
# resolves the engine lattice instead, where lowk (plane:byte), mxu
# (kernel:mxu) and streamed (residency:streamed) all compose with
# partition:mesh2d and the rest fail loud naming the missing token.
_SINGLE_CHIP_ONLY_BACKENDS = (
    "dense",
    "pallas",
    "bell",
    "packed",
    "ppush",
    "stencil",
    "streamed",
    "lowk",
    "mxu",
)
# Backends whose HBM footprint the bitbell estimate does not model — the
# single-chip capacity warning stays quiet for these.
_NON_BITBELL_FOOTPRINT_BACKENDS = _SINGLE_CHIP_ONLY_BACKENDS + (
    "vmap",
    "push",
)


def _road_class(graph) -> bool:
    """Deep-BFS degree profile (road networks/grids: low max and mean
    degree mean thousands of BFS levels).  Routing hint ONLY — it keeps
    the dense MXU engine off deep graphs and selects which warnings
    print; the bounded level loop itself no longer depends on it
    (round 4, see :func:`_level_chunk_policy`)."""
    if graph.n == 0 or graph.num_directed_edges == 0:
        return False
    mean_deg = graph.num_directed_edges / graph.n
    return int(graph.degrees.max()) <= 64 and mean_deg <= 8.0


_UNSET = object()


def _explicit_level_chunk() -> Optional[int]:
    """Parsed MSBFS_LEVEL_CHUNK, or None when unset/empty (empty means
    unset, like the file's other optional knobs) or malformed.  A
    MALFORMED value warns and falls back to the auto policy — a typo must
    not switch off a safety mitigation."""
    raw = knobs.raw("MSBFS_LEVEL_CHUNK")
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        print(
            f"MSBFS_LEVEL_CHUNK={raw!r} is not an integer; "
            "using the auto bound",
            file=sys.stderr,
        )
        return None


def _level_chunk_policy(graph, explicit=_UNSET) -> Optional[int]:
    """Per-dispatch level bound for the level-loop engines (None = whole
    BFS in one dispatch).  ALWAYS bounded by default (round 4): the
    round-3 degree heuristic could be fooled — a single >64-degree hub on
    an otherwise deep graph silently took the unbounded single-dispatch
    path, exactly the pattern that crashed the TPU worker
    (docs/PERF_NOTES.md "Push-engine TPU status").  The bounded loop
    exits its in-dispatch while_loop on convergence, so a shallow
    power-law BFS pays one host scalar sync total; measured at or below
    the unchunked path on both graph classes (benchmarks/
    exp_chunk_cost.py: RMAT-17/18 ratios 0.90-0.98, road 0.98-0.99 on
    the CPU backend).  MSBFS_LEVEL_CHUNK: > 0 forces the bound, 0
    explicitly disables it (single unbounded dispatch); malformed/empty
    fall back to auto (:func:`_explicit_level_chunk`).  The reference
    runs any graph at any -gn (per-rank serial BFS, main.cu:303-322);
    this unconditional bound is what keeps that promise here."""
    if explicit is _UNSET:
        explicit = _explicit_level_chunk()
    if explicit is not None:
        if explicit > 0:
            return explicit
        if explicit == 0:
            return None  # the documented explicit opt-out
        # Negative = sign typo, not an opt-out: warn and keep the bound.
        print(
            f"MSBFS_LEVEL_CHUNK={explicit} is negative; "
            "using the auto bound (0 disables)",
            file=sys.stderr,
        )
    if graph.n == 0 or graph.num_directed_edges == 0:
        return None
    return _AUTO_LEVEL_CHUNK


def _bitbell_ladder(graph, level_chunk):
    """Degradation rungs for the default single-chip bitbell route: on
    RESOURCE_EXHAUSTED the supervisor (runtime.supervisor) swaps in the
    next rung and re-runs the chunk instead of dying — wide-plane ->
    level-chunked -> streamed, the same ladder the up-front HBM estimate
    picks from, now applied reactively when the estimate was wrong.
    Factories are lazy: a rung's layout is built only when reached."""
    from .models.bell import BellGraph
    from .ops.bitbell import BitBellEngine
    from .ops.streamed import StreamedBitBellEngine

    rungs = []
    if not level_chunk:
        rungs.append((
            "level-chunked",
            lambda: BitBellEngine(
                BellGraph.from_host(graph), level_chunk=_AUTO_LEVEL_CHUNK
            ),
        ))
    rungs.append((
        "streamed",
        lambda: BitBellEngine(
            BellGraph.from_host(graph, keep_sparse=False),
            sparse_budget=0,
            level_chunk=min(level_chunk or 8, 8),
            # Deliberate safety bound — never megachunk-multiplied.
            megachunk=1,
            slot_budget=(
                1 << 25 if not knobs.raw("MSBFS_SLOT_BUDGET") else None
            ),
        ),
    ))
    # Last rung (round 6): the forest never enters HBM at all — host-
    # resident cols streamed through the device with double-buffered
    # uploads (ops.streamed).  Slower per level, but survives graphs
    # whose in-HBM streamed layout still exhausts memory.
    rungs.append((
        "host-streamed",
        lambda: StreamedBitBellEngine(
            BellGraph.from_host(graph, keep_sparse=False, device=False),
            slot_budget=(
                1 << 25 if not knobs.raw("MSBFS_SLOT_BUDGET") else None
            ),
        ),
    ))
    return rungs


def verify_main(argv: List[str]) -> int:
    """``msbfs verify``: offline certification of distance-to-set
    answers (docs/RESILIENCE.md "Silent data corruption").

    Recomputes the distance fields with the untrusted host sweep,
    certifies the recompute against the four BFS invariants, and checks
    a claimed F vector against the certified field.  The claim is either
    ``--expect-f`` (a stored query response's ``f_values`` — certifying
    results after the fact) or, by default, a fresh run of the stock
    serving engine under a full audit — a standalone hardware-distrust
    pass over this machine.  Exit 0: certified.  Exit 9
    (:class:`~.runtime.supervisor.CorruptionError`): the failing
    invariants are named on stderr.
    """
    import argparse
    import json

    import numpy as np

    ap = argparse.ArgumentParser(
        prog="msbfs-tpu verify",
        description="Certify distance-to-set answers against the BFS "
        "invariants (docs/RESILIENCE.md)",
    )
    ap.add_argument("-g", "--graph", required=True, metavar="GRAPH.bin",
                    help="reference-format graph .bin")
    ap.add_argument("-q", "--query", required=True, metavar="QUERY.bin",
                    help="reference-format query .bin")
    ap.add_argument(
        "--expect-f", default=None, metavar="F",
        help="claimed F values to certify: a JSON list, or @PATH to a "
        "JSON file (e.g. a stored response's f_values).  Default: run "
        "the stock engine under a full audit and certify its output.",
    )
    ap.add_argument(
        "--weighted", action="store_true",
        help="certify against the weighted (edge-cost) invariants; "
        "also implied by MSBFS_WEIGHTED=1.  The graph must carry a "
        "cost section.",
    )
    args = ap.parse_args(argv)

    from .ops import certify
    from .runtime.supervisor import CorruptionError, InputError, MsbfsError
    from .utils.io import load_graph_bin, load_query_bin, pad_queries
    from .utils.report import format_failure

    from .utils import knobs

    weighted = args.weighted or knobs.raw("MSBFS_WEIGHTED", "") == "1"
    try:
        try:
            graph = load_graph_bin(args.graph)
            queries = pad_queries(load_query_bin(args.query))
        except (OSError, ValueError) as exc:
            raise InputError(str(exc)) from exc
        if weighted and not graph.has_weights:
            raise InputError(
                f"--weighted verify of {args.graph}: the artifact "
                "carries no edge-cost section (regenerate with "
                "gen_cli --weights)"
            )
        if args.expect_f is not None:
            raw = args.expect_f
            if raw.startswith("@"):
                try:
                    with open(raw[1:], "r", encoding="utf-8") as fh:
                        raw = fh.read()
                except OSError as exc:
                    raise InputError(str(exc)) from exc
            try:
                f_claimed = np.asarray(json.loads(raw), dtype=np.int64)
            except (ValueError, TypeError) as exc:
                raise InputError(
                    f"--expect-f is not a JSON int list: {exc}"
                ) from exc
            source = "stored F values"
        elif weighted:
            from .serve.registry import build_supervised_weighted_engine

            supervisor = build_supervised_weighted_engine(graph)
            # Full audit regardless of MSBFS_AUDIT: verification is the
            # entire point of this verb, not a sampled overhead trade.
            if supervisor.auditor is None:
                supervisor.auditor = certify.make_weighted_auditor(graph)
            supervisor.audit_sample = 1.0
            f_claimed = np.asarray(
                supervisor.f_values(queries), dtype=np.int64
            )
            source = "weighted engine output"
        else:
            from .serve.registry import build_supervised_engine

            supervisor = build_supervised_engine(graph)
            # Full audit regardless of MSBFS_AUDIT: verification is the
            # entire point of this verb, not a sampled overhead trade.
            if supervisor.auditor is None:
                supervisor.auditor = certify.make_auditor(graph)
            supervisor.audit_sample = 1.0
            f_claimed = np.asarray(
                supervisor.f_values(queries), dtype=np.int64
            )
            source = "engine output"
        if weighted:
            failing = certify.audit_weighted_f_values(
                graph.row_offsets, graph.col_indices, graph.edge_weights,
                queries, f_claimed,
            )
        else:
            failing = certify.audit_f_values(
                graph.row_offsets, graph.col_indices, queries, f_claimed
            )
        if failing:
            raise CorruptionError(
                f"verification of {source} FAILED for {args.graph} / "
                f"{args.query}: invariants violated: "
                f"{', '.join(failing)}",
                invariants=failing,
            )
    except MsbfsError as err:
        from .utils.telemetry import dump_flight

        dump_flight(f"exit_{err.exit_code}")
        print(format_failure(err), file=sys.stderr)
        return err.exit_code
    print(
        f"verify: CERTIFIED {source} — {queries.shape[0]} queries on "
        f"{graph.n} vertices / {graph.m} edges; "
        f"F = {[int(x) for x in np.atleast_1d(f_claimed)]}"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv if argv is None else argv)
    # Serving-runtime subcommands (docs/SERVING.md) dispatch BEFORE the
    # reference argv contract: ``serve`` runs the persistent daemon,
    # ``query`` the thin client.  Neither word collides with the
    # reference grammar (whose post-program tokens are -g/-q/-gn flag
    # pairs, main.cu:216-224), so the batch path below stays
    # reference-exact for every existing invocation.
    if len(argv) > 1 and argv[1] == "serve":
        # ``--epoch-file`` arms membership fencing: a frame stamped with
        # a view other than the file's current value is refused with
        # FencedError, exit code 10 (docs/SERVING.md "Cross-machine
        # transport & fencing").
        from .serve.server import serve_main

        return serve_main(argv[2:])
    if len(argv) > 1 and argv[1] == "query":
        from .serve.client import query_main

        return query_main(argv[2:])
    if len(argv) > 1 and argv[1] == "fleet":
        # Replicated serving fleet: N replica daemons behind the
        # rendezvous-placement failover router (docs/SERVING.md "Fleet").
        from .serve.router import fleet_main

        return fleet_main(argv[2:])
    if len(argv) > 1 and argv[1] == "health":
        # Probe alias: ``msbfs health --connect ...`` is the external
        # health check's whole command line (docs/SERVING.md).
        from .serve.client import query_main

        return query_main(argv[2:] + ["--health"])
    if len(argv) > 1 and argv[1] == "trace":
        # Per-query distributed trace export: fetch a trace's span
        # events from a daemon or fleet front end and print Chrome-trace
        # JSON for Perfetto (docs/OBSERVABILITY.md).
        from .serve.client import trace_main

        return trace_main(argv[2:])
    if len(argv) > 1 and argv[1] == "verify":
        # Offline output certification (docs/RESILIENCE.md "Silent data
        # corruption"): exit 0 = certified, exit 9 = corrupt.
        return verify_main(argv[2:])
    if len(argv) > 1 and argv[1] == "analyze":
        # Repo-native static analysis (docs/ANALYSIS.md): trace-safety
        # lint, lock discipline, knob + error contracts.  Imports only
        # the AST passes — no jax — so CI can gate on it cheaply.
        from .analysis.cli import analyze_main

        return analyze_main(argv[2:])
    if len(argv) < 5:  # argc < 5, reference main.cu:204-212
        print(
            f"Usage: python {argv[0] if argv else 'main.py'} "
            "-g <graph.bin> -q <query.bin> -gn <numChips>",
            file=sys.stderr,
        )
        return -1

    graph_file, query_file, num_gpu = parse_args(argv)
    if graph_file is None or query_file is None:
        print("Missing -g or -q argument", file=sys.stderr)
        return -1

    # Resilience layer bring-up (runtime.supervisor, docs/RESILIENCE.md):
    # install the fault plan BEFORE any load so the loader seams see it.
    # A fresh plan per main() call keeps repeated in-process runs (tests)
    # deterministic.  A malformed MSBFS_FAULTS is a fail-loud InputError:
    # a typo'd plan silently arming nothing would make every recovery
    # rehearsal vacuous.
    from .utils import faults
    from .utils.report import format_failure

    try:
        fault_plan = faults.FaultPlan.from_env()
    except ValueError as exc:
        from .runtime.supervisor import InputError

        err = InputError(str(exc))
        print(format_failure(err), file=sys.stderr)
        return err.exit_code
    faults.activate(fault_plan)

    import jax

    # Multi-host bring-up (the mpirun analog, reference main.cu:197-201):
    # launch one process per host with MSBFS_COORDINATOR=<addr:port>,
    # MSBFS_NUM_PROCESSES and MSBFS_PROCESS_ID set; the mesh then spans
    # every host's devices and XLA's collectives ride ICI/DCN.  Unset =
    # single-process (the common case).  Genuine bring-up failures
    # propagate, like MPI_Init aborting.  MUST run before anything that
    # initializes the XLA backend (jax.distributed's own contract).
    coordinator = knobs.raw("MSBFS_COORDINATOR")
    if coordinator:
        from .parallel.mesh import initialize_distributed

        initialize_distributed(
            coordinator_address=coordinator,
            num_processes=_env_int("MSBFS_NUM_PROCESSES", 1),
            process_id=_env_int("MSBFS_PROCESS_ID", 0),
        )

    from .utils.platform import is_tpu_backend
    from .utils.xla_cache import configure_compilation_cache

    configure_compilation_cache()

    from .ops.engine import Engine
    from .parallel.distributed import DistributedEngine
    from .parallel.mesh import make_mesh
    from .utils.io import load_graph_bin, load_query_bin, pad_queries
    from .utils.report import format_report
    from .utils.timing import (
        Span,
        dispatch_count,
        record_dispatch,
        reset_dispatch_count,
    )

    # ---- preprocessing span: load + device placement (+ XLA compile),
    # the analog of main.cu:235-298 (load + MPI broadcast + H2D upload).
    from .runtime.supervisor import classify

    with Span() as pre:
        try:
            graph = load_graph_bin(graph_file)
        except (IOError, OSError, ValueError, IndexError) as exc:
            # Typed taxonomy instead of a blanket net: corrupt contents /
            # unreadable files classify as InputError, whose exit code IS
            # the reference's EXIT_FAILURE (main.cu:95-99); anything else
            # (an injected device fault, say) keeps its own documented
            # code (docs/RESILIENCE.md).
            err = classify(exc)
            print(f"Could not open graph file {graph_file}", file=sys.stderr)
            print(format_failure(err), file=sys.stderr)
            return err.exit_code
        try:
            queries = load_query_bin(query_file)
        except (IOError, OSError, ValueError, IndexError) as exc:
            err = classify(exc)
            print(f"Could not open query file {query_file}", file=sys.stderr)
            print(format_failure(err), file=sys.stderr)
            return err.exit_code
        padded = pad_queries(queries)
        if jax.process_count() > 1:
            # Multi-host: -gn is devices PER HOST (the reference's per-rank
            # GPU binding, main.cu:227-228 `rank % numGPU`), and the mesh
            # must span every process — a mesh over one host's chips would
            # hand other ranks non-addressable devices.  per_host derives
            # from the GLOBAL device list, not this process's local count:
            # on heterogeneous hosts every rank must compute the same
            # per_host or they build divergent meshes (SPMD mismatch).
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            per_host = max(
                1, min(num_gpu, min(len(v) for v in by_proc.values()))
            )
            mesh_devices = [
                d for pid in sorted(by_proc) for d in by_proc[pid][:per_host]
            ]
        else:
            mesh_devices = jax.devices()[: max(1, min(num_gpu, len(jax.devices())))]
        n_chips = len(mesh_devices)
        # HBM routing: estimate the default engine's footprint and compare
        # to the per-chip budget.  A graph beyond one chip auto-routes to
        # the vertex-sharded engine (multi-chip) or warns (single chip) —
        # the int32/HBM guard is a routing decision, not an error.
        from .models.bell import BellGraph
        from .utils.platform import device_hbm_bytes

        hbm_need = BellGraph.estimate_hbm_bytes(
            graph.n, graph.num_directed_edges, max(32, padded.shape[0])
        )
        hbm_have = device_hbm_bytes()
        explicit_chunk = _explicit_level_chunk()
        level_chunk = _level_chunk_policy(graph, explicit_chunk)
        road_class = _road_class(graph)
        # Megachunk policy (round 6): an EXPLICIT MSBFS_LEVEL_CHUNK is a
        # deliberate per-dispatch bound — honor it exactly (one chunk per
        # dispatch).  The AUTO bound exists only so no dispatch performs
        # unbounded work; the fused engines may fold several chunks into
        # one dispatch with an on-device early exit, amortizing the
        # ~100 ms tunnel floor (ops.bitbell.resolve_megachunk; None =
        # auto / MSBFS_MEGACHUNK override).
        megachunk = (
            1 if (explicit_chunk is not None and explicit_chunk > 0) else None
        )

        def announce_chunk():
            # Printed ONLY when the selected engine actually applies the
            # bound AND the graph's profile predicts a deep BFS (the case
            # the user cares about); the bound itself is on for every
            # graph — a user-forced backend without a chunked path must
            # not claim the mitigation is active.
            if level_chunk and road_class:
                print(
                    "road-class degree profile: bounding bit-plane "
                    f"dispatches to {level_chunk} BFS levels "
                    "(MSBFS_LEVEL_CHUNK overrides)",
                    file=sys.stderr,
                )

        # Capacity-degradation rungs for the supervisor; populated by the
        # routes that have a documented smaller-footprint fallback.
        ladder_rungs = []
        mesh_spec = knobs.raw("MSBFS_MESH", "").strip()
        weighted_route = knobs.raw("MSBFS_WEIGHTED", "") == "1"
        if weighted_route:
            # MSBFS_WEIGHTED=1: integer-cost distance-to-set through the
            # bucketed delta-stepping subsystem (weighted/).  F(U) becomes
            # a COST sum; the graph artifact must carry a cost section
            # (gen_cli --weights) or the route refuses with the typed
            # input error.  Flavor selection (MSBFS_WEIGHTED_ENGINE:
            # auto/bitbell/stencil/mesh2d) goes through the same
            # capability-token negotiation as the 2D mesh route — an
            # impossible ask fails loud naming the missing tokens.
            from . import weighted as weighted_pkg
            from .runtime.supervisor import InputError

            try:
                wlabel, engine = weighted_pkg.negotiate_weighted_engine(
                    graph
                )
            except InputError as err:
                print(format_failure(err), file=sys.stderr)
                return err.exit_code
            except (TypeError, ValueError) as exc:
                print(str(exc), file=sys.stderr)
                return 1
            print(
                f"weighted route: {wlabel}, delta={engine.delta} "
                "(MSBFS_WEIGHTED_ENGINE / MSBFS_DELTA override)",
                file=sys.stderr,
            )
        elif n_chips > 1 and mesh_spec:
            # MSBFS_MESH=RxC selects the 2D adjacency partition
            # (parallel/partition2d.py): the CSR is tiled over an (R, C)
            # device mesh so each chip holds an n/R x n/C adjacency tile,
            # and per-level traffic is a row-axis segment gather plus a
            # col-axis OR-reduce-scatter — payload scales with n/(R*C)
            # instead of the 1D row shard's full-frontier allgather.
            # MSBFS_MERGE_TREE picks the col-axis reduction tree
            # (auto/oneshot/ring/halving/pipelined); MSBFS_WIRE_SPARSE /
            # MSBFS_WIRE_CHUNKS shape the density-adaptive wire format.
            # The route resolves the FULL engine lattice: MSBFS_BACKEND
            # pins axis defaults (lowk -> plane:byte, mxu -> kernel:mxu)
            # and the direct axis knobs MSBFS_MESH_PLANE /
            # MSBFS_MESH_KERNEL / MSBFS_MESH_RESIDENCY override per axis,
            # so "low-K byte planes on a streamed mesh" or "MXU tile
            # matmul on the mesh" are negotiated compositions, not new
            # engine classes.  resolve_axes + negotiate_engine fail loud
            # (typed NegotiationError naming the missing tokens) when no
            # registered engine composes the ask — e.g. stencil's banded
            # layout or a word-plane backend on the 2D mesh.
            from .ops.engine import (
                engine_label,
                negotiate_engine,
                resolve_axes,
            )
            from .parallel.mesh import make_mesh2d, parse_mesh_spec
            from .parallel.partition2d import Mesh2DEngine

            try:
                rows, cols = parse_mesh_spec(mesh_spec)
                if rows * cols != n_chips:
                    raise ValueError(
                        f"MSBFS_MESH={mesh_spec} wants {rows * cols} chips "
                        f"but -gn selected {n_chips}"
                    )
                backend = knobs.raw("MSBFS_BACKEND", "auto")
                if backend in ("auto", "csr"):
                    backend = "bitbell"  # the mesh default plane layout
                residency = (
                    knobs.raw("MSBFS_MESH_RESIDENCY") or "hbm"
                ).strip().lower()
                plane = (
                    knobs.raw("MSBFS_MESH_PLANE") or ""
                ).strip().lower() or None
                kernel = (
                    knobs.raw("MSBFS_MESH_KERNEL") or ""
                ).strip().lower() or None
                async_levels = max(
                    1, knobs.get_int("MSBFS_ASYNC_LEVELS", 1)
                )
                axes, required = resolve_axes(
                    backend,
                    partition="mesh2d",
                    residency=residency,
                    plane=plane,
                    kernel=kernel,
                    async_levels=async_levels,
                )
                label = engine_label(axes, async_levels=async_levels)
                _, engine = negotiate_engine(
                    required,
                    [
                        (
                            label,
                            Mesh2DEngine,
                            lambda: Mesh2DEngine(
                                make_mesh2d(
                                    rows, cols, devices=mesh_devices
                                ),
                                graph,
                                level_chunk=level_chunk,
                                merge_tree=(
                                    knobs.raw("MSBFS_MERGE_TREE")
                                    or None
                                ),
                                residency=axes["residency"],
                                async_levels=async_levels,
                                plane=axes["plane"],
                                kernel=axes["kernel"],
                            ),
                        ),
                    ],
                )
            except (TypeError, ValueError) as exc:
                # Malformed spec, mesh/chip mismatch, bad merge tree, or
                # no capable engine: same user-facing engine-choice error
                # style as the push route.
                print(str(exc), file=sys.stderr)
                return 1
            print(
                f"mesh route: {label} ({rows}x{cols}, "
                f"{', '.join(sorted(required))})",
                file=sys.stderr,
            )
            announce_chunk()
        elif n_chips > 1:
            # MSBFS_VSHARD=v splits the CSR over a 'v' mesh axis of that
            # size (vertex sharding for graphs beyond one chip's HBM —
            # beyond-reference capability, parallel/sharded_bell.py);
            # remaining chips shard queries.  Default: all chips on 'q',
            # graph replicated (the reference's model, main.cu:242-255) —
            # unless the replicated footprint exceeds the chip budget, in
            # which case the smallest sufficient vertex-shard count that
            # divides the chips is chosen automatically.
            vshard = _env_int("MSBFS_VSHARD", 0)
            if vshard == 0:
                vshard = 1
                if hbm_need > hbm_have:
                    k_est = max(32, padded.shape[0])
                    for v in range(2, n_chips + 1):
                        # Re-estimate per shard count: only edge-
                        # proportional terms shrink (planes stay global).
                        if n_chips % v == 0 and BellGraph.estimate_hbm_bytes(
                            graph.n, graph.num_directed_edges, k_est, v
                        ) <= hbm_have:
                            vshard = v
                            break
                    else:
                        vshard = n_chips
                    print(
                        f"graph needs ~{hbm_need >> 20} MiB"
                        f" > {hbm_have >> 20} MiB/chip: auto-sharding the"
                        f" CSR over {vshard} of {n_chips} chips"
                        " (MSBFS_VSHARD overrides)",
                        file=sys.stderr,
                    )
            if vshard > 1 and n_chips % vshard != 0:
                print(
                    f"MSBFS_VSHARD={vshard} does not divide {n_chips} chips;"
                    " falling back to replicated-graph query sharding",
                    file=sys.stderr,
                )
            # MSBFS_BACKEND is honored at -gn > 1 too (round-3; it used to
            # be single-chip only): "csr"/"vmap" selects the per-query CSR
            # pull per shard, "push" the query-sharded work-optimal push
            # engine (road-class); everything else runs the bitbell
            # default, with a warning for backends that only exist
            # single-chip.
            backend = knobs.raw("MSBFS_BACKEND", "auto")
            if backend in _SINGLE_CHIP_ONLY_BACKENDS:
                print(
                    f"MSBFS_BACKEND={backend} is single-chip only; using "
                    "the distributed bitbell engine at -gn > 1",
                    file=sys.stderr,
                )
                backend = "auto"
            if vshard > 1 and n_chips % vshard == 0:
                mesh = make_mesh(
                    num_query_shards=n_chips // vshard,
                    num_vertex_shards=vshard,
                    devices=mesh_devices,
                )
                # Engine choice on the ('q', 'v') mesh: the owner-
                # partitioned push (parallel.push_sharded — work-optimal,
                # per-level cost proportional to the wavefront) serves
                # "push" explicitly and road-class graphs on auto, width
                # cap permitting; the sharded bitbell forest
                # (parallel.sharded_bell) is the default for everything
                # else and the fallback when push cannot apply.
                engine = None
                if backend == "push" or (backend == "auto" and road_class):
                    from .parallel.push_sharded import ShardedPushEngine

                    try:
                        engine = ShardedPushEngine(
                            mesh, graph, level_chunk=level_chunk
                        )
                        announce_chunk()
                    except ValueError as exc:
                        if backend == "push":
                            # Explicit choice: surface the engine error
                            # like the single-chip push route.
                            print(str(exc), file=sys.stderr)
                            return 1
                        print(
                            f"auto: {exc}; using the sharded bitbell "
                            "engine",
                            file=sys.stderr,
                        )
                elif backend in ("csr", "vmap"):
                    print(
                        f"MSBFS_BACKEND={backend} has no vertex-sharded "
                        "variant; using the sharded bitbell engine",
                        file=sys.stderr,
                    )
                if engine is None:
                    from .parallel.sharded_bell import ShardedBellEngine

                    announce_chunk()

                    def _opt_env_int(name):
                        # None = unset (engine auto-sizes); 0 disables.
                        raw = knobs.raw(name)
                        if raw is None or raw == "":
                            return None
                        try:
                            return int(raw)
                        except ValueError:
                            return None

                    engine = ShardedBellEngine(
                        mesh,
                        graph,
                        level_chunk=level_chunk,
                        halo_budget=_opt_env_int("MSBFS_HALO_BUDGET"),
                        push_budget=_opt_env_int("MSBFS_PUSH_HALO"),
                    )
            elif backend == "push":
                from .parallel.push_dist import DistributedPushEngine

                try:
                    engine = DistributedPushEngine(
                        make_mesh(
                            num_query_shards=n_chips, devices=mesh_devices
                        ),
                        graph,
                    )
                except ValueError as exc:
                    # Degree beyond the width cap: same user-facing
                    # engine-choice error as the single-chip push route.
                    print(str(exc), file=sys.stderr)
                    return 1
            else:
                mesh = make_mesh(
                    num_query_shards=n_chips, devices=mesh_devices
                )
                if backend in ("csr", "vmap"):
                    if road_class or (explicit_chunk or 0) > 0:
                        # The distributed per-query pull is the one path
                        # left without a bounded level loop; say so both
                        # when the graph looks deep and when the user
                        # explicitly asked for a bound it can't honor.
                        print(
                            f"warning: MSBFS_BACKEND={backend} has no "
                            "bounded-dispatch level loop at -gn > 1; a "
                            "high-diameter graph may exceed per-dispatch "
                            "limits (unset MSBFS_BACKEND for the chunked "
                            "bitbell engine)",
                            file=sys.stderr,
                        )
                    engine = DistributedEngine(mesh, graph, backend="csr")
                else:
                    announce_chunk()
                    engine = DistributedEngine(
                        mesh, graph, level_chunk=level_chunk
                    )
        else:
            # Backend selection (beyond-reference knob, env-controlled so the
            # argv contract stays reference-exact): "dense" runs frontier
            # expansion as a bf16 matmul on the MXU, worthwhile when the
            # n^2 adjacency fits HBM; "auto" picks it for small graphs on
            # MXU-bearing devices only.
            backend = knobs.raw("MSBFS_BACKEND", "auto")
            hbm_warn = (
                hbm_need > hbm_have
                and backend not in _NON_BITBELL_FOOTPRINT_BACKENDS
            )
            # Every single-chip backend honors level_chunk (round 4):
            # the generic Engine (dense/vmap/pallas), BellEngine and
            # PackedEngine run the host-chunked distance loop
            # (ops.bfs.host_chunked_loop), bitbell its bit-plane dual,
            # and the push engine chunks natively — so no backend choice
            # can reach an unbounded dispatch.
            #
            # Stencil routing (round 5): road-class graphs are probed for
            # a banded adjacency decomposition — lattices/grids, where
            # frontier expansion is a handful of masked shifts instead of
            # gathers, breaking the per-level gather/compaction floor on
            # thousands-of-levels BFS (ops.stencil).  Auto-only on
            # road-class profiles (the O(m) host probe is skipped for
            # power-law graphs); MSBFS_STENCIL=0 disables,
            # MSBFS_BACKEND=stencil forces (error if not banded).
            engine = None
            if backend == "stencil" or (
                backend == "auto"
                and road_class
                and knobs.raw("MSBFS_STENCIL", "") != "0"
            ):
                from .ops.stencil import (
                    AUTO_STENCIL_LEVEL_CHUNK,
                    StencilEngine,
                    StencilGraph,
                )

                try:
                    sg = StencilGraph.from_host(graph)
                except ValueError as exc:
                    if backend == "stencil":
                        print(str(exc), file=sys.stderr)
                        return 1
                    sg = None  # auto probe failed: keep the gather engines
                if sg is not None:
                    # Stencil levels are gather-free bandwidth streams, so
                    # the auto dispatch bound can be much larger than the
                    # gather engines' (ops.stencil); an explicit
                    # MSBFS_LEVEL_CHUNK still wins.  A NEGATIVE explicit
                    # value is the warned sign-typo case: it must land on
                    # the stencil auto bound, not the gather engines' 128
                    # that _level_chunk_policy fell back to (review r5).
                    stencil_chunk = (
                        level_chunk
                        if explicit_chunk is not None and explicit_chunk >= 0
                        else (AUTO_STENCIL_LEVEL_CHUNK if level_chunk else None)
                    )
                    print(
                        "banded adjacency detected: stencil engine "
                        f"({len(sg.offsets)} offsets, "
                        f"{int(sg.res_src.shape[0])} residual edges, "
                        f"{stencil_chunk or 'unbounded'} levels/dispatch; "
                        "MSBFS_STENCIL=0 disables)",
                        file=sys.stderr,
                    )
                    engine = StencilEngine(
                        sg, level_chunk=stencil_chunk, megachunk=megachunk
                    )
            # Low-K fast path (round 7): for a handful of queries the
            # bit-plane engines pad K to the 32-lane word and stream 4
            # bytes/vertex to move <= 4 bits; the byte-flag engine
            # (ops.lowk) keeps K as-is — 1 byte/vertex at K=1, the
            # BASELINE config-1 shape — with the same hybrid pull/push
            # and fused single-dispatch best().  Auto-only when no
            # earlier route claimed the graph; MSBFS_LOWK=0 disables,
            # MSBFS_BACKEND=lowk forces.  MSBFS_STATS=2 keeps the
            # bitbell route: the per-level trace rides its stepped
            # loop, and a trace request outranks the byte diet.
            if engine is None and (
                backend == "lowk"
                or (
                    backend == "auto"
                    and not hbm_warn
                    and 0 < padded.shape[0] <= _env_int("MSBFS_LOWK_MAX_K", 4)
                    and knobs.raw("MSBFS_LOWK", "") != "0"
                    and knobs.raw("MSBFS_STATS", "") != "2"
                )
            ):
                from .models.bell import BellGraph
                from .ops.lowk import LowKEngine

                print(
                    f"low-K fast path: byte-flag engine for "
                    f"{padded.shape[0]} queries (MSBFS_LOWK=0 disables)",
                    file=sys.stderr,
                )
                announce_chunk()
                engine = LowKEngine(
                    BellGraph.from_host(graph),
                    level_chunk=level_chunk,
                    megachunk=megachunk,
                )
            use_dense = backend == "dense"
            if backend == "auto" and is_tpu_backend():
                threshold = _env_int("MSBFS_DENSE_THRESHOLD", 8192)
                # Road-class profiles skip the dense engine: thousands of
                # n^2-matmul levels is the worst shape for a deep BFS even
                # chunked; the bitbell forest below is the cheaper path.
                # A mis-detected profile is now a perf miss, not a safety
                # hole — the dense loop is bounded too.
                use_dense = graph.n <= threshold and not road_class
            if engine is not None:
                pass  # stencil route above
            elif use_dense:
                from .ops.dense import DenseGraph

                engine = Engine(
                    DenseGraph.from_host(graph), level_chunk=level_chunk
                )
            elif backend == "vmap":
                engine = Engine(graph.to_device(), level_chunk=level_chunk)
            elif backend == "pallas":
                # ELL-slab layout + Pallas VMEM-resident-frontier kernel.
                from .models.ell import EllGraph

                engine = Engine(
                    EllGraph.from_host(graph), level_chunk=level_chunk
                )
            elif backend == "bell":
                # Scatter-free bucketed-ELL reduction forest (ops.bell);
                # pull-only, so skip the hybrid's dedup-CSR upload.
                from .models.bell import BellGraph
                from .ops.bell import BellEngine

                engine = BellEngine(
                    BellGraph.from_host(graph, keep_sparse=False),
                    level_chunk=level_chunk,
                )
            elif backend == "mxu":
                # Tensor-core frontier expansion (ops.mxu): adjacency
                # packed into dense per-tile blocks (all-zero tiles
                # skipped via a host-built index), one level = a blocked
                # tile x frontier matmul with OR-accumulate counts, with
                # a per-level density switch back to the gather push for
                # thin frontiers (MSBFS_MXU_SWITCH; MSBFS_MXU_TILE sizes
                # the tiles, MSBFS_MXU_KERNEL=1 runs the Pallas chain).
                from .ops.mxu import MxuEngine, MxuGraph

                try:
                    mg = MxuGraph.from_host(graph)
                except ValueError as exc:
                    # Tile cap exceeded: a user-facing engine-choice
                    # error, like the push width cap.
                    print(str(exc), file=sys.stderr)
                    return 1
                announce_chunk()
                engine = MxuEngine(
                    mg, level_chunk=level_chunk, megachunk=megachunk
                )
            elif backend == "push":
                # Frontier-compacted queue BFS: work-optimal on
                # high-diameter, low-degree graphs (road networks, grids).
                from .ops.push import PaddedAdjacency, PushEngine

                try:
                    engine = PushEngine(PaddedAdjacency.from_host(graph))
                except ValueError as exc:
                    # Degree beyond the width cap: a user-facing
                    # engine-choice error.
                    print(str(exc), file=sys.stderr)
                    return 1
            elif backend == "ppush":
                # Packed-lane union-frontier push (ops.push_packed): one
                # compacted queue serves all K bit-packed queries, so the
                # per-level hit scatter is C*w ROWS for the whole batch
                # instead of K separate lanes (measured 5.4x over the
                # vmapped push on road-1024/K=16, BASELINE.md config 4).
                from .ops.push import PaddedAdjacency
                from .ops.push_packed import PackedPushEngine

                try:
                    engine = PackedPushEngine(
                        PaddedAdjacency.from_host(graph)
                    )
                except ValueError as exc:
                    print(str(exc), file=sys.stderr)
                    return 1
            elif backend == "streamed":
                # Host-resident BELL forest, streamed through the device
                # per BFS level with double-buffered uploads
                # (ops.streamed).  The forest never occupies HBM — the
                # opt-in route for graphs beyond even the slot-budget
                # streamed layout (the auto over-HBM path below reaches
                # it via the degradation ladder).
                from .models.bell import BellGraph
                from .ops.streamed import StreamedBitBellEngine

                engine = StreamedBitBellEngine(
                    BellGraph.from_host(
                        graph, keep_sparse=False, device=False
                    )
                )
            elif backend == "packed":
                # Coalesced query-major (n, K) engine over the flat CSR.
                # MSBFS_EDGE_CHUNKS bounds the per-level (E/chunks, K)
                # gather intermediate on HBM-constrained chips.
                from .ops.packed import PackedEngine

                edge_chunks = _env_int("MSBFS_EDGE_CHUNKS", 1)
                engine = PackedEngine(
                    graph.to_device(),
                    edge_chunks=edge_chunks,
                    level_chunk=level_chunk,
                )
            else:
                # Default CSR path: bit-packed BELL reduction forest — the
                # fastest measured engine (RMAT-20/64q on v5e: 2x the packed
                # CSR path; see BASELINE.md).
                from .models.bell import BellGraph
                from .ops.bitbell import BitBellEngine

                if hbm_warn:
                    # The estimate models this default HYBRID layout
                    # (forest + dedup CSR + byte-lane scratch; the other
                    # backends have different footprints and the stencil
                    # route a far smaller one, so ONLY this path prints —
                    # review r5).  Round 5: instead of warning and
                    # probably OOMing, drop the hybrid CSR and run the
                    # streamed pure-pull configuration — the
                    # RMAT-25-certified constants
                    # (benchmarks/raw_r5/bench_rmat25.json): no dedup
                    # CSR, 32M-slot gather segments, at most 8 levels per
                    # dispatch (an unchunked wide-plane dispatch is what
                    # crashed the TPU worker, raw_r5 root cause).
                    # Explicit MSBFS_LEVEL_CHUNK/MSBFS_SLOT_BUDGET still
                    # win via the normal knobs.  Printed in place of
                    # announce_chunk() so the stated bound is the one
                    # that actually runs.
                    streamed_chunk = (
                        min(level_chunk or 8, 8)
                        if explicit_chunk is None or explicit_chunk < 0
                        else level_chunk
                    )
                    if explicit_chunk == 0:
                        # ADVICE r5: an explicit 0 (unbounded) here is
                        # exactly the unchunked wide-plane dispatch this
                        # branch exists to avoid (it crashed the TPU
                        # worker, raw_r5 root cause) — clamp to the
                        # streamed bound instead of honoring it, loudly.
                        streamed_chunk = 8
                        print(
                            "MSBFS_LEVEL_CHUNK=0 would issue an unbounded "
                            "wide-plane dispatch on an over-HBM graph "
                            "(documented worker crash); clamping to 8 "
                            "levels/dispatch",
                            file=sys.stderr,
                        )
                    print(
                        f"graph needs ~{hbm_need >> 20} MiB (hybrid "
                        f"layout) but one chip has {hbm_have >> 20} MiB: "
                        "dropping the hybrid CSR and streaming per-level "
                        "gathers within budget, "
                        f"{streamed_chunk or 'unbounded'} levels/dispatch "
                        "(slower, and a graph beyond even the streamed "
                        "layout may still exhaust memory; run with "
                        "-gn > 1 to auto-shard instead)",
                        file=sys.stderr,
                    )
                    engine = BitBellEngine(
                        BellGraph.from_host(graph, keep_sparse=False),
                        sparse_budget=0,
                        level_chunk=streamed_chunk,
                        # The streamed chunk IS a deliberate safety bound
                        # (an unchunked wide-plane dispatch crashed the
                        # worker): never megachunk-multiply it.
                        megachunk=1,
                        slot_budget=(
                            1 << 25
                            if not knobs.raw("MSBFS_SLOT_BUDGET")
                            else None
                        ),
                    )
                else:
                    announce_chunk()
                    engine = BitBellEngine(
                        BellGraph.from_host(graph),
                        level_chunk=level_chunk,
                        megachunk=megachunk,
                    )
                    ladder_rungs = _bitbell_ladder(graph, level_chunk)

        # ---- sub-batch split (round 7, K=1024 regime): past ~256 queries
        # one program's planes outgrow the cache-friendly working set
        # (BASELINE round 6: K=1024 6.27 vs K=256 8.05 GTEPS), so very
        # wide batches run as ordered 256-wide sub-batches against the
        # SAME device graph buffers (ops.packed.SubBatchEngine; strict-<
        # winner merge keeps the first-minimum tie-break bit-identical).
        # Single-chip only — the distributed engine shards queries its
        # own way.  MSBFS_SUBBATCH_K resizes, 0 disables.  The
        # degradation ladder's rungs are rebuilt engines and stay
        # unwrapped: a degraded run trades the split for survival.
        subbatch_k = _env_int("MSBFS_SUBBATCH_K", 256)
        if (
            n_chips == 1
            and engine is not None
            and subbatch_k > 0
            and padded.shape[0] > subbatch_k
        ):
            from .ops.packed import SubBatchEngine

            print(
                f"wide batch: splitting {padded.shape[0]} queries into "
                f"{subbatch_k}-wide sub-batches (MSBFS_SUBBATCH_K=0 "
                "disables)",
                file=sys.stderr,
            )
            engine = SubBatchEngine(engine, batch_k=subbatch_k)

        # ---- resilient execution (runtime.supervisor): every engine call
        # below runs supervised — watchdog, typed taxonomy, transient
        # retry with backoff, capacity degradation down the ladder,
        # survivor resharding on chip loss.  Knobs: MSBFS_WATCHDOG
        # (seconds, 0/unset = off), MSBFS_RETRIES, MSBFS_BACKOFF,
        # MSBFS_FAULT_SEED (replayable jitter).  docs/RESILIENCE.md.
        from .runtime.supervisor import (
            ChunkSupervisor,
            MsbfsError,
            RetryPolicy,
        )

        engine = ChunkSupervisor(
            engine,
            policy=RetryPolicy(
                max_retries=_env_int("MSBFS_RETRIES", 2),
                base_delay=_env_float("MSBFS_BACKOFF", 0.1),
                seed=_env_int("MSBFS_FAULT_SEED", 0),
            ),
            watchdog=_env_float("MSBFS_WATCHDOG", 0.0) or None,
            ladder=ladder_rungs,
            plan=fault_plan,
        )
        if weighted_route:
            # MSBFS_AUDIT on the weighted route certifies every sampled
            # F against the weighted five-invariant certificate
            # (ops.certify.WEIGHTED_INVARIANTS) — a flunk escalates to
            # CorruptionError exit 9 exactly like the unit-cost serve
            # path.
            from .ops.certify import make_weighted_auditor
            from .serve.registry import audit_sample_rate

            audit_rate = audit_sample_rate()
            if audit_rate > 0.0:
                engine.auditor = make_weighted_auditor(graph)
                engine.audit_sample = audit_rate
        stats_env = knobs.raw("MSBFS_STATS", "")
        stats_mode = stats_env in ("1", "2")
        # MSBFS_STATS=2: additionally trace each BFS level (frontier size,
        # wall time) via the engine's stepped loop, when it has one.
        stats_level = stats_env == "2" and callable(
            getattr(engine, "level_stats", None)
        )
        ckpt_path = knobs.raw("MSBFS_CHECKPOINT")
        ckpt_chunk = _env_int("MSBFS_CHECKPOINT_CHUNK", 64)
        try:
            if ckpt_path:
                # The checkpoint path calls f_values/query_stats on
                # (chunk, S) slices, not best() on the full (K, S) batch —
                # warm exactly those shapes so XLA compiles land in the
                # preprocessing span.  MSBFS_STATS rides the journal
                # (round 4): per-chunk levels/reached are recorded
                # alongside F, so the longest runs are no longer the
                # blindest ones.
                k, s = padded.shape
                for shape_k in {min(max(1, ckpt_chunk), max(k, 1)), *(
                    [k % ckpt_chunk] if k % ckpt_chunk else []
                )}:
                    dummy = np.full((shape_k, s), -1, dtype=np.int32)
                    if not (
                        stats_mode and engine.query_stats(dummy) is not None
                    ):
                        engine.f_values(dummy)
            else:
                engine.compile(
                    padded.shape,
                    warm_stats=stats_mode and not stats_level,
                    warm_levels=stats_level,
                )
        except MsbfsError as err:
            # The supervisor exhausted its recovery budget during warm-up:
            # same one-line report + documented exit code as a failure in
            # the computation span.
            from .utils.telemetry import dump_flight

            dump_flight(f"exit_{err.exit_code}")
            print(format_failure(err, engine.events), file=sys.stderr)
            return err.exit_code

    # ---- computation span: all BFS + objective + argmin (main.cu:301-400).
    # MSBFS_PROFILE_DIR captures a jax.profiler trace of the span (tracing
    # subsystem — new capability, the reference has none; SURVEY.md §5).
    from .utils.trace import profiler_trace

    # MSBFS_CHECKPOINT=<path>: chunk-wise resumable execution (utils.
    # checkpoint — beyond-reference; the reference recomputes everything on
    # failure).  Works with any engine; chunk via MSBFS_CHECKPOINT_CHUNK.
    stats = None
    level_rows = None
    # The dispatch counter scopes to the computation span: warm-up/compile
    # dispatches are the preprocessing span's business (utils.timing).
    reset_dispatch_count()
    try:
        with Span() as comp:
            with profiler_trace():
                if ckpt_path:
                    from .utils.checkpoint import CheckpointedRunner

                    runner = CheckpointedRunner(
                        engine, ckpt_path, chunk=ckpt_chunk, stats=stats_mode
                    )
                    try:
                        f_arr, _ = runner.run(
                            graph.n,
                            graph.num_directed_edges,
                            np.asarray(padded),
                        )
                    except MsbfsError:
                        raise
                    except ValueError as exc:
                        # stale/foreign journal: fail loud
                        print(f"Checkpoint error: {exc}", file=sys.stderr)
                        return 1
                    if (
                        stats_mode
                        and padded.shape[0]
                        and runner.last_stats is not None
                        and (runner.last_stats[0] >= 0).any()
                    ):
                        # -1 rows are F-only entries resumed from a
                        # stats-less journal; the selection below derives
                        # from stats[2].
                        stats = (*runner.last_stats, f_arr)
                    else:
                        if (
                            stats_mode
                            and padded.shape[0]
                            and runner.last_stats is not None
                        ):
                            # Engine supports stats but every row came from
                            # a stats-less (pre-round-4) journal: say THAT,
                            # not "engine doesn't support stats".
                            sys.stderr.write(
                                "MSBFS_STATS: the resumed journal predates "
                                "stats journaling (F-only rows); delete it "
                                "to recompute with stats\n"
                            )
                            stats_mode = False  # suppress the generic note
                        from .ops.objective import select_best_jit
                        import jax.numpy as jnp

                        # One device_get for both scalars: sequential
                        # int() reads each pay their own blocking
                        # round-trip on this platform.
                        arr = jnp.asarray(f_arr)
                        min_f, min_k = jax.device_get(
                            select_best_jit(arr, arr >= 0)
                        )
                        record_dispatch()
                        min_f, min_k = int(min_f), int(min_k)
                elif stats_mode and padded.shape[0]:
                    # One BFS pass serves both the report and the stats
                    # table: stats include the F values, so selection
                    # derives from them.
                    if stats_level:
                        levels, reached, f, lvl_counts, lvl_secs = (
                            engine.level_stats(np.asarray(padded))
                        )
                        stats = (levels, reached, f)
                        level_rows = (lvl_counts, lvl_secs)
                    else:
                        stats = engine.query_stats(np.asarray(padded))
                if stats is not None:
                    from .ops.objective import select_best_jit
                    import jax.numpy as jnp

                    # One device_get for both scalars (see the checkpoint
                    # branch above).
                    f = jnp.asarray(stats[2])
                    min_f, min_k = jax.device_get(
                        select_best_jit(f, f >= 0)
                    )
                    record_dispatch()
                    min_f, min_k = int(min_f), int(min_k)
                elif not ckpt_path:
                    min_f, min_k = engine.best(np.asarray(padded))
    except MsbfsError as err:
        # The supervisor's recovery budget (retries, ladder rungs, mesh
        # rebuilds) ran out: one-line report, documented exit code
        # (docs/RESILIENCE.md), no traceback spray.  The flight recorder
        # dumps first — the ring's tail (audit failures, retries) is the
        # post-mortem context the one-line report cannot carry.
        from .utils.telemetry import dump_flight

        dump_flight(f"exit_{err.exit_code}")
        print(format_failure(err, engine.events), file=sys.stderr)
        return err.exit_code

    if stats_mode:
        # Blocking device commits in the computation span: the dispatch-
        # floor budget the perf smoke pins (benchmarks/perf_smoke.py).
        sys.stderr.write(f"dispatch_count: {dispatch_count()}\n")
    if stats is not None:
        # Per-query diagnostics to stderr (stdout stays reference-exact).
        from .utils.trace import format_level_stats, format_query_stats

        if level_rows is not None:
            sys.stderr.write(format_level_stats(*level_rows))
            halo = getattr(engine, "last_halo_trace", None)
            if halo:
                from .utils.trace import format_halo_stats

                sys.stderr.write(format_halo_stats(halo))
        elif stats_env == "2":
            sys.stderr.write(
                "MSBFS_STATS=2: per-level trace not available "
                + (
                    "under checkpointing"
                    if ckpt_path
                    else "on this engine"
                )
                + "; per-query stats only\n"
            )
        sys.stderr.write(format_query_stats(*stats))
    elif stats_mode:
        if padded.shape[0] == 0:
            sys.stderr.write("MSBFS_STATS: no queries\n")
        else:
            sys.stderr.write(
                "MSBFS_STATS: per-query stats are not available on this "
                "engine; ignored for this run\n"
            )

    # Rank-0-only report, exactly the reference's contract (main.cu:403-414
    # prints on world_rank 0 alone); every process computes — the merged
    # result is replicated — but only process 0 speaks on stdout.
    if jax.process_index() == 0:
        sys.stdout.write(
            format_report(
                graph_path=graph_file,
                query_path=query_file,
                min_k=min_k,
                min_f=min_f,
                num_gpu=num_gpu,
                preprocessing_time=pre.seconds,
                computation_time=comp.seconds,
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
