"""Flash-crowd autoscaling & adaptive overload control (docs/SERVING.md
"Autoscaling & overload"): the autoscaler's hysteresis/cooldown/churn
stabilizers, sleepless token-bucket and priority-class admission units,
CoDel shed-order, the brownout ladder's step-down/dwell/step-up contract
(with its JSONL transition journal), weighted + host-aware placement
(equal weights bit-identical to the unweighted ring), the ``host_down``
fault kind, the ``health`` verb's monotonic queue gauge pin, the
``posture`` verb, and — slow-marked for the tier-1 wall-clock budget —
the elastic chaos chain against a real multi-process fleet: a simulated
flash crowd makes the autoscaler add a replica, ``host_down`` takes a
whole host out mid-stampede, the router fails over across hosts while
the brownout ladder engages and disengages, and the scale-down drains
its victim cleanly — zero acked queries lost, every answer bit-identical
to a single-daemon oracle.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from virtual_cpu import virtual_cpu_env  # noqa: E402

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E402
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (  # noqa: E402
    BackpressureError,
    RetryPolicy,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.autoscale import (  # noqa: E402
    AutoscaleConfig,
    AutoscalePolicy,
    ReplicaSignal,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.batcher import (  # noqa: E402
    MicroBatcher,
    QueryRequest,
    TokenBucket,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.brownout import (  # noqa: E402
    RUNGS,
    BrownoutLadder,
    effects_for,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (  # noqa: E402
    MsbfsClient,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.fleet import (  # noqa: E402
    FleetSupervisor,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.ring import (  # noqa: E402
    PlacementRing,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.router import (  # noqa: E402
    FleetRouter,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.server import (  # noqa: E402
    MsbfsServer,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils import (  # noqa: E402
    faults,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (  # noqa: E402
    save_graph_bin,
)

QS = [[1, 2], [3, 4]]


def answer(out: dict):
    return (out["f_values"], out["min_f"], out["min_k"])


# ---------------------------------------------------------------------------
# Autoscaler units (pure controller: no threads, no clocks)
# ---------------------------------------------------------------------------

HOT = [ReplicaSignal(utilization=0.9, oldest_age_s=0.0)]
COLD = [ReplicaSignal(utilization=0.0, oldest_age_s=0.0)]
WARM = [ReplicaSignal(utilization=0.4, oldest_age_s=0.0)]


def test_autoscale_hysteresis_up_and_cooldown():
    p = AutoscalePolicy(AutoscaleConfig(
        min_replicas=1, max_replicas=4, up_after=2, down_after=3,
        cooldown_ticks=4, churn_budget=8, churn_window=100,
    ))
    # One hot tick is noise: no decision.
    assert p.tick(size=1, replicas=HOT) == 0
    assert p.last_reason == "hot"
    # The second consecutive hot tick commits +1 (max_step).
    assert p.tick(size=1, replicas=HOT) == +1
    assert p.last_reason == "hot" and p.scale_ups == 1
    # Cooldown holds regardless of signals for cooldown_ticks.
    for _ in range(3):
        assert p.tick(size=2, replicas=HOT) == 0
        assert p.last_reason == "cooldown"
    # The hot streak kept accumulating through the cooldown (the gate
    # defers the decision, it does not erase the evidence): the first
    # post-cooldown tick commits the next step.
    assert p.tick(size=2, replicas=HOT) == +1
    # A warm tick resets both counters: hot-cold-hot-warm never fires.
    p2 = AutoscalePolicy(AutoscaleConfig(up_after=2, down_after=2))
    assert p2.tick(1, HOT) == 0
    assert p2.tick(1, WARM) == 0 and p2.hot_ticks == 0
    assert p2.tick(1, HOT) == 0  # counter restarted, not resumed


def test_autoscale_any_hot_signal_suffices_and_down_needs_all_quiet():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=4, up_after=1,
                          down_after=2, cooldown_ticks=1,
                          high_watermark=0.75, low_watermark=0.15,
                          age_high_s=1.0, churn_budget=16)
    # Each hot signal alone: util, shed, stuck head.
    for replicas, shed in (
        ([ReplicaSignal(utilization=0.8)], 0),
        ([ReplicaSignal(utilization=0.0)], 3),
        ([ReplicaSignal(utilization=0.0, oldest_age_s=2.0)], 0),
    ):
        p = AutoscalePolicy(cfg)
        assert p.tick(size=1, replicas=replicas, shed_since_last=shed) == +1
    # An empty fleet is maximally under-provisioned.
    p = AutoscalePolicy(cfg)
    assert p.tick(size=0, replicas=[]) == +1
    # Scale-down needs EVERY signal quiet: a shed tick is HOT (not
    # merely not-cold) and resets the cold streak.
    slow_up = AutoscaleConfig(min_replicas=1, max_replicas=4, up_after=5,
                              down_after=2, cooldown_ticks=1,
                              churn_budget=16)
    p = AutoscalePolicy(slow_up)
    assert p.tick(size=2, replicas=COLD) == 0
    assert p.tick(size=2, replicas=COLD, shed_since_last=1) == 0
    assert p.cold_ticks == 0 and p.hot_ticks == 1
    assert p.tick(size=2, replicas=COLD) == 0
    assert p.tick(size=2, replicas=COLD) == -1
    assert p.scale_downs == 1
    # Never below min_replicas, never above max_replicas.
    p = AutoscalePolicy(cfg)
    for _ in range(10):
        assert p.tick(size=1, replicas=COLD) <= 0
    assert p.scale_downs == 0
    p = AutoscalePolicy(cfg)
    assert p.tick(size=4, replicas=HOT) == 0  # at max: hold, not grow


def test_autoscale_churn_budget_and_cancel():
    p = AutoscalePolicy(AutoscaleConfig(
        min_replicas=1, max_replicas=8, up_after=1, cooldown_ticks=1,
        churn_budget=2, churn_window=1000,
    ))
    size = 1
    assert p.tick(size, HOT) == +1
    size += 1
    assert p.tick(size, HOT) == +1
    size += 1
    # Budget spent: still hot, but the ring must not thrash.
    for _ in range(5):
        assert p.tick(size, HOT) == 0
    assert p.last_reason == "churn-budget"
    # cancel() refunds the last event (the spawn failed): the policy
    # may retry instead of starving.
    p.cancel()
    assert p.tick(size, HOT) == +1
    d = p.describe()
    assert d["config"]["churn_budget"] == 2
    assert d["scale_ups"] == 3 and d["churn_left"] == 0


def test_autoscale_config_validation():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=0).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(low_watermark=0.8, high_watermark=0.5).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(up_after=0).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(churn_budget=0).validate()


# ---------------------------------------------------------------------------
# Admission-control units (sleepless: every clock is injected)
# ---------------------------------------------------------------------------


def test_token_bucket_is_sleepless_and_capped():
    b = TokenBucket(rate=2.0, burst=3.0, now=100.0)
    assert [b.take(100.0) for _ in range(3)] == [True, True, True]
    assert b.take(100.0) is False  # burst spent, no time passed
    assert b.take(100.5) is True   # 0.5s * 2/s = 1 token refilled
    assert b.take(100.5) is False
    b.take(1000.0)                 # long idle refills to burst, not beyond
    assert b.tokens == pytest.approx(3.0 - 1.0)


def _req(priority="interactive", client_id=None):
    return QueryRequest(
        graph_key="g", graph_name="g", version=1,
        rows=np.full((2, 2), 1, dtype=np.int32), s_pad=2,
        submitted=0.0, priority=priority, client_id=client_id,
    )


def test_batcher_priority_gate_reserves_headroom():
    mb = MicroBatcher(execute=lambda *a: None, capacity=10,
                      batch_admit_frac=0.5, client_rate=0.0,
                      codel_target_s=0.0)
    # Never started: pure admission arithmetic against the queue.
    for _ in range(5):
        mb.submit(_req("batch"), now=0.0)
    with pytest.raises(BackpressureError):
        mb.submit(_req("batch"), now=0.0)  # gate at 0.5 * 10
    assert mb.rejected_batch == 1 and mb.rejected == 0
    # The reserved headroom still admits interactive work...
    for _ in range(5):
        mb.submit(_req("interactive"), now=0.0)
    # ...until the hard capacity gate, which is a different counter.
    with pytest.raises(BackpressureError):
        mb.submit(_req("interactive"), now=0.0)
    assert mb.rejected == 1 and mb.depth() == 10


def test_batcher_per_client_token_bucket():
    mb = MicroBatcher(execute=lambda *a: None, capacity=64,
                      client_rate=1.0, client_burst=2.0,
                      codel_target_s=0.0)
    mb.submit(_req(client_id="stampeder"), now=0.0)
    mb.submit(_req(client_id="stampeder"), now=0.0)
    with pytest.raises(BackpressureError):
        mb.submit(_req(client_id="stampeder"), now=0.0)
    assert mb.rejected_client == 1
    # Another client is unaffected (per-client isolation)...
    mb.submit(_req(client_id="bystander"), now=0.0)
    # ...and anonymous requests are exempt (backward compatible).
    mb.submit(_req(client_id=None), now=0.0)
    # The stampeder earns a token back with time.
    mb.submit(_req(client_id="stampeder"), now=1.1)


def test_codel_sheds_oldest_batch_victim_not_the_head():
    mb = MicroBatcher(execute=lambda *a: None, capacity=64,
                      client_rate=0.0, codel_target_s=0.1,
                      codel_interval_s=0.5)
    head = _req("interactive")
    victim = _req("batch")
    tail = _req("batch")
    for r, t in ((head, 0.0), (victim, 0.1), (tail, 0.2)):
        mb.submit(r, now=t)
    # (The controller runs lock-held on the consumer's dequeue path.)
    with mb._lock:
        # Sojourn above target arms the interval; nothing shed yet.
        assert mb._shed_overload_locked(0.3) == []
        assert mb._shed_overload_locked(0.5) == []  # interval not elapsed
        shed = mb._shed_overload_locked(0.9)
    # One victim per interval: the OLDEST batch request, not the
    # (interactive) head — capacity is reclaimed from the class that
    # will retry, and the user-facing request keeps its place.
    assert shed == [victim] and mb.shed_overload == 1
    assert mb.depth() == 2
    # Below target the controller disarms.
    mb2 = MicroBatcher(execute=lambda *a: None, capacity=8,
                       codel_target_s=0.1, codel_interval_s=0.5)
    mb2.submit(_req("interactive"), now=0.0)
    with mb2._lock:
        assert mb2._shed_overload_locked(0.05) == []
    assert mb2._first_above is None
    # Draining suspends shedding: accepted work is finished.
    mb.begin_drain()
    with mb._lock:
        assert mb._shed_overload_locked(99.0) == []


# ---------------------------------------------------------------------------
# Brownout ladder units
# ---------------------------------------------------------------------------


def test_brownout_steps_down_and_up_with_dwell(tmp_path):
    jpath = str(tmp_path / "brownout.jsonl")
    lad = BrownoutLadder(down_after=2, up_after=2, min_dwell=3,
                         journal_path=jpath)
    assert lad.rung == "full" and RUNGS[0] == "full"
    assert lad.tick(True) is None          # 1 saturated tick: hold
    # down_after satisfied at tick 2, but the INITIAL rung serves its
    # dwell too (entered at tick 0, min_dwell 3 -> earliest step tick 3).
    assert lad.tick(True) is None
    assert lad.tick(True) == ("full", "no-vote")
    assert lad.vote_suppressed() and not lad.audit_suppressed()
    # The step reset the streak and re-armed the dwell: two more
    # saturated ticks satisfy down_after but not dwell (entered tick 3).
    assert lad.tick(True) is None
    assert lad.tick(True) is None
    assert lad.tick(True) == ("no-vote", "no-audit")
    assert lad.audit_suppressed() and not lad.cache_only()
    for _ in range(3):
        lad.tick(True)
    assert lad.rung == "cache-only" and lad.cache_only()
    lad.tick(True)  # already at the last rung: stays
    assert lad.level == len(RUNGS) - 1
    # Recovery is symmetric: up_after clear ticks per rung, dwell held.
    steps = []
    for _ in range(30):
        t = lad.tick(False)
        if t:
            steps.append(t)
        if lad.level == 0:
            break
    assert steps == [("cache-only", "no-audit"), ("no-audit", "no-vote"),
                     ("no-vote", "full")]
    assert not lad.vote_suppressed()
    # Every transition journaled (fsync'd JSONL) and in the stats log.
    lines = [json.loads(ln) for ln in
             open(jpath, encoding="utf-8").read().splitlines()]
    assert [ln["to"] for ln in lines] == [
        "no-vote", "no-audit", "cache-only", "no-audit", "no-vote", "full",
    ]
    assert [t["to"] for t in lad.describe()["transitions"]] == [
        ln["to"] for ln in lines
    ]
    assert lad.describe()["steps_down"] == 3
    assert lad.describe()["steps_up"] == 3


def test_brownout_validation_and_effects_table():
    with pytest.raises(ValueError):
        BrownoutLadder(down_after=0)
    with pytest.raises(ValueError):
        BrownoutLadder(up_after=0)
    with pytest.raises(ValueError):
        BrownoutLadder(min_dwell=-1)
    assert effects_for(0) == []
    assert effects_for(1) == ["cross-replica voting suspended"]
    assert len(effects_for(3)) == 3
    # A broken journal path never blocks the control loop.
    lad = BrownoutLadder(down_after=1, min_dwell=0,
                         journal_path="/nonexistent/dir/x.jsonl")
    assert lad.tick(True) == ("full", "no-vote")


# ---------------------------------------------------------------------------
# Weighted + host-aware placement
# ---------------------------------------------------------------------------


def test_ring_equal_weights_bit_identical_to_unweighted():
    members = ["r0", "r1", "r2", "r3"]
    plain = PlacementRing(members, replication=2)
    weighted = PlacementRing(members, replication=2,
                             weights={m: 1.0 for m in members})
    for i in range(100):
        d = f"digest{i:03d}"
        assert weighted.preference(d) == plain.preference(d)
        assert weighted.owners(d) == plain.owners(d)


def test_ring_weight_skews_ownership_proportionally():
    members = ["big", "s0", "s1", "s2"]
    ring = PlacementRing(members, replication=1,
                         weights={"big": 3.0})
    wins = {m: 0 for m in members}
    n = 600
    for i in range(n):
        wins[ring.owners(f"key{i:04d}")[0]] += 1
    # big (weight 3 of total 6) should win ~n/2; each small ~n/6.
    assert 0.4 * n < wins["big"] < 0.6 * n
    for s in ("s0", "s1", "s2"):
        assert 0.08 * n < wins[s] < 0.26 * n
    with pytest.raises(ValueError):
        PlacementRing(["a", "b"], weights={"a": 0.0})
    with pytest.raises(ValueError):
        PlacementRing(["a", "b"], weights={"a": -1.0})
    with pytest.raises(ValueError):
        PlacementRing(["a", "b"], weights={"a": float("inf")})


def test_ring_elastic_membership_minimal_movement():
    ring = PlacementRing(["r0", "r1", "r2"], replication=2)
    digests = [f"key{i:03d}" for i in range(200)]
    before = {d: ring.owners(d) for d in digests}
    ring.add_member("r3")
    moved = 0
    for d in digests:
        after = ring.owners(d)
        if after != before[d]:
            # HRW: the only keys that move are the ones the newcomer
            # wins; every move introduces r3 and evicts at most one.
            assert "r3" in after
            assert len(set(before[d]) - set(after)) <= 1
            moved += 1
    assert 0 < moved < len(digests)
    ring.remove_member("r3")
    for d in digests:
        assert ring.owners(d) == before[d]  # put-back is exact
    with pytest.raises(ValueError):
        ring.add_member("r0")  # duplicate
    with pytest.raises(ValueError):
        ring.remove_member("r9")  # absent
    with pytest.raises(ValueError):
        PlacementRing(["solo"]).remove_member("solo")  # never to zero
    # Replication un-clamps as membership grows past the request.
    r = PlacementRing(["a"], replication=2)
    assert r.replication == 1
    r.add_member("b")
    assert r.replication == 2


def test_ring_host_aware_owner_spread_and_fallback():
    members = ["r0", "r1", "r2", "r3"]
    hosts = {"r0": "hostA", "r1": "hostA", "r2": "hostB", "r3": "hostB"}
    ring = PlacementRing(members, replication=2, hosts=hosts)
    for i in range(60):
        owners = ring.owners(f"key{i:03d}")
        assert {hosts[m] for m in owners} == {"hostA", "hostB"}, (
            "owners must land on distinct hosts while enough hosts exist"
        )
    # One whole host dark: colocation beats under-replication.
    alive = ["r0", "r1"]  # hostB is gone
    for i in range(60):
        owners = ring.owners(f"key{i:03d}", alive=alive)
        assert sorted(owners) == ["r0", "r1"]
    assert ring.host_of("r2") == "hostB"
    assert PlacementRing(["x"]).host_of("x") is None


# ---------------------------------------------------------------------------
# host_down fault kind
# ---------------------------------------------------------------------------


def test_host_down_parse_trip_and_single_shot():
    plan = faults.FaultPlan.parse("host_down:siteB:2")
    (spec,) = plan.specs
    assert spec.kind == "host_down" and spec.host == "siteB"
    assert spec.at == 2 and spec.trip_site == "siteB"
    faults.activate(plan)
    try:
        faults.trip("siteB")  # first heartbeat: arms, does not fire
        with pytest.raises(faults.SimulatedHostDown) as err:
            faults.trip("siteB")
        assert err.value.host == "siteB"
        faults.trip("siteB")  # single-shot: inert afterwards
        faults.trip("siteA")  # other hosts never match
    finally:
        faults.activate(None)
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("host_down::1")  # empty label
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("host_down:bad host:1")  # space in label


# ---------------------------------------------------------------------------
# Health gauge, posture verb, router suppression (in-process, no sockets)
# ---------------------------------------------------------------------------


def test_health_queue_gauge_is_monotonic_and_pinned(tmp_path):
    """The autoscaler's input gauge: ``health.queue`` must report depth,
    capacity and the MONOTONIC age of the queue head — a wall clock
    stepping backward must never read as a drained queue.  Semantics
    referenced by serve/server.py; this test is the pin."""
    srv = MsbfsServer(listen=f"unix:{tmp_path}/h.sock", graphs={})
    h = srv._op_health()
    assert h["queue"] == {
        "depth": 0,
        "capacity": srv.batcher.capacity,
        "oldest_age_s": 0.0,
    }
    assert h["queue_depth"] == 0
    # Inject two queued requests with monotonic stamps 5s apart: the
    # gauge reads the HEAD's age, from time.monotonic, not time.time.
    srv.batcher.submit(_req(), now=time.monotonic() - 5.0)
    srv.batcher.submit(_req(), now=time.monotonic())
    h = srv._op_health()
    assert h["queue"]["depth"] == 2
    assert 4.5 <= h["queue"]["oldest_age_s"] <= 6.0
    # Injectable-now form used by the supervisor's probe: monotonic in
    # the literal sense — a later now never reads smaller.
    t = time.monotonic()
    a1 = srv.batcher.oldest_age(now=t + 1.0)
    a2 = srv.batcher.oldest_age(now=t + 2.0)
    assert a2 > a1 >= 5.0
    # An (impossible) earlier now clamps at 0, never negative.
    fresh = MicroBatcher(execute=lambda *a: None, capacity=4)
    fresh.submit(_req(), now=100.0)
    assert fresh.oldest_age(now=99.0) == 0.0


def test_posture_verb_overrides_and_restores_audit(tmp_path):
    srv = MsbfsServer(listen=f"unix:{tmp_path}/p.sock", graphs={})
    out = srv.handle({"op": "posture", "audit_sample": 0.0,
                      "cache_only": True})
    assert out["ok"] and out["posture"]["audit_sample_override"] == 0.0
    assert out["posture"]["cache_only"] is True
    st = srv.stats()
    assert st["posture"]["audit_sample_override"] == 0.0
    assert st["posture"]["cache_only"] is True
    out = srv.handle({"op": "posture", "audit_sample": "restore",
                      "cache_only": False})
    assert out["posture"]["audit_sample_override"] is None
    assert out["posture"]["cache_only"] is False
    # Garbage is refused typed, not applied.
    bad = srv.handle({"op": "posture", "audit_sample": 7.0})
    assert bad["ok"] is False


def test_router_vote_suppression_and_route_index():
    ring = PlacementRing(["r0"], replication=2)
    router = FleetRouter(ring, {"r0": "unix:/dev/null"}, {},
                         brownout_fn=lambda: True)
    assert router._vote_suppressed() is True
    router.brownout_fn = lambda: False
    assert router._vote_suppressed() is False
    router.brownout_fn = None
    assert router._vote_suppressed() is False

    def boom():
        raise RuntimeError("broken hook")

    router.brownout_fn = boom
    # A broken hook reads as not-suppressed: integrity redundancy only
    # yields to an affirmative signal.
    assert router._vote_suppressed() is False
    assert "votes_suppressed" in router.stats()
    # A member that JOINS after construction gets its chaos-site index
    # from its slot name, so ``route<i>`` fault sites stay stable
    # across elastic membership churn.
    assert router._route_index("r0") == 0   # construction-time member
    assert router._route_index("r7") == 7   # elastic joiner: slot-parsed
    assert router._route_index("oracle") == 2  # non-slot: next free


# ---------------------------------------------------------------------------
# The elastic chaos chain (slow: subprocess fleet + host kill + drain)
# ---------------------------------------------------------------------------


def _await(predicate, deadline_s, what):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow
def test_stampede_chaos_scaleup_hostdown_brownout_scaledown(tmp_path):
    """The acceptance chain for ISSUE 9: a flash crowd (shed signal)
    makes the autoscaler ADD a replica through the ring's minimal-
    movement reshard; ``host_down`` then takes a whole host dark
    mid-stampede and the router walks owners ACROSS hosts; the brownout
    ladder engages under the sustained saturation (posture pushed to
    replicas) and disengages on recovery; finally the autoscaler scales
    back down and the victim drains cleanly.  Throughout: every acked
    answer bit-identical to a single-daemon oracle, zero lost."""
    n, edges = generators.gnm_edges(120, 360, seed=7)
    gpath = str(tmp_path / "g.bin")
    save_graph_bin(gpath, n, edges)

    oracle_srv = MsbfsServer(listen=f"unix:{tmp_path}/oracle.sock",
                             graphs={"default": gpath},
                             window_s=0.0, request_timeout_s=60.0)
    oracle_srv.start()
    qsets = [QS, [[5, 6], [7, 8]], [[9, 10], [11, 12]]]
    with MsbfsClient(f"unix:{tmp_path}/oracle.sock") as c:
        oracle = [answer(c.query(q)) for q in qsets]

    # The flash crowd by its SIGNAL: shed_fn is the fleet-wide shed
    # counter the supervisor normally reads off the router — here the
    # test owns it, so "the crowd arrives" is deterministic.
    crowd = [0]
    heartbeat_s = 0.25
    supervisor = FleetSupervisor(
        size=2,
        base_dir=str(tmp_path / "fleet"),
        replication=2,
        heartbeat_s=heartbeat_s,
        env=virtual_cpu_env(1),
        restart_policy=RetryPolicy(max_retries=6, base_delay=0.2,
                                   max_delay=1.0, seed=0),
        host_pool=["siteA", "siteB"],
        autoscale=AutoscalePolicy(AutoscaleConfig(
            min_replicas=2, max_replicas=3, up_after=2, down_after=4,
            cooldown_ticks=2, high_watermark=0.95, low_watermark=0.5,
            age_high_s=30.0, churn_budget=8, churn_window=10_000,
        )),
        brownout=BrownoutLadder(down_after=2, up_after=3, min_dwell=0),
        shed_fn=lambda: crowd[0],
    )
    try:
        supervisor.start(wait_ready_s=240.0)
        # Round-robin host pool: r0 -> siteA, r1 -> siteB.
        assert [r.host for r in supervisor.replicas] == ["siteA", "siteB"]
        supervisor.register("default", gpath)
        router = FleetRouter.for_fleet(supervisor, timeout=60.0)
        assert router.brownout_fn is not None  # vote rung wired

        def owners_live():
            live = supervisor.status()["graphs"]["default"]["live_owners"]
            return len(live) >= 2

        _await(owners_live, 240.0, "both owners live")
        acked = 0
        for i, q in enumerate(qsets):  # warm the serving path
            assert answer(router.query(q, deadline_s=120.0)) == oracle[i]
            acked += 1

        # ---- phase 1: flash crowd -> scale-up within the reaction SLO.
        t_crowd = time.monotonic()
        crowd[0] += 1  # every tick from here reads shed>0 = hot

        def grown():
            crowd[0] += 1  # the crowd keeps stampeding
            i = acked % len(qsets)
            assert answer(
                router.query(qsets[i], deadline_s=30.0)
            ) == oracle[i]
            return supervisor.status()["size"] >= 3

        _await(grown, 120.0, "autoscaler scale-up to 3")
        reaction_s = time.monotonic() - t_crowd
        # Reaction SLO: decision within up_after+1 heartbeats; the
        # commit includes a real replica boot, so budget generously —
        # the bench pins the tight heartbeat-denominated number.
        assert reaction_s < 60.0, f"scale-up took {reaction_s:.1f}s"
        newcomer = supervisor.replicas[2]
        assert newcomer.name == "r2" and newcomer.host == "siteA"
        assert newcomer.name in supervisor.ring.members
        _await(lambda: newcomer.state == "ready", 120.0, "r2 ready")

        # Brownout engaged under the sustained crowd (posture pushed).
        _await(lambda: supervisor.brownout.level >= 1, 30.0,
               "brownout engages")
        assert router._vote_suppressed() is True
        st = supervisor.status()
        assert st["autoscale"]["scale_ups"] >= 1
        assert st["brownout"]["level"] >= 1

        # ---- phase 2: host_down mid-stampede -> cross-host failover.
        faults.activate(faults.FaultPlan.parse("host_down:siteB:1"))
        victim = supervisor.replicas[1]  # the only siteB resident
        _await(lambda: victim.injected_kills >= 1, 60.0,
               "host_down fires")
        assert supervisor.replicas[0].injected_kills == 0  # siteA spared
        # The graph stays reachable the entire time the host is dark:
        # its owners spread across hosts, so at most one owner died.
        end = time.monotonic() + 20.0
        while time.monotonic() < end and victim.state != "ready":
            i = acked % len(qsets)
            out = router.query(qsets[i], deadline_s=30.0)
            assert answer(out) == oracle[i], "acked query lost/corrupted"
            acked += 1
        _await(lambda: victim.state == "ready" and victim.restarts >= 1,
               240.0, "victim restarts after host_down")

        # ---- phase 3: recovery -> brownout disengages, scale-down
        # drains the newest replica cleanly.
        # crowd[0] stops moving: shed_delta reads 0, queues are empty.
        _await(lambda: supervisor.brownout.level == 0, 60.0,
               "brownout disengages")
        assert router._vote_suppressed() is False

        def shrunk():
            i = acked % len(qsets)
            assert answer(
                router.query(qsets[i], deadline_s=30.0)
            ) == oracle[i]
            return supervisor.status()["size"] == 2

        _await(shrunk, 120.0, "autoscaler scale-down to 2")
        _await(lambda: newcomer.state == "removed", 120.0,
               "victim drained and removed")
        assert newcomer.name not in supervisor.ring.members
        assert newcomer.name not in supervisor.addresses

        # The survivors still serve, bit-identical; nothing was lost.
        for i, q in enumerate(qsets):
            assert answer(router.query(q, deadline_s=30.0)) == oracle[i]
        assert router.stats()["shed"] == 0
        st = supervisor.status()
        assert st["autoscale"]["scale_downs"] >= 1
        assert [t["to"] for t in st["brownout"]["transitions"]][-1] == "full"
    finally:
        faults.activate(None)
        supervisor.stop()
        oracle_srv.stop()


@pytest.mark.slow
def test_scale_down_drains_victim_before_removal(tmp_path):
    """Scale-down safety: ``remove_replica`` takes the victim out of
    the ring FIRST (new queries reshard away), then lets in-flight and
    queued work finish, then stops the process — queries racing the
    removal are all acked bit-identical to the oracle, zero lost."""
    n, edges = generators.gnm_edges(120, 360, seed=7)
    gpath = str(tmp_path / "g.bin")
    save_graph_bin(gpath, n, edges)
    oracle_srv = MsbfsServer(listen=f"unix:{tmp_path}/oracle.sock",
                             graphs={"default": gpath},
                             window_s=0.0, request_timeout_s=60.0)
    oracle_srv.start()
    qsets = [QS, [[5, 6], [7, 8]], [[9, 10], [11, 12]]]
    with MsbfsClient(f"unix:{tmp_path}/oracle.sock") as c:
        oracle = [answer(c.query(q)) for q in qsets]

    supervisor = FleetSupervisor(
        size=2,
        base_dir=str(tmp_path / "fleet"),
        replication=2,
        heartbeat_s=0.25,
        env=virtual_cpu_env(1),
        restart_policy=RetryPolicy(max_retries=6, base_delay=0.2,
                                   max_delay=1.0, seed=0),
    )
    try:
        supervisor.start(wait_ready_s=240.0)
        supervisor.register("default", gpath)
        router = FleetRouter.for_fleet(supervisor, timeout=60.0)

        def owners_live():
            live = supervisor.status()["graphs"]["default"]["live_owners"]
            return len(live) >= 2

        _await(owners_live, 240.0, "both owners live")
        victim = supervisor.replicas[1]
        # Warm BOTH replicas directly so drain-window queries measure
        # serving, not first-compile.
        for r in supervisor.replicas:
            with MsbfsClient(r.address, timeout=300.0) as c:
                for q in qsets:
                    c.query(q)

        # In-flight load pointed AT the victim while it is removed:
        # these were admitted before (or during) the drain and must all
        # be answered — the drain contract — or refused typed BEFORE
        # admission (a TransientError, which the ring walk absorbs).
        results, failures = [], []

        def one_query(i):
            try:
                with MsbfsClient(victim.address, timeout=60.0,
                                 retry=RetryPolicy(max_retries=0)) as c:
                    results.append((i, answer(c.query(qsets[i % 3]))))
            except Exception as exc:  # noqa: BLE001 — audited below
                failures.append((i, exc))

        threads = [threading.Thread(target=one_query, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.15)  # let the queries reach the victim's queue
        supervisor.remove_replica(victim.name, sync=True)
        for t in threads:
            t.join(timeout=120.0)

        # Zero lost acks: every completed query matches the oracle.
        for i, got in results:
            assert got == oracle[i % 3], f"query {i} corrupted"
        # Any failure must be a typed pre-admission refusal, never a
        # dropped in-flight request (socket cut mid-response).
        for i, exc in failures:
            name = type(exc).__name__
            assert name in ("ServerError", "TransientError"), (
                f"query {i}: non-typed loss {exc!r}"
            )
        assert len(results) + len(failures) == 8 and results

        # The victim is fully retired: out of the ring, out of the
        # address book, process gone — and the survivor owns the graph.
        assert victim.state == "removed"
        assert victim.name not in supervisor.ring.members
        assert victim.name not in supervisor.addresses
        assert supervisor.status()["size"] == 1
        for i, q in enumerate(qsets):
            assert answer(router.query(q, deadline_s=60.0)) == oracle[i]
        # The last live replica is load-bearing: removal is refused.
        with pytest.raises(ValueError):
            supervisor.remove_replica(supervisor.replicas[0].name)
    finally:
        supervisor.stop()
        oracle_srv.stop()
