"""Dynamic-graph subsystem (round 11, docs/SERVING.md "Mutations &
versions"): the versioned edge-delta log (canonicalization rules, the
fuzz-parity contract — ``apply()`` bit-identical to a from-scratch CSR
rebuild at every version boundary, the chained content digest),
incremental BFS repair (insert / delete / mixed parity against full
recompute plus the output certificate, disconnect and reconnect cones,
the host-side cost-model fallback), the delta binary format and its
fail-before-allocate loader, the ``gen_cli --deltas`` fixture path, and
the serving integration — ``mutate`` / ``versions`` verbs, result-cache
invalidation, the warm-plane repair hit, journaled mutation replay
after a restart, and the digest-mismatch refusal posture.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.dynamic.delta import (  # noqa: E402
    DeltaLog,
    canonical_edge_keys,
    canonicalize_batch,
    keys_to_pairs,
    load_delta_bin,
    save_delta_bin,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.dynamic.repair import (  # noqa: E402
    repair_distances,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (  # noqa: E402
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (  # noqa: E402
    CSRGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops import (  # noqa: E402
    certify,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (  # noqa: E402
    pad_queries,
    save_graph_bin,
)


def _assert_csr_identical(a: CSRGraph, b: CSRGraph) -> None:
    assert a.n == b.n
    np.testing.assert_array_equal(
        np.asarray(a.row_offsets), np.asarray(b.row_offsets)
    )
    np.testing.assert_array_equal(
        np.asarray(a.col_indices), np.asarray(b.col_indices)
    )


# ---------------------------------------------------------------------------
# Delta-log units
# ---------------------------------------------------------------------------


def test_canonicalization_rules():
    """The set algebra's ground rules: self-loops dropped, duplicates
    and reversed pairs collapsed, insert/delete overlap nets to
    PRESENT (the delete side loses)."""
    keys = canonical_edge_keys(
        np.array([[3, 1], [1, 3], [1, 3], [5, 5], [0, 2]])
    )
    np.testing.assert_array_equal(
        keys_to_pairs(keys), np.array([[0, 2], [1, 3]], dtype=np.int32)
    )
    ins, dels = canonicalize_batch(
        inserts=[[2, 1], [4, 4], [1, 2]], deletes=[[1, 2], [0, 3]], n=8
    )
    np.testing.assert_array_equal(
        keys_to_pairs(ins), np.array([[1, 2]], dtype=np.int32)
    )
    np.testing.assert_array_equal(
        keys_to_pairs(dels), np.array([[0, 3]], dtype=np.int32)
    )
    with pytest.raises(ValueError, match="out of range"):
        canonicalize_batch([[0, 9]], [], n=8)
    with pytest.raises(ValueError, match="out of range"):
        canonicalize_batch([], [[-1, 2]], n=8)


def test_fuzz_apply_matches_scratch_rebuild():
    """The fuzz-parity contract: drive a log with raw (duplicated,
    reversed, self-looped, absent-delete, present-insert) batches and
    check, at EVERY version boundary, that the log's edge set matches
    an independent Python-set model and that ``apply()`` is
    bit-identical to ``CSRGraph.from_edges`` on that model's pairs."""
    rng = np.random.default_rng(7)
    n = 60
    n0, edges = generators.gnm_edges(n, 150, seed=11)
    assert n0 == n
    g0 = CSRGraph.from_edges(n, edges)
    log = DeltaLog.from_graph(g0, "fuzzbase")

    model = set(int(k) for k in canonical_edge_keys(edges))
    for _ in range(6):
        raw_ins = rng.integers(0, n, size=(rng.integers(0, 12), 2))
        raw_del = rng.integers(0, n, size=(rng.integers(0, 12), 2))
        if model and rng.random() < 0.8:
            # Target some LIVE edges so deletes actually bite.
            live = np.array(sorted(model), dtype=np.int64)
            pick = live[rng.integers(0, live.size, size=3)]
            raw_del = np.concatenate([raw_del, keys_to_pairs(pick)])
        log.append(raw_ins, raw_del)
        ins_k, del_k = canonicalize_batch(raw_ins, raw_del, n)
        model -= set(int(k) for k in del_k)
        model |= set(int(k) for k in ins_k)

        want_keys = np.array(sorted(model), dtype=np.int64)
        np.testing.assert_array_equal(log.keys_at(), want_keys)
        got, (base_digest, v) = log.apply()
        assert (base_digest, v) == ("fuzzbase", log.version)
        _assert_csr_identical(
            got, CSRGraph.from_edges(n, keys_to_pairs(want_keys))
        )
    # Historic versions stay addressable after later appends.
    for v in range(log.version + 1):
        got, (_, gv) = log.apply(v)
        assert gv == v
        _assert_csr_identical(
            got, CSRGraph.from_edges(n, keys_to_pairs(log.keys_at(v)))
        )


def test_digest_chain_names_content():
    """Two logs fed the same batches agree on every digest; a diverging
    batch splits the chain at exactly the first bad version; the raw
    pair ORDER does not matter (canonicalization runs first)."""
    n, edges = generators.gnm_edges(40, 80, seed=3)
    g = CSRGraph.from_edges(n, edges)
    a = DeltaLog.from_graph(g, "basehash")
    b = DeltaLog.from_graph(g, "basehash")
    assert a.digest(0) == "basehash"
    a.append([[1, 2], [3, 4]], [[5, 6]])
    b.append([[3, 4], [2, 1]], [[6, 5], [5, 6]])  # same canonical batch
    assert a.digest(1) == b.digest(1)
    a.append([[7, 8]], [])
    b.append([[7, 9]], [])  # diverges HERE
    assert a.digest(1) == b.digest(1)
    assert a.digest(2) != b.digest(2)
    with pytest.raises(ValueError, match="outside"):
        a.digest(3)


def test_net_delta_composes_and_cancels():
    """Churn that nets out across a version span vanishes from the net
    delta, and applying the net delta to the older edge set reproduces
    the newer one exactly."""
    n, edges = generators.gnm_edges(30, 60, seed=5)
    g = CSRGraph.from_edges(n, edges)
    log = DeltaLog.from_graph(g, "nd")
    live = keys_to_pairs(log.keys_at(0))
    victim = live[0]
    log.append([[0, 17]], [victim])  # v1: +A -B
    log.append([victim], [[0, 17]])  # v2: -A +B  (round trip)
    ins, dels = log.net_delta(0, 2)
    assert ins.shape == (0, 2) and dels.shape == (0, 2)
    log.append([[1, 19], [2, 21]], [])
    ins, dels = log.net_delta(1)
    old = log.keys_at(1)
    rebuilt = np.union1d(
        np.setdiff1d(old, canonical_edge_keys(dels), assume_unique=True),
        canonical_edge_keys(ins),
    )
    np.testing.assert_array_equal(rebuilt, log.keys_at(3))


def test_delta_bin_roundtrip_and_corruption(tmp_path):
    """The binary delta format round-trips (canonicalized on write) and
    the loader fails BEFORE allocating on truncation, bad magic, and
    counts that exceed the bytes actually present."""
    path = str(tmp_path / "d.bin")
    batches = [
        (np.array([[2, 1], [1, 2], [3, 3]]), np.array([[4, 5]])),
        (np.zeros((0, 2), dtype=np.int32), np.array([[0, 7]])),
    ]
    save_delta_bin(path, 10, batches)
    n, got = load_delta_bin(path)
    assert n == 10 and len(got) == 2
    np.testing.assert_array_equal(
        got[0][0], np.array([[1, 2]], dtype=np.int32)
    )
    np.testing.assert_array_equal(
        got[0][1], np.array([[4, 5]], dtype=np.int32)
    )
    assert got[1][0].shape == (0, 2)
    np.testing.assert_array_equal(
        got[1][1], np.array([[0, 7]], dtype=np.int32)
    )

    raw = open(path, "rb").read()
    trunc = str(tmp_path / "trunc.bin")
    with open(trunc, "wb") as f:
        f.write(raw[:7])
    with pytest.raises(IOError, match="truncated delta header"):
        load_delta_bin(trunc)

    badmagic = str(tmp_path / "magic.bin")
    with open(badmagic, "wb") as f:
        f.write(b"XXXX" + raw[4:])
    with pytest.raises(IOError, match="bad delta magic"):
        load_delta_bin(badmagic)

    # Flip the first batch's insert count sky-high: the loader must
    # refuse from the file size, never attempt the allocation.
    import struct

    bloat = bytearray(raw)
    bloat[16:24] = struct.pack("<q", 1 << 40)
    bloated = str(tmp_path / "bloat.bin")
    with open(bloated, "wb") as f:
        f.write(bytes(bloat))
    with pytest.raises(IOError, match="corrupt delta batch"):
        load_delta_bin(bloated)


# ---------------------------------------------------------------------------
# Incremental repair
# ---------------------------------------------------------------------------


def _repair_case(g0, rows, ins, dels, **kwargs):
    """Run one repair against its from-scratch reference and return
    (dist, stats) after asserting the two ground contracts: bit
    identity and a clean certificate on the post-delta graph."""
    log = DeltaLog.from_graph(g0, "rc")
    log.append(ins, dels)
    g1, _ = log.apply()
    net_ins, net_dels = log.net_delta(0)
    old = certify.reference_distances(
        g0.row_offsets, g0.col_indices, rows
    )
    dist, stats = repair_distances(
        g1, rows, old, net_ins, net_dels, **kwargs
    )
    full = certify.reference_distances(
        g1.row_offsets, g1.col_indices, rows
    )
    np.testing.assert_array_equal(dist, full)
    assert (
        certify.certify_distances(
            g1.row_offsets, g1.col_indices, rows, dist
        )
        == []
    )
    return dist, stats


def test_repair_insert_only_shrinks_distances():
    """A pure-insert delta can only DECREASE distances; the repaired
    plane must reflect the shortcut exactly."""
    n, edges = generators.road_edges(12, 12, seed=21)
    g0 = CSRGraph.from_edges(n, edges)
    rows = pad_queries([np.array([0], dtype=np.int32)], pad_to=2)
    # A shortcut from the source corner to the far corner.
    dist, stats = _repair_case(g0, rows, ins=[[0, n - 1]], dels=[])
    assert int(dist[0, n - 1]) == 1
    assert not stats.fallback
    assert stats.repaired_plane_bytes < stats.full_plane_bytes


def test_repair_delete_disconnects_component():
    """Deleting a bridge strands the far side: repaired distances must
    go to the canonical unreached -1, same as a cold recompute."""
    # Two 4-cliques joined by one bridge edge (3, 4).
    edges = np.array(
        [[u, v] for u in range(4) for v in range(u + 1, 4)]
        + [[u, v] for u in range(4, 8) for v in range(u + 1, 8)]
        + [[3, 4]]
    )
    g0 = CSRGraph.from_edges(8, edges)
    rows = pad_queries([np.array([0], dtype=np.int32)], pad_to=1)
    dist, stats = _repair_case(g0, rows, ins=[], dels=[[3, 4]])
    assert (dist[0, 4:] == -1).all()
    assert (dist[0, :4] >= 0).all()
    assert stats.invalidated >= 4


def test_repair_mixed_delete_and_reconnect():
    """A delete that severs the graph plus an insert that reconnects it
    elsewhere in the SAME batch: the cone covers both the invalidated
    descendants and the new shortcut."""
    n, edges = generators.grid_edges(10, 4)
    g0 = CSRGraph.from_edges(n, edges)
    rows = pad_queries(
        [np.array([0, 1], dtype=np.int32), np.array([5], dtype=np.int32)],
        pad_to=2,
    )
    # Cut a middle rung, reconnect through a long chord.
    dist, stats = _repair_case(
        g0, rows, ins=[[2, n - 1]], dels=[[20, 24]]
    )
    assert stats.cone_size > 0
    assert (dist >= -1).all()


def test_repair_cost_model_falls_back_identically():
    """With the threshold forced tiny the cost model must refuse the
    sweep — and the answer contract is identical anyway."""
    n, edges = generators.road_edges(16, 16, seed=22)
    g0 = CSRGraph.from_edges(n, edges)
    rows = pad_queries([np.array([3], dtype=np.int32)], pad_to=1)
    dist, stats = _repair_case(
        g0, rows, ins=[[0, n - 1]], dels=[], max_frac=1e-9
    )
    assert stats.fallback is True
    assert stats.repaired_plane_bytes == stats.full_plane_bytes


def test_repair_max_frac_env_knob(monkeypatch, capsys):
    """MSBFS_REPAIR_MAX_FRAC drives the default threshold; malformed
    values fall back to the built-in default with a stderr note (the
    repo-wide knob convention)."""
    n, edges = generators.road_edges(10, 10, seed=23)
    g0 = CSRGraph.from_edges(n, edges)
    rows = pad_queries([np.array([0], dtype=np.int32)], pad_to=1)
    monkeypatch.setenv("MSBFS_REPAIR_MAX_FRAC", "0.000000001")
    _, stats = _repair_case(g0, rows, ins=[[0, n - 1]], dels=[])
    assert stats.fallback is True
    monkeypatch.setenv("MSBFS_REPAIR_MAX_FRAC", "banana")
    _, stats = _repair_case(g0, rows, ins=[[0, n - 1]], dels=[])
    assert stats.fallback is False  # default 0.5 admits this tiny cone
    assert "MSBFS_REPAIR_MAX_FRAC" in capsys.readouterr().err


@pytest.mark.slow
def test_repair_fuzz_parity():
    """Randomized repair parity: random graphs, random multi-version
    delta spans (net_delta composition), random query batches — every
    repaired plane bit-identical to cold recompute and certified."""
    rng = np.random.default_rng(31)
    for trial in range(8):
        n, edges = generators.gnm_edges(
            96, 220 + 10 * trial, seed=100 + trial
        )
        g0 = CSRGraph.from_edges(n, edges)
        log = DeltaLog.from_graph(g0, f"fz{trial}")
        for b in generators.delta_batches(
            n,
            edges,
            batches=int(rng.integers(1, 4)),
            batch_size=int(rng.integers(4, 20)),
            locality=float(rng.uniform(0.0, 1.0)),
            seed=200 + trial,
        ):
            log.append(*b)
        g1, _ = log.apply()
        rows = pad_queries(
            generators.random_queries(
                n, int(rng.integers(1, 5)), max_group=4, seed=300 + trial
            ),
            pad_to=4,
        )
        old = certify.reference_distances(
            g0.row_offsets, g0.col_indices, rows
        )
        net_ins, net_dels = log.net_delta(0)
        dist, _ = repair_distances(g1, rows, old, net_ins, net_dels)
        full = certify.reference_distances(
            g1.row_offsets, g1.col_indices, rows
        )
        np.testing.assert_array_equal(dist, full)
        assert (
            certify.certify_distances(
                g1.row_offsets, g1.col_indices, rows, dist
            )
            == []
        )


# ---------------------------------------------------------------------------
# Generator + gen_cli fixtures
# ---------------------------------------------------------------------------


def test_delta_batches_deterministic_and_local():
    n, edges = generators.road_edges(24, 24, seed=41)
    kw = dict(batches=3, batch_size=16, locality=0.95, seed=9)
    a = generators.delta_batches(n, edges, **kw)
    b = generators.delta_batches(n, edges, **kw)
    assert len(a) == 3
    for (ia, da), (ib, db) in zip(a, b):
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(da, db)
    live = canonical_edge_keys(edges)
    span = max(8, int(round(n * 0.05)))
    seen_deleted = set()
    for ins, dels in a:
        ends = np.concatenate([ins.reshape(-1), dels.reshape(-1)])
        # Every endpoint inside one contiguous window of the span size.
        assert int(ends.max()) - int(ends.min()) < span
        del_keys = canonical_edge_keys(dels)
        assert np.isin(del_keys, live).all()  # drawn from the live set
        for k in del_keys:  # batches compose: no re-deletes
            assert int(k) not in seen_deleted
            seen_deleted.add(int(k))


def test_gen_cli_deltas_roundtrip(tmp_path):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        gen_cli,
        load_graph_bin,
    )

    g_path = str(tmp_path / "g.bin")
    d_path = str(tmp_path / "g.delta")
    rc = gen_cli.main(
        [
            "--kind", "gnm", "--scale", "6", "--edge-factor", "3",
            "--graph", g_path, "--deltas", d_path,
            "--delta-batches", "2", "--delta-size", "8",
            "--delta-locality", "0.9", "--seed", "13",
        ]
    )
    assert rc == 0
    g = load_graph_bin(g_path)
    n, batches = load_delta_bin(d_path)
    assert n == g.n and len(batches) == 2
    # The file's batches apply cleanly against the emitted graph.
    log = DeltaLog.from_graph(g, "cli")
    for ins, dels in batches:
        log.append(ins, dels)
    g1, (_, v) = log.apply()
    assert v == 2 and g1.n == g.n
    # Bad delta flags fail fast, before any generation.
    assert (
        gen_cli.main(
            ["--kind", "gnm", "--scale", "6", "--graph", g_path,
             "--deltas", d_path, "--delta-locality", "2.0"]
        )
        == 2
    )


# ---------------------------------------------------------------------------
# Serving integration (in-process servers on unix sockets)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def graph_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("dynamic_graphs")
    # High-diameter road grid: single-edge deltas visibly move F, so a
    # stale cache can't pass by coincidence (a low-diameter gnm graph
    # absorbs single-edge deltas without changing any distance sum).
    n, edges = generators.road_edges(12, 12, seed=51)
    path = str(d / "g.bin")
    save_graph_bin(path, n, edges)
    return n, edges, path


def _start_server(tmp_path, graph_path, **kwargs):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.server import (
        MsbfsServer,
    )

    sock = str(tmp_path / f"s{len(os.listdir(tmp_path))}.sock")
    srv = MsbfsServer(
        listen=f"unix:{sock}",
        graphs={"default": graph_path} if graph_path else {},
        window_s=0.0,
        request_timeout_s=60.0,
        **kwargs,
    )
    srv.start()
    return srv, f"unix:{sock}"


def _expected_f(graph_path, applied_batches, queries):
    """Client-side oracle for the post-delta answer: rebuild the same
    canonical patched CSR the server holds and fold the host reference
    planes to F."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_graph_bin,
    )

    g0 = load_graph_bin(graph_path)
    log = DeltaLog.from_graph(g0, "oracle")
    for ins, dels in applied_batches:
        log.append(ins, dels)
    g1, _ = log.apply()
    rows = pad_queries(
        [np.asarray(q, dtype=np.int32) for q in queries], pad_to=2
    )
    dist = certify.reference_distances(
        g1.row_offsets, g1.col_indices, rows
    )
    return [int(x) for x in certify.f_from_distances(dist)]


def test_serve_mutate_versions_and_repair(graph_file, tmp_path, monkeypatch):
    """The live-mutation loop: mutate bumps the version chain and
    invalidates cached results; the next engine query retains a warm
    plane; a second mutate then lets the SAME bucket answer through the
    incremental repair path (repaired: true + dynamic accounting), with
    F matching the client-side post-delta oracle either way."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
        MsbfsClient,
        ServerError,
    )

    _, _, path = graph_file
    monkeypatch.setenv("MSBFS_RETRIES", "0")
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    srv, addr = _start_server(tmp_path, path)
    try:
        with MsbfsClient(addr) as c:
            queries = [[1, 2], [3, 4]]
            r0 = c.query(queries)
            assert c.query(queries)["cached"] is True

            b1 = ([[40, 143]], [[0, 1]])
            m1 = c.mutate(inserts=b1[0], deletes=b1[1])
            assert m1["graph"]["delta_version"] == 1
            assert m1["invalidated_results"] >= 1

            v = c.versions()
            assert v["delta_version"] == 1
            assert len(v["chain"]) == 2
            assert v["chain"][-1]["digest"] == v["digest"]
            assert v["chain"][0]["digest"] != v["digest"]

            # Post-mutate answer: NOT the stale cache, matches oracle.
            r1 = c.query(queries)
            assert r1["cached"] is False
            assert r1["f_values"] == _expected_f(path, [b1], queries)
            assert r1["f_values"] != r0["f_values"]

            b2 = ([[5, 130]], [])
            c.mutate(inserts=b2[0], deletes=b2[1])
            r2 = c.query(queries)
            assert r2["f_values"] == _expected_f(path, [b1, b2], queries)
            assert r2.get("repaired") is True
            dyn = r2["dynamic"]
            assert dyn["fallback"] is False
            assert 0 < dyn["repaired_plane_bytes"] < dyn["full_plane_bytes"]

            stats = c.stats()["dynamic"]
            assert stats["mutations"] == 2
            assert stats["requests_repaired"] == 1
            assert stats["planes_retained"] >= 1
            assert stats["repair_audit_failures"] == 0

            # Input validation: ragged pairs and out-of-range endpoints
            # are typed InputErrors, not daemon damage.
            with pytest.raises(ServerError, match="InputError"):
                c.call({"op": "mutate", "graph": "default",
                        "inserts": [[1]], "deletes": []})
            with pytest.raises(ServerError, match="out of range"):
                c.mutate(inserts=[[0, 10 ** 6]])
            assert c.ping()
    finally:
        srv.stop()


def test_serve_journal_replays_mutation_chain(
    graph_file, tmp_path, monkeypatch
):
    """Acceptance: mutate, die, restart on the journal alone — the
    version chain re-derives digest-identical and a re-query returns
    the correct post-delta answer.  Then tamper with one journaled
    digest: the restarted server REFUSES the whole registration (the
    chain no longer names the data it served)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.serve.client import (
        MsbfsClient,
        ServerError,
    )

    _, _, path = graph_file
    monkeypatch.setenv("MSBFS_RETRIES", "0")
    monkeypatch.delenv("MSBFS_FAULTS", raising=False)
    journal = str(tmp_path / "state.journal")
    queries = [[7, 8], [9, 10]]
    batches = [([[0, 141], [2, 50]], [[7, 8]]), ([[3, 60]], [])]

    srv_a, addr_a = _start_server(tmp_path, path, journal_path=journal)
    try:
        with MsbfsClient(addr_a) as c:
            for ins, dels in batches:
                c.mutate(inserts=ins, deletes=dels)
            chain_a = c.versions()["chain"]
            f_a = c.query(queries)["f_values"]
            assert f_a == _expected_f(path, batches, queries)
    finally:
        srv_a.stop()  # journal-wise, stop IS a crash (never compacts)

    srv_b, addr_b = _start_server(tmp_path, None, journal_path=journal)
    try:
        assert srv_b._ready.wait(120), "journal replay never finished"
        with MsbfsClient(addr_b) as c:
            v = c.versions()
            assert v["delta_version"] == 2
            assert v["chain"] == chain_a  # digest-identical re-derive
            assert c.query(queries)["f_values"] == f_a
    finally:
        srv_b.stop()

    # Tamper: corrupt the journaled digest of the second mutate record.
    lines = open(journal, encoding="utf-8").read().splitlines()
    tampered = []
    for line in lines:
        rec = json.loads(line)
        if rec.get("op") == "mutate" and rec["inserts"] == [[3, 60]]:
            rec["digest"] = "beefbeefbeef"
        tampered.append(json.dumps(rec))
    with open(journal, "w", encoding="utf-8") as f:
        f.write("\n".join(tampered) + "\n")

    srv_c, addr_c = _start_server(tmp_path, None, journal_path=journal)
    try:
        assert srv_c._replayed.wait(120)
        with MsbfsClient(addr_c) as c:
            with pytest.raises(ServerError):
                c.versions()
            with pytest.raises(ServerError):
                c.query(queries)
            assert c.health()["graphs"] == []  # registration refused
    finally:
        srv_c.stop()
