"""Where does a bitbell level go?  Times the forest OR-gather vs the
per-query count unpack on a real RMAT graph (run on the TPU host)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
    CSRGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    bell_hits_or,
    unpack_counts,
)

scale = int(os.environ.get("S", "20"))
K = int(os.environ.get("K", "64"))
W = K // 32

n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
g = CSRGraph.from_edges(n, edges)
bg = BellGraph.from_host(g)
print(f"n={n} E={g.num_directed_edges} {bg}", flush=True)

rng = np.random.default_rng(0)
frontier = jnp.asarray(
    rng.integers(0, 2**32, size=(n, W), dtype=np.uint32)
    & rng.integers(0, 2**32, size=(n, W), dtype=np.uint32)
    & rng.integers(0, 2**32, size=(n, W), dtype=np.uint32)
)


def bench(name, fn, *args):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        ts.append(time.perf_counter() - t0)
    t = min(ts)
    e = g.num_directed_edges
    print(f"{name:28s} {t*1e3:9.2f} ms ({e/t/1e9:6.2f} Gslot/s)", flush=True)
    return t


bench("hits_or (forest gather)", lambda fr: bell_hits_or(fr, bg), frontier)
bench("unpack_counts", unpack_counts, frontier)
bench("new&~vis + counts + or", lambda fr: (
    unpack_counts(fr & ~(fr >> 1)), fr | (fr >> 1)
), frontier)
bench(
    "full level (hits+counts)",
    lambda fr: unpack_counts(bell_hits_or(fr, bg) & ~fr),
    frontier,
)


# --- Pallas VMEM-gather probe: existing ELL kernel, single uint8 frontier.
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.ell import (
    EllGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.pallas_bfs import (
    ell_hits,
)

eg = EllGraph.from_host(g, width=16)
print(repr(eg), flush=True)
pad_to = max(128, -(-(n + 1) // 128) * 128)
fr1 = jnp.zeros((pad_to,), dtype=jnp.int8).at[: n].set(
    jnp.asarray((rng.random(n) < 0.1).astype(np.int8))
)
bench(
    "pallas ell_hits (1 query)",
    lambda fr: ell_hits(fr, eg.cols, eg.num_vrows, eg.width),
    fr1,
)
