"""Distributed layer on the 8-device virtual CPU mesh: cyclic assignment,
pmax merge, parity with single-device results (SURVEY.md C8-C10)."""

import jax
import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    Engine,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel import (
    DistributedEngine,
    cyclic_assignment,
    make_mesh,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.scheduler import (
    cyclic_grid,
)

from oracle import oracle_best, oracle_bfs, oracle_f

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, edges, q)) for q in queries]


def test_cyclic_assignment_matches_reference_loop():
    # Reference: for(kidx = world_rank; kidx < K; kidx += world_size)
    # (main.cu:303-307).
    assert cyclic_assignment(10, 4) == [[0, 4, 8], [1, 5, 9], [2, 6], [3, 7]]
    assert cyclic_assignment(3, 8)[5] == []


def test_cyclic_grid_layout():
    queries = np.arange(10, dtype=np.int32).reshape(10, 1)
    grid, gids, k_pad = cyclic_grid(queries, 4)
    assert grid.shape == (4, 3, 1) and k_pad == 12
    # Slot [r, j] holds global query r + j*W.
    for r in range(4):
        for j in range(3):
            gid = r + j * 4
            assert gids[r, j] == gid
            expected = gid if gid < 10 else -1
            assert grid[r, j, 0] == expected


@pytest.fixture(scope="module")
def problem():
    n, edges = generators.gnm_edges(150, 500, seed=41)
    queries = generators.random_queries(n, 13, max_group=5, seed=42)
    return n, edges, queries, pad_queries(queries)


@pytest.mark.parametrize("w", [1, 2, 8])
def test_distributed_matches_single_device(problem, w):
    n, edges, queries, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=w, devices=jax.devices()[:w])
    deng = DistributedEngine(mesh, graph)
    got = np.asarray(deng.f_values(padded))
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(got, want)
    assert deng.best(padded) == oracle_best(want)


def test_fewer_queries_than_shards(problem):
    n, edges, queries, _ = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=8)
    padded = pad_queries(queries[:3])
    deng = DistributedEngine(mesh, graph)
    got = np.asarray(deng.f_values(padded))
    want = oracle_f_values(n, edges, queries[:3])
    np.testing.assert_array_equal(got, want)


def test_query_chunked_distributed(problem):
    n, edges, queries, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=4, devices=jax.devices()[:4])
    deng = DistributedEngine(mesh, graph, query_chunk=2, backend="csr")
    got = np.asarray(deng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_distributed_csr_backend_matches(problem):
    n, edges, queries, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=2, devices=jax.devices()[:2])
    a = np.asarray(DistributedEngine(mesh, graph, backend="csr").f_values(padded))
    b = np.asarray(DistributedEngine(mesh, graph).f_values(padded))
    np.testing.assert_array_equal(a, b)


def test_distributed_query_stats_match_single_chip(problem):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )

    n, edges, _, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=4, devices=jax.devices()[:4])
    a = DistributedEngine(mesh, graph).query_stats(padded)
    b = BitBellEngine(BellGraph.from_host(graph)).query_stats(padded)
    assert a is not None
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_distributed_bitbell_rejects_csr_knobs(problem):
    n, edges, _, _ = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError):
        DistributedEngine(mesh, graph, query_chunk=2)


def test_two_axis_mesh_query_sharding(problem):
    # ('q','v') mesh with v=2: graph replicated, queries over q=4.
    n, edges, queries, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=4, num_vertex_shards=2)
    deng = DistributedEngine(mesh, graph)
    got = np.asarray(deng.f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))
