"""Persistent query-serving runtime (docs/SERVING.md).

The batch CLI pays full process startup, graph load and XLA compilation
on every invocation; this subpackage turns the engines, scheduler and
supervisor into an always-on daemon that amortizes all three:

* :mod:`.registry` — load-once, device-resident graphs keyed by
  name + content hash, versioned so caches invalidate on reload;
* :mod:`.protocol` — length-prefixed JSON frames over a unix or TCP
  socket (the wire contract, shared by server and client);
* :mod:`.batcher` — dynamic micro-batching into power-of-two shape
  buckets, so concurrent requests coalesce into one dispatch and every
  bucket hits the compiled-executable cache instead of recompiling;
* :mod:`.caches` — the LRU result cache and the executable/compile
  bookkeeping behind the ``stats`` verb;
* :mod:`.server` — the daemon (``msbfs-tpu serve`` / ``python main.py
  serve``): admission control with typed backpressure, every dispatch
  wrapped in the PR-1 :class:`~..runtime.supervisor.ChunkSupervisor`
  so faults degrade per-request instead of killing the process;
* :mod:`.client` — the importable Python client and the thin CLI
  (``msbfs-tpu query --connect ...``);
* :mod:`.smoke` — the ``make serve`` end-to-end smoke;
* :mod:`.ring` — rendezvous-hash placement: graph content digest ->
  replication-factor owner set, minimal movement on replica loss;
* :mod:`.fleet` — the fleet supervisor (``msbfs-tpu fleet``): N replica
  daemons, health heartbeats, backoff restarts, ring reconciliation;
* :mod:`.router` — the front-end failover/hedge/shed router and the
  fleet's client-facing socket.
"""

from __future__ import annotations

__all__ = [
    "FleetFrontend",
    "FleetRouter",
    "FleetSupervisor",
    "MsbfsClient",
    "MsbfsServer",
    "PlacementRing",
    "ServerError",
]


def __getattr__(name):
    # Lazy re-exports: importing the package must stay cheap (the CLI
    # imports it only to dispatch subcommands; jax loads on first use).
    if name == "MsbfsServer":
        from .server import MsbfsServer

        return MsbfsServer
    if name in ("MsbfsClient", "ServerError"):
        from . import client

        return getattr(client, name)
    if name == "FleetSupervisor":
        from .fleet import FleetSupervisor

        return FleetSupervisor
    if name in ("FleetFrontend", "FleetRouter"):
        from . import router

        return getattr(router, name)
    if name == "PlacementRing":
        from .ring import PlacementRing

        return PlacementRing
    raise AttributeError(name)
