"""End-to-end CLI test: exact report format diffing (reference main.cu:403-414),
per SURVEY.md section 4(e)."""

import re

import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import (
    main,
    parse_args,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    save_graph_bin,
    save_query_bin,
)

from oracle import oracle_best, oracle_bfs, oracle_f

REPORT_RE = re.compile(
    r"^Graph: (?P<g>.+)\n"
    r"Query: (?P<q>.+)\n"
    r"Query number \(k\) with minimum F value: (?P<mink>-?\d+)\n"
    r"Minimum F value: (?P<minf>-?\d+)\n"
    r"GPU # : (?P<gn>\d+) GPU\n"
    r"Preprocessing time: (?P<pre>\d+\.\d{9}) s\n"
    r"Computation time: (?P<comp>\d+\.\d{9}) s\n$"
)


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    n, edges = generators.gnm_edges(90, 300, seed=51)
    queries = generators.random_queries(n, 9, max_group=4, seed=52)
    gpath, qpath = str(d / "g.bin"), str(d / "q.bin")
    save_graph_bin(gpath, n, edges)
    save_query_bin(qpath, queries)
    want = oracle_best([oracle_f(oracle_bfs(n, edges, q)) for q in queries])
    return gpath, qpath, want


def run_cli(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


def test_report_format_and_values(files, capsys):
    gpath, qpath, (min_f, min_k) = files
    rc, out, _ = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "1"], capsys)
    assert rc == 0
    m = REPORT_RE.match(out)
    assert m, f"report format mismatch:\n{out!r}"
    assert m["g"] == gpath and m["q"] == qpath
    assert int(m["mink"]) == min_k + 1  # 1-based (main.cu:409)
    assert int(m["minf"]) == min_f
    assert int(m["gn"]) == 1


def test_multichip_gn(files, capsys):
    gpath, qpath, (min_f, min_k) = files
    rc, out, _ = run_cli(["main.py", "-g", gpath, "-q", qpath, "-gn", "8"], capsys)
    assert rc == 0
    m = REPORT_RE.match(out)
    assert m and int(m["mink"]) == min_k + 1 and int(m["minf"]) == min_f
    assert int(m["gn"]) == 8  # reported as given (main.cu:411)


def test_usage_on_missing_args(capsys):
    rc, out, err = run_cli(["main.py", "-g", "x"], capsys)
    assert rc == -1 and out == "" and "Usage:" in err


def test_missing_graph_file(files, capsys):
    _, qpath, _ = files
    rc, _, err = run_cli(
        ["main.py", "-g", "/nonexistent.bin", "-q", qpath, "-gn", "1"], capsys
    )
    assert rc == 1 and "Could not open graph file" in err


def test_parse_args_reference_semantics():
    # Unknown flags silently ignored; -gn default 1 (main.cu:214-224).
    g, q, gn = parse_args(["prog", "-x", "1", "-g", "a", "-q", "b", "--foo"])
    assert (g, q, gn) == ("a", "b", 1)
    assert parse_args(["prog", "-g", "a", "-q", "b", "-gn", "3"])[2] == 3
    assert parse_args(["prog", "-g", "a", "-q", "b", "-gn", "zzz"])[2] == 0
