"""Experiment: per-bucket frontier dirty-flags for the dense mid-levels.

Hypothesis (round-2 verdict item 7): a BELL bucket's gather can be skipped
when every owner in the bucket is already visited by ALL K queries — the
owner's new bits are masked to zero by ``& ~visited`` regardless, so
zeroing its hits early is semantics-preserving (including hub chunk rows:
deeper forest levels only feed that same owner's final hit).

Before building the cond-per-bucket machinery, this script measures the
HEADROOM: per BFS level, how many padded slots belong to buckets whose
owners are all fully visited (the slots a dirty-flag would skip), on the
bitbell engine's own stepped trace.

RESULT (2026-07-30, r3): **negative — the lever cannot fire.**  On
RMAT-16/K=64 (and RMAT-14 in debugging), the fraction of vertices visited
by ALL 64 query groups is 0.0000 at EVERY level including convergence,
so no bucket is ever skippable (skippable_frac 0.0000 across the board;
whole-BFS headroom 0.0 dense-level-equivalents).  Root cause is
structural, not statistical: a single query group whose sources land
outside the giant component (near-certain as K grows — random groups of
1-64 sources regularly fall into small components) never visits the
giant component's vertices, so the all-K intersection that would clean a
bucket stays empty forever.  Per-word flags (32-query granularity) fail
the same way — one stray group per word suffices — and per-owner
granularity is no longer a *bucket* skip (that is exactly what the
hybrid's frontier-sparse push already exploits at edge granularity).
The dense-mid-level cost therefore cannot be cut by visited-set dirty
flags; the remaining levers are layout-side (fill, widths ladder), not
frontier-side.  Kept runnable for re-checking on other graph families.

Run: python benchmarks/exp_bucket_dirty.py [scale] [K]
(re-execs onto the virtual CPU platform when needed)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(scale: int, k: int) -> None:
    import numpy as np

    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
        CSRGraph,
        pad_queries,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
        generators,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
        _pack_queries_jit,
        bitbell_step,
    )

    n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
    g = CSRGraph.from_edges(n, edges)
    bell = BellGraph.from_host(g)
    eng = BitBellEngine(bell)
    queries = pad_queries(
        generators.random_queries(n, k, max_group=64, seed=43), pad_to=64
    )
    queries, _ = eng._pad_queries(queries)

    # Level-0 bucket membership: owner -> bucket, and slots per bucket.
    # Owners appear in _bucket_rows order: ascending within each bucket.
    shapes0 = bell.level_shapes[0]
    deg = np.zeros(n, dtype=np.int64)
    _, _, dd = g.deduped_pairs()
    deg[: dd.shape[0]] = dd
    widths = [w for _, w in shapes0]
    bucket_of = np.full(n, -1, dtype=np.int64)
    prev_w = 0
    for bi, w in enumerate(widths):
        if bi == len(widths) - 1:
            sel = deg > prev_w
        else:
            sel = (deg > prev_w) & (deg <= w)
        bucket_of[sel] = bi
        prev_w = w
    slots_per_owner = np.where(
        bucket_of == len(widths) - 1,
        -(-deg // widths[-1]) * widths[-1],
        np.where(bucket_of >= 0, np.asarray(widths)[np.maximum(bucket_of, 0)], 0),
    )

    visited = _pack_queries_jit(n, queries)
    frontier = visited
    total_slots = int(sum(r * w for r, w in shapes0))
    full_word = np.uint32(0xFFFFFFFF)
    level = 0
    rows = []
    while True:
        vis = np.asarray(visited)
        fully = (vis == full_word).all(axis=1)  # all K queries visited
        skippable = 0
        for bi in range(len(widths)):
            owners = bucket_of == bi
            if owners.any() and fully[owners].all():
                skippable += int(slots_per_owner[owners].sum())
        rows.append(
            {
                "level": level,
                "fully_visited_frac": round(float(fully.mean()), 4),
                "skippable_slots": skippable,
                "skippable_frac": round(skippable / max(total_slots, 1), 4),
            }
        )
        print(json.dumps(rows[-1]), flush=True)
        visited, frontier, counts = bitbell_step(bell, visited, frontier, 0)
        if not np.asarray(counts).any():
            break
        level += 1
    tot = sum(r["skippable_frac"] for r in rows)
    print(
        f"# whole-BFS skippable work: {tot:.4f} dense-level-equivalents "
        f"over {len(rows)} levels (scale={scale}, K={k})"
    )


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    if os.environ.get("MSBFS_EXP_CHILD"):
        measure(scale, k)
        return
    from virtual_cpu import virtual_cpu_env

    env = virtual_cpu_env(1)
    env["MSBFS_EXP_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
        env=env,
        cwd=REPO,
    )
    sys.exit(proc.returncode)


if __name__ == "__main__":
    main()
