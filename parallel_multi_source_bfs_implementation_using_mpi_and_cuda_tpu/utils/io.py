"""Binary graph/query I/O, byte-for-byte compatible with the reference formats.

Graph format (reference LoadGraphBin, main.cu:92-130):
    int32  n                      -- vertex count          (main.cu:102)
    int64  m                      -- undirected edge count (main.cu:104)
    m x (int32 u, int32 v)        -- edge records          (main.cu:108-116)
All little-endian native ints.  Every record is inserted in BOTH adjacency
lists (undirected doubling, main.cu:114-115); duplicates and self-loops are
preserved; neighbor order is insertion order.

Query format (reference LoadQueryBin, main.cu:134-164):
    uint8  K                      -- number of query groups ("up to 64")
    per group: uint8 set_size, then set_size x int32 vertex ids

The reference reads one int per fread (2m+2 calls for the graph — its I/O
hot loop, SURVEY.md section 3 hot-loop #3); here the whole file is read in
one shot and decoded with NumPy, with an optional native C++ decoder
(:mod:`..runtime`) for the CSR build.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

from ..models.csr import CSRGraph

GRAPH_HEADER = struct.Struct("<iq")  # int32 n, int64 m

# Optional trailing weight section (the weighted/ subsystem's cost
# artifact): after the m edge records, a 4-byte magic then m x int32
# positive costs, one per record.  Weightless readers that validate the
# edge count strictly (this loader pre-PR-17, the native C++ loader)
# never see it because they stop at 8m bytes; this loader recognizes the
# magic and refuses anything else trailing — a truncated or bit-flipped
# weight section must fail loud, never load as a weightless graph.
WEIGHT_MAGIC = b"MSBW"


def _graph_bin_layout(path: str | os.PathLike):
    """(n, m, weighted) after full fail-before-allocate validation of
    the header, the edge-list size, and any trailing weight section."""
    with open(path, "rb") as f:
        header = f.read(GRAPH_HEADER.size)
        if len(header) < GRAPH_HEADER.size:
            raise IOError(f"truncated graph header in {path}")
        n, m = GRAPH_HEADER.unpack(header)
        # Validate the counts against the actual file size BEFORE
        # allocating: a bit-flipped header can claim billions of edges,
        # and np.fromfile would try to allocate them all (a corrupt
        # 1 KiB file must never turn into a 288 GiB MemoryError —
        # fuzz-found; the native loader's rc=3 size check, mirrored).
        if n < 0 or m < 0:
            raise IOError(f"corrupt graph header in {path}: n={n}, m={m}")
        remaining = os.fstat(f.fileno()).st_size - GRAPH_HEADER.size
        if remaining < 8 * m:
            raise IOError(
                f"truncated edge list in {path}: header claims {m} edges "
                f"({8 * m} bytes), file has {remaining}"
            )
        extra = remaining - 8 * m
        if extra == 0:
            return n, m, False
        # Anything after the edge records must be EXACTLY one complete
        # weight section: magic + m costs.  Short sections, long
        # sections and wrong magic all refuse — same posture as the
        # header check above.
        if extra != len(WEIGHT_MAGIC) + 4 * m:
            raise IOError(
                f"corrupt weight section in {path}: {extra} trailing "
                f"bytes, expected {len(WEIGHT_MAGIC) + 4 * m} "
                f"(magic + {m} int32 costs) or none"
            )
        f.seek(GRAPH_HEADER.size + 8 * m)
        magic = f.read(len(WEIGHT_MAGIC))
        if magic != WEIGHT_MAGIC:
            raise IOError(
                f"corrupt weight section in {path}: bad magic {magic!r}"
            )
        return n, m, True


def load_graph_bin(path: str | os.PathLike, native: Optional[bool] = None) -> CSRGraph:
    """Load a reference-format binary graph into a host CSR.

    ``native=True`` forces the C++ runtime loader, ``False`` the NumPy path,
    ``None`` auto-selects (native when the shared library is built).
    Weighted files (trailing :data:`WEIGHT_MAGIC` cost section) always
    decode on the NumPy path — the native loader has no cost column, and
    silently dropping weights would serve wrong distances; ``native=True``
    on a weighted file is a typed routing error.
    """
    from .faults import trip

    trip("load_graph")  # fault seam (utils.faults): injectable load failure
    if native:
        # A forced-native request with no library is a typed routing
        # error regardless of what (or whether) the file is — checked
        # before touching the path, like the pre-PR-17 loader.
        from ..runtime import native_loader
        from ..runtime.supervisor import InputError

        if not native_loader.available():
            raise InputError(
                "native loader requested but librt_loader.so is not built "
                "(run `make -C runtime` / `make native`)"
            )
    n, m, weighted = _graph_bin_layout(path)
    if weighted and native:
        from ..runtime.supervisor import InputError

        raise InputError(
            f"{path} carries a weight section, which the native loader "
            "does not decode; use native=False (the NumPy path keeps "
            "the cost array)"
        )
    if not weighted and (native is None or native):
        from ..runtime import native_loader

        if native_loader.available():
            return native_loader.load_graph_csr(os.fspath(path))
    with open(path, "rb") as f:
        f.seek(GRAPH_HEADER.size)
        edges = np.fromfile(f, dtype=np.int32, count=2 * m)
        if edges.size != 2 * m:
            raise IOError(
                f"truncated edge list in {path}: wanted {2*m} ints, "
                f"got {edges.size}"
            )
        weights = None
        if weighted:
            f.seek(len(WEIGHT_MAGIC), os.SEEK_CUR)
            weights = np.fromfile(f, dtype=np.int32, count=m)
            if weights.size != m:
                raise IOError(f"truncated weight section in {path}")
            if m and weights.min() < 1:
                raise IOError(
                    f"corrupt weight section in {path}: costs must be >= 1"
                )
    return CSRGraph.from_edges(n, edges.reshape(m, 2), weights=weights)


def save_graph_bin(
    path: str | os.PathLike,
    n: int,
    edges: np.ndarray,
    weights: Optional[np.ndarray] = None,
) -> None:
    """Write the reference graph format from an (m, 2) int array, with
    an optional trailing :data:`WEIGHT_MAGIC` cost section ((m,) positive
    int32 costs, one per record) for the weighted/ subsystem."""
    edges = np.ascontiguousarray(np.asarray(edges, dtype=np.int32))
    if edges.ndim != 2 or edges.shape[1] != 2:
        raise ValueError("edges must be (m, 2)")
    if weights is not None:
        weights = np.ascontiguousarray(np.asarray(weights, dtype=np.int32))
        if weights.shape != (edges.shape[0],):
            raise ValueError(
                f"weights must be ({edges.shape[0]},), got {weights.shape}"
            )
        if weights.size and weights.min() < 1:
            raise ValueError("edge weights must be >= 1")
    with open(path, "wb") as f:
        f.write(GRAPH_HEADER.pack(int(n), int(edges.shape[0])))
        edges.tofile(f)
        if weights is not None:
            f.write(WEIGHT_MAGIC)
            weights.tofile(f)


def load_query_bin(path: str | os.PathLike) -> List[np.ndarray]:
    """Load the reference query format -> list of K int32 arrays (ragged)."""
    from .faults import trip

    trip("load_query")  # fault seam (utils.faults)
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 1:
        raise IOError(f"empty query file {path}")
    k = data[0]
    queries: List[np.ndarray] = []
    off = 1
    for _ in range(k):
        if off >= len(data):
            raise IOError(f"truncated query file {path}")
        size = data[off]
        off += 1
        if len(data) - off < 4 * size:  # pre-check: frombuffer would raise
            raise IOError(f"truncated query group in {path}")  # ValueError
        ids = np.frombuffer(data, dtype=np.int32, count=size, offset=off)
        off += 4 * size
        queries.append(ids.copy())
    return queries


def save_query_bin(path: str | os.PathLike, queries: Sequence[Sequence[int]]) -> None:
    """Write the reference query format (uint8 K, per-group uint8 size + int32s)."""
    if len(queries) > 255:
        raise ValueError("K must fit in uint8 (reference main.cu:143-145)")
    with open(path, "wb") as f:
        f.write(bytes([len(queries)]))
        for q in queries:
            q = np.asarray(q, dtype=np.int32)
            if q.size > 255:
                raise ValueError("group size must fit in uint8 (main.cu:150-152)")
            f.write(bytes([q.size]))
            q.tofile(f)


def _open_text(path: str | os.PathLike):
    """Open a text dataset, transparently decompressing .gz files."""
    if os.fspath(path).endswith(".gz"):
        import gzip

        return gzip.open(path, "rt")
    return open(path, "r")


def _canonical_undirected(edges: np.ndarray) -> np.ndarray:
    """Arc list -> unique undirected edge list (u <= v).

    Public datasets list both directions of every road segment (DIMACS
    .gr) or mix conventions (SNAP); the reference format stores each
    undirected edge ONCE and doubles it at load (main.cu:106-116), so
    converting arcs verbatim would double every adjacency.  Dropping
    duplicate arcs cannot change BFS distances or F(U) — the per-level hit
    is a set predicate (see BellGraph.from_host on dedup).
    """
    lo = np.minimum(edges[:, 0], edges[:, 1]).astype(np.int64)
    hi = np.maximum(edges[:, 0], edges[:, 1]).astype(np.int64)
    # One packed int64 key per pair: np.unique on a 1-D integer array
    # sorts natively, ~20x the void-dtype row sort that
    # np.unique(..., axis=0) falls back to (measured 6.0 s -> 0.3 s on a
    # 2.5M-arc road file, r5) — ids are int32 so lo << 32 | hi is exact.
    keys = np.unique((lo << 32) | hi)
    # Back to int32 (ids are < 2^31 by construction): the loaders buffer
    # int32 precisely to halve peak RAM on the big public datasets, and
    # every downstream consumer re-casts to int32 anyway.
    return np.stack([keys >> 32, keys & 0xFFFFFFFF], axis=1).astype(np.int32)


def _canonical_undirected_weighted(edges: np.ndarray, weights: np.ndarray):
    """Weighted :func:`_canonical_undirected`: unique undirected pairs
    plus the MINIMUM cost seen across a pair's arcs (both directions of
    a road segment list the same cost in the DIMACS files; where inputs
    disagree, min is the only choice that preserves shortest paths)."""
    lo = np.minimum(edges[:, 0], edges[:, 1]).astype(np.int64)
    hi = np.maximum(edges[:, 0], edges[:, 1]).astype(np.int64)
    keys = (lo << 32) | hi
    order = np.argsort(keys, kind="stable")
    ks, ws = keys[order], np.asarray(weights, dtype=np.int64)[order]
    uniq, start = np.unique(ks, return_index=True)
    wmin = np.minimum.reduceat(ws, start) if uniq.size else ws[:0]
    pairs = np.stack([uniq >> 32, uniq & 0xFFFFFFFF], axis=1).astype(np.int32)
    return pairs, wmin.astype(np.int32)


def _native_text_parse(path, native, parse, label):
    """The ONE native-dispatch policy for the text converters
    (load_dimacs_gr / load_edgelist): auto-select the C++ parser when
    built and the file is plain text, honor native=True/False forcing,
    keep .gz on the Python path.  ``parse(native_loader)`` runs the
    native parse and returns its result, or None when the library is
    unavailable; this helper returns that result or None when the caller
    should fall through to its Python loop."""
    if (native is None or native) and not os.fspath(path).endswith(".gz"):
        from ..runtime import native_loader

        if native_loader.available():
            out = parse(native_loader)
            if out is not None:
                return out
        if native:
            from ..runtime.supervisor import InputError

            raise InputError(
                f"native {label} parser requested but librt_loader.so is "
                "not built (run `make native`)"
            )
    elif native:
        from ..runtime.supervisor import InputError

        raise InputError(f"native {label} parser cannot read .gz files")
    return None


def load_dimacs_gr(
    path: str | os.PathLike,
    native: Optional[bool] = None,
    keep_weights: bool = False,
):
    """Parse a DIMACS shortest-path ``.gr`` file (USA-road-d family) into
    (n, edges) for :func:`save_graph_bin`.

    Format: comment lines ``c ...``, one ``p sp <n> <m>`` header, and arc
    lines ``a <u> <v> <w>`` with 1-based endpoints; weights are dropped
    (the objective is hop-distance, reference main.cu:30-32) unless
    ``keep_weights=True``, which returns (n, edges, weights) for the
    weighted/ subsystem instead — Python path only (the native parser
    has no cost column, so ``native=True`` + ``keep_weights`` is a typed
    routing error).  Arcs are canonicalized to unique undirected edges
    (min cost per pair when kept).

    ``native=True`` forces the C++ parser (plain-text files only; ~40x the
    Python line loop on a 23M-arc file), ``False`` the Python path,
    ``None`` auto-selects (native when built and the file is not .gz).
    """
    if keep_weights and native:
        from ..runtime.supervisor import InputError

        raise InputError(
            "native DIMACS .gr parser drops the cost column; "
            "keep_weights needs native=False"
        )
    parsed = (
        None
        if keep_weights
        else _native_text_parse(
            path,
            native,
            lambda nl: nl.load_gr_arcs(os.fspath(path)),
            "DIMACS .gr",
        )
    )
    if parsed is not None:
        n, arcs = parsed
        return n, _canonical_undirected(arcs)
    n = None
    us: List[np.ndarray] = []
    vs: List[np.ndarray] = []
    wsl: List[np.ndarray] = []
    chunk_u: List[int] = []
    chunk_v: List[int] = []
    chunk_w: List[int] = []
    with _open_text(path) as f:
        for line in f:
            if line.startswith("a "):
                _, u, v, *rest = line.split()
                chunk_u.append(int(u))
                chunk_v.append(int(v))
                if keep_weights:
                    chunk_w.append(int(rest[0]) if rest else 1)
                if len(chunk_u) >= 1 << 20:
                    # int32 buffers: ids fit (the reference format is
                    # int32, main.cu:102), and USA-road-d's 58M arcs would
                    # double peak RAM in int64; out-of-range python ints
                    # raise OverflowError here (fail loud, never wrap).
                    us.append(np.asarray(chunk_u, dtype=np.int32))
                    vs.append(np.asarray(chunk_v, dtype=np.int32))
                    wsl.append(np.asarray(chunk_w, dtype=np.int32))
                    chunk_u, chunk_v, chunk_w = [], [], []
            elif line.startswith("p "):
                parts = line.split()
                n = int(parts[2])
    if n is None:
        raise ValueError(f"{path}: no 'p sp <n> <m>' header line")
    us.append(np.asarray(chunk_u, dtype=np.int32))
    vs.append(np.asarray(chunk_v, dtype=np.int32))
    wsl.append(np.asarray(chunk_w, dtype=np.int32))
    arcs = np.stack([np.concatenate(us), np.concatenate(vs)], axis=1) - 1
    if arcs.size and (arcs.min() < 0 or arcs.max() >= n):
        raise ValueError(f"{path}: arc endpoint outside 1..{n}")
    if not keep_weights:
        return n, _canonical_undirected(arcs)
    weights = np.concatenate(wsl)
    if weights.size and weights.min() < 1:
        raise ValueError(f"{path}: arc costs must be >= 1 for keep_weights")
    pairs, wmin = _canonical_undirected_weighted(arcs, weights)
    return n, pairs, wmin


def save_dimacs_gr(
    path: str | os.PathLike, n: int, edges: np.ndarray, comment: str = ""
) -> int:
    """Write a DIMACS shortest-path ``.gr`` file from an (m, 2) undirected
    edge array, USA-road-d convention: both arc directions listed, weight 1
    (weights are dropped on load — hop-distance objective, main.cu:30-32).

    Returns the number of ``a`` lines written (2m).  This is the
    round-trip complement of :func:`load_dimacs_gr`, used to fabricate
    large real-format fixtures where the sandbox cannot fetch the public
    datasets (zero egress; see benchmarks/exp_gr_end_to_end.sh).
    """
    edges = np.asarray(edges)
    if edges.ndim != 2 or edges.shape[1] != 2:
        raise ValueError("edges must be (m, 2)")
    m = int(edges.shape[0])
    with open(path, "w") as f:
        if comment:
            for line in comment.splitlines():
                f.write(f"c {line}\n")
        f.write(f"p sp {int(n)} {2 * m}\n")
        chunk = 1 << 20
        for lo in range(0, m, chunk):
            part = edges[lo : lo + chunk].astype(np.int64) + 1  # 1-based
            both = np.empty((2 * part.shape[0], 2), dtype=np.int64)
            both[0::2] = part
            both[1::2] = part[:, ::-1]
            np.savetxt(f, both, fmt="a %d %d 1")
    return 2 * m


def load_edgelist(path: str | os.PathLike, native: Optional[bool] = None):
    """Parse a SNAP-style whitespace edge list (``# comments``, one
    ``u v`` pair per line, 0-based ids) into (n, edges).

    n = max id + 1; pairs are canonicalized to unique undirected edges
    (SNAP files mix one-per-edge and both-directions conventions).

    ``native=True`` forces the C++ parser (plain-text only), ``False``
    the Python loop, ``None`` auto-selects (native when built and the
    file is not .gz) — same contract as :func:`load_dimacs_gr`.
    """
    pairs = _native_text_parse(
        path,
        native,
        lambda nl: nl.load_snap_pairs(os.fspath(path)),
        "SNAP",
    )
    if pairs is not None:
        if pairs.size == 0:
            raise ValueError(f"{path}: no edges found")
        n = int(pairs.max()) + 1
        return n, _canonical_undirected(pairs)
    us: List[np.ndarray] = []
    chunk: List[int] = []
    with _open_text(path) as f:
        for line in f:
            if line.startswith(("#", "%")) or not line.strip():
                continue
            u, v, *_ = line.split()
            chunk.append(int(u))
            chunk.append(int(v))
            if len(chunk) >= 1 << 21:
                # int32 (see load_dimacs_gr): halves peak RAM on the big
                # public datasets; ids beyond int32 raise OverflowError.
                us.append(np.asarray(chunk, dtype=np.int32))
                chunk = []
    us.append(np.asarray(chunk, dtype=np.int32))
    flat = np.concatenate(us)
    if flat.size == 0:
        raise ValueError(f"{path}: no edges found")
    pairs = flat.reshape(-1, 2)
    if pairs.min() < 0:
        raise ValueError(f"{path}: negative vertex id")
    n = int(pairs.max()) + 1
    return n, _canonical_undirected(pairs)


def pad_queries(
    queries: Sequence[Sequence[int]], pad_to: Optional[int] = None
) -> np.ndarray:
    """Pad ragged query groups to a (K, S) int32 array with -1 fill.

    -1 padding is semantics-preserving because the BFS source init drops
    out-of-range ids exactly as the reference's bounds check does
    (main.cu:46-51).  ``pad_to`` overrides S (>= max group size).
    """
    K = len(queries)
    max_s = max((len(q) for q in queries), default=0)
    S = pad_to if pad_to is not None else max(max_s, 1)
    if S < max_s:
        raise ValueError(f"pad_to={S} < largest group size {max_s}")
    out = np.full((K, S), -1, dtype=np.int32)
    for i, q in enumerate(queries):
        out[i, : len(q)] = np.asarray(q, dtype=np.int32)
    return out
