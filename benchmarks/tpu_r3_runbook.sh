#!/bin/bash
# Round-3 TPU measurement runbook — run when the axon tunnel is up
# (probe: timeout 110 python -c "import jax; print(jax.devices())").
# Captures every number the round-3 work needs certified, in order of
# importance.  Each step is independently restartable; the persistent XLA
# cache makes repeats cheap.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== 1. headline bench (K=64 + K=256 extra; the driver artifact twin)"
python bench.py | tee /tmp/bench_r3_headline.json

echo "== 2. RMAT-24 (the BASELINE.json target scale)"
BENCH_SCALE=24 BENCH_REPEATS=2 BENCH_EXTRA_KS= python bench.py \
    | tee /tmp/bench_r3_rmat24.json

echo "== 3. estimate_hbm_bytes ground truth via memory_stats"
MSBFS_TEST_TPU=1 python -m pytest \
    tests/test_hbm_estimate.py::test_estimate_brackets_memory_stats -q

echo "== 4. road-class single chip (config 4, push engine)"
python benchmarks/run_baseline.py --config 4

echo "== 5. chunked bitbell on a road graph (the -gn>1 safety path, 1 chip)"
python - <<'EOF'
import time
import numpy as np
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph, pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import generators
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import BellGraph
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import BitBellEngine

side = 512
n, edges = generators.road_edges(side, side, seed=46)
g = CSRGraph.from_edges(n, edges)
q = pad_queries(generators.random_queries(n, 16, max_group=8, seed=44), pad_to=8)
eng = BitBellEngine(BellGraph.from_host(g), level_chunk=32)
eng.compile(q.shape)
t0 = time.perf_counter(); out = eng.best(q); dt = time.perf_counter() - t0
print(f"road-{side} chunked bitbell: {dt:.2f}s best={out} "
      f"({16 * g.num_directed_edges / dt / 1e6:.2f} MTEPS)")
EOF

echo "== done; fold numbers into BASELINE.md and docs/PERF_NOTES.md"
