#!/bin/bash
# Real-dataset end-to-end (VERDICT r4 "Next round" item 5): DIMACS .gr ->
# gen_cli --convert -> main.py report, timed at every stage.
#
# The sandbox has zero egress, so step 0 RECORDS the fetch attempt for the
# USA-road-d family; the fallback (as the verdict prescribes) is a large
# generated .gr fixture — road-class lattice, >= 10M arc lines — pushed
# through the exact same converter + driver path a real download would use.
# Big intermediates are deleted after the run; sizes/hashes stay in the log.
# (No /usr/bin/time in this image: stages are timed with $SECONDS.)
set -uo pipefail
cd "$(dirname "$0")/.."
RAW="${1:-benchmarks/raw_r5}"
WORK="$RAW/gr_fixture"
mkdir -p "$WORK"
SIDE="${GR_SIDE:-3072}"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
echo "gr end-to-end start $(stamp) (side=$SIDE)"

echo "== 0. fetch attempt (expected to fail: zero-egress sandbox)"
timeout 30 curl -sSL -o "$WORK/USA-road-d.NY.gr.gz" \
    "http://www.diag.uniroma1.it/challenge9/data/USA-road-d/USA-road-d.NY.gr.gz" \
    2>&1 && echo "fetch OK (unexpected)" || echo "fetch FAILED rc=$? (zero egress, as expected)"

echo "== 1. fabricate .gr fixture (road-${SIDE}x${SIDE}, save_dimacs_gr)"
T0=$SECONDS
python - "$WORK" "$SIDE" <<'EOF'
import sys, time
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import generators
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import save_dimacs_gr
work, side = sys.argv[1], int(sys.argv[2])
t0 = time.perf_counter()
n, edges = generators.road_edges(side, side, seed=46)
gen_s = time.perf_counter() - t0
t0 = time.perf_counter()
arcs = save_dimacs_gr(f"{work}/fixture.gr", n, edges,
                      comment=f"generated road-{side}x{side} fixture (zero-egress fallback)")
print(f"wrote {arcs} arc lines, n={n}, m={edges.shape[0]} "
      f"(gen {gen_s:.1f}s, write {time.perf_counter()-t0:.1f}s)", flush=True)
EOF
echo "stage-1 wall: $((SECONDS - T0)) s"
ls -l "$WORK/fixture.gr"

echo "== 2. gen_cli --convert (the public-dataset ingest path, timed)"
T0=$SECONDS
python -m parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.gen_cli \
    --convert "$WORK/fixture.gr" --informat dimacs \
    --graph "$WORK/fixture_graph.bin" \
    --queries 16 --max-group 8 --query-file "$WORK/fixture_query.bin" --seed 43
echo "stage-2 wall (parse + canonicalize + write): $((SECONDS - T0)) s"
ls -l "$WORK"/fixture_graph.bin "$WORK"/fixture_query.bin

echo "== 3. main.py end-to-end (reference argv contract, timed)"
T0=$SECONDS
python main.py -g "$WORK/fixture_graph.bin" -q "$WORK/fixture_query.bin" -gn 1
echo "stage-3 wall: $((SECONDS - T0)) s"

echo "== 4. artifact hashes, then delete the big intermediates"
sha256sum "$WORK"/fixture.gr "$WORK"/fixture_graph.bin "$WORK"/fixture_query.bin
du -h "$WORK"/fixture.gr "$WORK"/fixture_graph.bin
rm -f "$WORK"/fixture.gr "$WORK"/fixture_graph.bin "$WORK"/fixture_query.bin "$WORK"/USA-road-d.NY.gr.gz
echo "gr end-to-end end $(stamp)"
