"""Checkpoint/resume for long query batches (beyond-reference capability).

The reference has no checkpointing (SURVEY.md section 5): a failed run
recomputes every query group.  Total job state here is tiny — one int64 F
value per completed query (the distances are scratch) — so the natural
checkpoint unit is a chunk of query groups:

* queries are processed in chunks of ``chunk`` groups through any engine's
  ``f_values``;
* after each chunk the (gid, F) pairs are appended to a CSV-like journal
  and fsync'd via atomic rename (write temp + ``os.replace``), so a crash
  can lose at most the in-flight chunk;
* a restart replays the journal, skips every completed chunk, and finishes
  the rest; selection then runs over the merged F array with the exact
  reference argmin semantics (ties -> lowest index, main.cu:379-397).

The journal is keyed by a fingerprint of the workload (n, directed edge
count, K, S, and a hash of the query ids) — resuming against a different
graph or query set raises instead of silently mixing results.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

import numpy as np

from ..ops.objective import select_best

_MAGIC = "msbfs-ckpt-v1"


def workload_fingerprint(n: int, num_edges: int, queries: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(f"{n}:{num_edges}:{queries.shape}".encode())
    h.update(np.ascontiguousarray(queries, dtype=np.int32).tobytes())
    return h.hexdigest()[:16]


class CheckpointedRunner:
    """Drives ``engine.f_values`` chunk by chunk with a resumable journal.

    >>> runner = CheckpointedRunner(engine, "run.ckpt", chunk=64)
    >>> min_f, min_k = runner.best(graph_n, num_edges, padded_queries)
    """

    def __init__(self, engine, path: str, chunk: int = 64, stats: bool = False):
        self.engine = engine
        self.path = str(path)
        self.chunk = max(1, int(chunk))  # <= 0 would silently compute nothing
        # ``stats``: journal per-query (levels, reached) alongside F via
        # engine.query_stats, so MSBFS_STATS stays alive on checkpointed
        # runs (round 4 — the longest runs used to be the blindest ones).
        # Rows resumed from a stats-less journal keep -1 placeholders.
        self.stats = bool(stats)
        self.last_stats: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # ---- journal ----------------------------------------------------------
    def _read(self, fingerprint: str) -> dict:
        """{gid: F} for completed queries; {} when absent/empty."""
        if not os.path.exists(self.path):
            return {}
        done = {}
        with open(self.path) as f:
            header = f.readline().strip().split(",")
            if header[:1] != [_MAGIC]:
                raise ValueError(f"{self.path}: not a checkpoint journal")
            if len(header) < 2:  # truncated: magic present, fingerprint lost
                raise ValueError(f"{self.path}: malformed checkpoint header")
            if header[1] != fingerprint:
                raise ValueError(
                    f"{self.path}: checkpoint belongs to a different "
                    f"workload (have {header[1]}, want {fingerprint})"
                )
            for line in f:
                parts = line.strip().split(",")
                # 2-column rows are F only (stats-less journals, and every
                # journal before round 4); 4-column rows add levels,reached.
                if len(parts) >= 4:
                    done[int(parts[0])] = (
                        int(parts[1]), int(parts[2]), int(parts[3]),
                    )
                else:
                    done[int(parts[0])] = (int(parts[1]), -1, -1)
        return done

    def _write(self, fingerprint: str, done: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{_MAGIC},{fingerprint}\n")
            for gid in sorted(done):
                fv, lv, rc = done[gid]
                if lv >= 0 or rc >= 0:
                    f.write(f"{gid},{fv},{lv},{rc}\n")
                else:
                    f.write(f"{gid},{fv}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)  # atomic: crash keeps the old journal

    # ---- driver -----------------------------------------------------------
    def run(
        self, n: int, num_edges: int, queries: np.ndarray
    ) -> Tuple[np.ndarray, int]:
        """All K F values (completing missing chunks); returns
        (f_values (K,), number of queries computed this call)."""
        queries = np.asarray(queries, dtype=np.int32)
        k = queries.shape[0]
        fp = workload_fingerprint(n, num_edges, queries)
        done = self._read(fp)
        computed = 0
        for lo in range(0, k, self.chunk):
            hi = min(lo + self.chunk, k)
            if all(g in done for g in range(lo, hi)):
                continue
            chunk_q = queries[lo:hi]
            stats = self.engine.query_stats(chunk_q) if self.stats else None
            if stats is not None:
                levels, reached, f = stats
                for g in range(lo, hi):
                    i = g - lo
                    done[g] = (int(f[i]), int(levels[i]), int(reached[i]))
            else:
                f = np.asarray(self.engine.f_values(chunk_q))
                for g in range(lo, hi):
                    done[g] = (int(f[g - lo]), -1, -1)
            computed += hi - lo
            self._write(fp, done)
        out = np.array([done[g][0] for g in range(k)], dtype=np.int64)
        if self.stats:
            self.last_stats = (
                np.array([done[g][1] for g in range(k)], dtype=np.int32),
                np.array([done[g][2] for g in range(k)], dtype=np.int32),
            )
        return out, computed

    def best(
        self, n: int, num_edges: int, queries: np.ndarray
    ) -> Tuple[int, int]:
        f, _ = self.run(n, num_edges, queries)
        import jax.numpy as jnp

        arr = jnp.asarray(f)
        min_f, min_k = select_best(arr, arr >= 0)
        return int(min_f), int(min_k)

    def clear(self) -> None:
        if os.path.exists(self.path):
            os.remove(self.path)
