"""Device compute: BFS engines, objective, batched execution."""

from .bfs import (
    multi_source_bfs,
    batched_multi_source_bfs,
    init_distances,
    frontier_expand,
    graph_expand,
)
from .dense import DenseGraph
from .objective import f_of_u, select_best
from .engine import Engine, QueryEngineBase

__all__ = [
    "multi_source_bfs",
    "batched_multi_source_bfs",
    "init_distances",
    "frontier_expand",
    "graph_expand",
    "DenseGraph",
    "f_of_u",
    "select_best",
    "Engine",
    "QueryEngineBase",
]
