#!/usr/bin/env python3
"""Entry point preserving the reference CLI (reference main.cu:195-422):

    python main.py -g <graph.bin> -q <query.bin> -gn <numChips>
"""

import sys

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main(sys.argv))
