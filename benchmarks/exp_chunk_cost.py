#!/usr/bin/env python3
"""Measure the cost of ALWAYS bounding bit-plane dispatches (round 4).

Round 3 gated the bounded level loop (``bitbell_run_chunked``) behind a
degree heuristic because the unbounded single-dispatch path was assumed
faster on shallow power-law graphs.  The heuristic can be fooled (VERDICT
r3 "Missing" #2: one >64-degree hub on a deep graph takes the unbounded
path), so round 4 wants the bound unconditional — IF the cost on shallow
graphs is small.  The chunked loop's inner while_loop exits on
convergence, so a ~10-level power-law BFS pays exactly one extra host
scalar sync; this script measures that end to end.

Prints one line per scenario: engine wall time unchunked vs chunked and
the ratio.  Run on the CPU mesh for the routing decision; re-run on TPU
via benchmarks/tpu_r4_runbook.sh step 6 for the certified number.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.csr import (
    CSRGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
    pad_queries,
)


def scenario(name, g, k, repeats=3, chunk=32):
    q = pad_queries(
        generators.random_queries(g.n, k, max_group=8, seed=7), pad_to=8
    )
    bell = BellGraph.from_host(g)
    rows = {}
    for label, level_chunk in (("unchunked", None), (f"chunk={chunk}", chunk)):
        eng = BitBellEngine(bell, level_chunk=level_chunk)
        eng.compile(q.shape)
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = eng.best(q)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        rows[label] = (best, out)
    (tu, ou), (tc, oc) = rows["unchunked"], rows[f"chunk={chunk}"]
    assert ou == oc, f"{name}: chunked result {oc} != unchunked {ou}"
    print(
        f"{name}: unchunked {tu:.4f}s  chunk={chunk} {tc:.4f}s  "
        f"ratio {tc / tu:.3f}  (K={k})"
    )


def main():
    import jax

    print(f"backend: {jax.default_backend()}, devices: {len(jax.devices())}")
    scale = int(os.environ.get("CHUNK_COST_SCALE", "18"))
    n, edges = generators.rmat_edges(scale, edge_factor=16, seed=42)
    scenario(f"RMAT-{scale} power-law", CSRGraph.from_edges(n, edges), 64)
    side = int(os.environ.get("CHUNK_COST_SIDE", "256"))
    n, edges = generators.road_edges(side, side, seed=46)
    scenario(f"road-{side}x{side}", CSRGraph.from_edges(n, edges), 16)


if __name__ == "__main__":
    main()
