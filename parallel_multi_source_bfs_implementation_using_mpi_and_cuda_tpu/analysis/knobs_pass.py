"""Knob-contract pass: the ``MSBFS_*`` env surface must round-trip
through ``utils/knobs.py`` and the README table exactly.

Rules:

* ``unregistered-knob`` — a ``MSBFS_*`` string literal anywhere in the
  scanned tree that is not a registry name.
* ``raw-env-read`` — package code (outside ``utils/knobs.py``) reading a
  knob straight off ``os.environ``/``os.getenv`` instead of through the
  registry accessors.  Env *writes* (harness setup, subprocess plumbing)
  stay legal.
* ``dead-knob`` — a registered knob nothing references.  References are
  counted across .py files plus the native sources (``runtime/*.cpp``),
  since ``MSBFS_NATIVE_THREADS`` is read in C++.
* ``undocumented-knob`` — a registered knob missing from README.md's
  knob table.

The analyzer's own fixture corpus (``tests/test_analyze.py``) is
excluded from the literal scan: it deliberately contains violating
snippets.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Set

from .core import Finding, ParsedFile, dotted

KNOB_RE = re.compile(r"^MSBFS_[A-Z0-9_]+$")
KNOB_TOKEN_RE = re.compile(r"MSBFS_[A-Z0-9_]+")
EXCLUDED_FILES = {"tests/test_analyze.py"}
REGISTRY_FILE = "parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu/utils/knobs.py"


def _load_registry() -> Dict[str, object]:
    from ..utils import knobs as _knobs

    return dict(_knobs.KNOBS)


def _is_env_read(node: ast.Call) -> bool:
    name = dotted(node.func) or ""
    return name in ("os.environ.get", "os.getenv", "environ.get", "getenv")


def run(files: List[ParsedFile], root: str, registry: Dict[str, object] = None) -> List[Finding]:
    registry = registry if registry is not None else _load_registry()
    findings: List[Finding] = []
    referenced: Set[str] = set()

    for pf in files:
        if pf.path in EXCLUDED_FILES:
            continue
        in_registry_file = pf.path == REGISTRY_FILE
        in_package = pf.path.startswith(
            "parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu/"
        )
        env_read_lines: Set[int] = set()
        if in_package and not in_registry_file:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Call) and _is_env_read(node):
                    args = list(node.args)
                    if args and isinstance(args[0], ast.Constant) and isinstance(args[0].value, str):
                        if KNOB_RE.match(args[0].value):
                            env_read_lines.add(node.lineno)
                            findings.append(Finding(
                                "knobs", "raw-env-read", pf.path, node.lineno, "",
                                args[0].value,
                                f"{args[0].value} read via os.environ — go through utils.knobs",
                            ))
                elif (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and (dotted(node.value) or "") in ("os.environ", "environ")
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and KNOB_RE.match(node.slice.value)
                ):
                    env_read_lines.add(node.lineno)
                    findings.append(Finding(
                        "knobs", "raw-env-read", pf.path, node.lineno, "",
                        node.slice.value,
                        f"{node.slice.value} read via os.environ[] — go through utils.knobs",
                    ))

        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for tok in KNOB_TOKEN_RE.findall(node.value):
                    if not in_registry_file:
                        # Registry declarations don't count as references,
                        # or dead-knob could never fire.
                        referenced.add(tok)
                    if (
                        KNOB_RE.match(node.value)
                        and tok not in registry
                        and not in_registry_file
                    ):
                        findings.append(Finding(
                            "knobs", "unregistered-knob", pf.path, node.lineno, "",
                            tok,
                            f"{tok} is not declared in utils/knobs.py",
                        ))

    # Native sources count as references (MSBFS_NATIVE_THREADS lives in C++).
    for cpp in glob.glob(os.path.join(root, "**", "*.cpp"), recursive=True):
        with open(cpp, "r", errors="replace") as fh:
            referenced.update(KNOB_TOKEN_RE.findall(fh.read()))

    reg_names = set(registry)
    for name in sorted(reg_names):
        if name not in referenced:
            findings.append(Finding(
                "knobs", "dead-knob", REGISTRY_FILE, 1, "KNOBS", name,
                f"{name} is registered but nothing reads it — delete it",
            ))

    readme = os.path.join(root, "README.md")
    documented: Set[str] = set()
    if os.path.exists(readme):
        with open(readme, "r") as fh:
            documented = set(KNOB_TOKEN_RE.findall(fh.read()))
    for name in sorted(reg_names):
        if name not in documented:
            findings.append(Finding(
                "knobs", "undocumented-knob", "README.md", 1, "knob-table", name,
                f"{name} is registered but missing from the README knob table",
            ))
    return findings
