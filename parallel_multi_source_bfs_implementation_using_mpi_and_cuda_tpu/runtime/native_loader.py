"""ctypes bindings for the native graph loader (runtime/loader.cpp).

Protocol (caller-allocated buffers, no cross-language ownership):
  1. ``msbfs_graph_header(path, &n, &m)`` reads the header;
  2. Python allocates ``row_offsets`` (n+1 int64) and ``col_indices``
     (2m int32);
  3. ``msbfs_load_graph_csr(path, n, m, row_offsets, col_indices)`` decodes
     the edge list and builds the insertion-order CSR (the exact adjacency
     order of reference main.cu:106-129) in one pass.

Falls back cleanly when the shared library has not been built.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..models.csr import CSRGraph

_LIB_NAME = "librt_loader.so"
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIB_NAME)


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    path = _lib_path()
    if not os.path.exists(path):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.msbfs_graph_header.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.msbfs_graph_header.restype = ctypes.c_int
        lib.msbfs_load_graph_csr.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int64, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
        ]
        lib.msbfs_load_graph_csr.restype = ctypes.c_int
        lib.msbfs_csr_from_edges.argtypes = [
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=2, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int64, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
        ]
        lib.msbfs_csr_from_edges.restype = ctypes.c_int
        lib.msbfs_dedup_rows.argtypes = [
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int64, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int64, ndim=1, flags="C_CONTIGUOUS"),
        ]
        lib.msbfs_dedup_rows.restype = ctypes.c_int64
        i64v = np.ctypeslib.ndpointer(
            dtype=np.int64, ndim=1, flags="C_CONTIGUOUS"
        )
        i32v = np.ctypeslib.ndpointer(
            dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"
        )
        lib.msbfs_bell_assign.argtypes = [
            ctypes.c_int64, i64v, ctypes.c_int, i32v, i64v, i64v, i64v, i64v,
        ]
        lib.msbfs_bell_assign.restype = ctypes.c_int64
        lib.msbfs_bell_fill.argtypes = [
            ctypes.c_int64, i64v, i64v, ctypes.c_int, i32v, i32v,
            ctypes.c_int64, i64v, i64v, i64v, ctypes.c_int32, i32v,
        ]
        lib.msbfs_bell_fill.restype = ctypes.c_int
        lib.msbfs_rmat_edges.argtypes = [
            ctypes.c_int32, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_uint64,
            np.ctypeslib.ndpointer(
                dtype=np.int32, ndim=2, flags="C_CONTIGUOUS"
            ),
        ]
        lib.msbfs_rmat_edges.restype = ctypes.c_int
        lib.msbfs_gr_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.msbfs_gr_scan.restype = ctypes.c_int
        lib.msbfs_gr_arcs.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
        ]
        lib.msbfs_gr_arcs.restype = ctypes.c_int
        lib.msbfs_snap_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.msbfs_snap_scan.restype = ctypes.c_int
        lib.msbfs_snap_pairs.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, ndim=1, flags="C_CONTIGUOUS"),
        ]
        lib.msbfs_snap_pairs.restype = ctypes.c_int
        _lib = lib
    except (OSError, AttributeError):
        # AttributeError: a stale .so built before a newer symbol existed —
        # fall back to the NumPy paths rather than crash ("make native").
        _load_failed = True
    return _lib


def available() -> bool:
    return _get_lib() is not None


def load_graph_csr(path: str) -> CSRGraph:
    lib = _get_lib()
    if lib is None:
        from .supervisor import InputError

        raise InputError(f"{_LIB_NAME} not built (run `make native`)")
    n = ctypes.c_int64()
    m = ctypes.c_int64()
    rc = lib.msbfs_graph_header(path.encode(), ctypes.byref(n), ctypes.byref(m))
    if rc != 0:
        raise IOError(f"native loader: cannot read header of {path} (rc={rc})")
    row_offsets = np.zeros(n.value + 1, dtype=np.int64)
    col_indices = np.zeros(2 * m.value, dtype=np.int32)
    rc = lib.msbfs_load_graph_csr(path.encode(), n.value, m.value, row_offsets, col_indices)
    if rc != 0:
        raise IOError(f"native loader: failed to decode {path} (rc={rc})")
    return CSRGraph(
        n=int(n.value), m=int(m.value), row_offsets=row_offsets, col_indices=col_indices
    )


def csr_from_edges(n: int, edges: np.ndarray):
    """Native in-memory CSR build from an (m, 2) int32 edge array.

    Returns (row_offsets, col_indices) or None when the library is
    unavailable (caller falls back to the NumPy argsort path).  Raises
    ValueError on an out-of-range endpoint — the same contract as the
    NumPy path's explicit bounds check.
    """
    lib = _get_lib()
    if lib is None or not hasattr(lib, "msbfs_csr_from_edges"):
        return None
    edges = np.asarray(edges)
    if edges.size and edges.dtype != np.int32 and (
        edges.min() < -(2**31) or edges.max() >= 2**31
    ):
        # int32 conversion would wrap (possibly onto a VALID id) before
        # the native bounds check could see it — fail loud instead.
        raise ValueError("edge endpoint exceeds int32")
    edges = np.ascontiguousarray(edges, dtype=np.int32)
    m = edges.shape[0]
    row_offsets = np.zeros(n + 1, dtype=np.int64)
    col_indices = np.empty(2 * m, dtype=np.int32)
    rc = lib.msbfs_csr_from_edges(n, m, edges, row_offsets, col_indices)
    if rc == 4:
        raise ValueError(f"edge endpoint out of range [0, {n})")
    if rc != 0:
        raise ValueError(f"native csr_from_edges failed (rc={rc})")
    return row_offsets, col_indices


def dedup_rows(row_offsets: np.ndarray, col_indices: np.ndarray):
    """Native per-row neighbor dedup (sorted, self-loops dropped).

    Returns (dst, deg) with ``dst`` already sliced to the deduped slot
    count, or None when the native library is unavailable (caller falls
    back to the NumPy path).
    """
    lib = _get_lib()
    if lib is None:
        return None
    n = row_offsets.shape[0] - 1
    row_offsets = np.ascontiguousarray(row_offsets, dtype=np.int64)
    col_indices = np.ascontiguousarray(col_indices, dtype=np.int32)
    out_dst = np.empty(col_indices.shape[0], dtype=np.int32)
    out_deg = np.empty(max(n, 1), dtype=np.int64)
    w = lib.msbfs_dedup_rows(
        n, col_indices.shape[0], row_offsets, col_indices, out_dst, out_deg
    )
    if w < 0:
        raise ValueError("native dedup_rows: corrupt CSR input")
    return out_dst[:w], out_deg[:n]


def bell_level(item_start, item_count, item_vals, widths, sentinel_value):
    """Fused native build of one BELL forest level: bucket assignment +
    padded-row fill + value mapping + sentinel fix in two O(V)/O(slots)
    passes writing the final int32 flat array directly (the NumPy path,
    models/bell._bucket_rows + the map/fix/pack that follows, makes five
    full-size passes through int64 intermediates).

    Returns (flat int32, shapes, rows_per_owner int64, first_row int64)
    with exactly models/bell semantics, or None when the library is
    unavailable or lacks the symbols (stale .so)."""
    lib = _get_lib()
    if lib is None or not hasattr(lib, "msbfs_bell_assign"):
        return None
    item_start = np.ascontiguousarray(item_start, dtype=np.int64)
    item_count = np.ascontiguousarray(item_count, dtype=np.int64)
    item_vals = np.ascontiguousarray(item_vals, dtype=np.int32)
    widths_arr = np.ascontiguousarray(widths, dtype=np.int32)
    v_total = item_count.shape[0]
    nb = widths_arr.shape[0]
    rows_per_owner = np.empty(max(v_total, 1), dtype=np.int64)
    first_row = np.empty(max(v_total, 1), dtype=np.int64)
    bucket_rows = np.empty(max(nb, 1), dtype=np.int64)
    flat_off = np.empty(max(nb, 1), dtype=np.int64)
    slots = lib.msbfs_bell_assign(
        v_total, item_count, nb, widths_arr, rows_per_owner, first_row,
        bucket_rows, flat_off,
    )
    if slots < 0:
        raise ValueError("native bell_assign: bad input")
    flat = np.empty(slots, dtype=np.int32)
    rc = lib.msbfs_bell_fill(
        v_total, item_start, item_count, nb, widths_arr, item_vals,
        item_vals.shape[0], first_row, bucket_rows, flat_off,
        np.int32(sentinel_value), flat,
    )
    if rc != 0:
        raise ValueError(f"native bell_fill failed (rc={rc})")
    shapes = tuple(
        (int(bucket_rows[b]), int(widths_arr[b])) for b in range(nb)
    )
    return flat, shapes, rows_per_owner[:v_total], first_row[:v_total]


def rmat_edges(scale, m, a, b, c, seed):
    """Native R-MAT edge sampler: same construction as
    models/generators.rmat_edges but a different RNG stream (splitmix64),
    so a given seed yields a different — identically distributed — graph.
    Returns an (m, 2) int32 array or None when unavailable."""
    lib = _get_lib()
    if lib is None or not hasattr(lib, "msbfs_rmat_edges"):
        return None
    out = np.empty((m, 2), dtype=np.int32)
    rc = lib.msbfs_rmat_edges(scale, m, a, b, c, np.uint64(seed), out)
    if rc != 0:
        raise ValueError(f"native rmat_edges failed (rc={rc})")
    return out


_GR_ERRORS = {
    1: "cannot open file",
    2: "no 'p sp <n> <m>' header line",
    3: "malformed arc line",
    4: "arc endpoint outside 1..n",
    5: "arc count changed between scan and parse",
    6: "header vertex count exceeds int32 (reference format is int32 n)",
}


def load_gr_arcs(path: str):
    """Native DIMACS .gr parse -> (n, (R, 2) int32 0-based arc array), or
    None when the native library is unavailable (the caller keeps its
    Python line loop).  Raises ValueError on a malformed file with the
    same fail-loud posture as the Python parser (utils/io.py).  Plain
    text only — .gz files stay on the Python path."""
    lib = _get_lib()
    if lib is None:
        # A stale .so missing the symbol already failed _get_lib's
        # argtypes setup (AttributeError -> _load_failed), so lib being
        # non-None implies the symbol exists.
        return None
    n = ctypes.c_int64()
    arcs = ctypes.c_int64()
    rc = lib.msbfs_gr_scan(path.encode(), ctypes.byref(n), ctypes.byref(arcs))
    if rc != 0:
        raise ValueError(
            f"{path}: {_GR_ERRORS.get(rc, f'native gr_scan rc={rc}')}"
        )
    u = np.empty(arcs.value, dtype=np.int32)
    v = np.empty(arcs.value, dtype=np.int32)
    rc = lib.msbfs_gr_arcs(path.encode(), n.value, arcs.value, u, v)
    if rc != 0:
        raise ValueError(
            f"{path}: {_GR_ERRORS.get(rc, f'native gr_arcs rc={rc}')}"
        )
    return int(n.value), np.stack([u, v], axis=1)


_SNAP_ERRORS = {
    1: "cannot open file",
    3: "malformed edge line (expected two integer ids)",
    5: "edge count changed between scan and parse",
    6: "vertex id exceeds int32",
}


def load_snap_pairs(path: str):
    """Native SNAP whitespace-edge-list parse -> (R, 2) int32 0-based
    pairs, or None when the native library is unavailable.  Mirrors the
    Python loop's skip rules ('#'/'%'/blank) and fail-loud posture
    (utils/io.py::load_edgelist); plain text only."""
    lib = _get_lib()
    if lib is None:
        return None
    pairs = ctypes.c_int64()
    rc = lib.msbfs_snap_scan(path.encode(), ctypes.byref(pairs))
    if rc != 0:
        raise ValueError(
            f"{path}: {_SNAP_ERRORS.get(rc, f'native snap_scan rc={rc}')}"
        )
    u = np.empty(pairs.value, dtype=np.int32)
    v = np.empty(pairs.value, dtype=np.int32)
    rc = lib.msbfs_snap_pairs(path.encode(), pairs.value, u, v)
    if rc != 0:
        raise ValueError(
            f"{path}: {_SNAP_ERRORS.get(rc, f'native snap_pairs rc={rc}')}"
        )
    return np.stack([u, v], axis=1)
