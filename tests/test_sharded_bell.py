"""Vertex-sharded bit-plane engine: oracle parity across mesh shapes."""

import jax
import numpy as np
import pytest

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
    make_mesh,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
    ShardedBellEngine,
    build_sharded_forest,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def oracle_f_values(n, edges, queries):
    return [oracle_f(oracle_bfs(n, edges, q)) for q in queries]


@pytest.fixture(scope="module")
def problem():
    n, edges = generators.rmat_edges(8, edge_factor=8, seed=401)
    queries = generators.random_queries(n, 9, max_group=4, seed=402)
    queries[4] = np.zeros(0, dtype=np.int32)
    return n, edges, queries, pad_queries(queries)


@pytest.mark.parametrize("q,v", [(1, 2), (1, 8), (2, 4), (4, 2)])
def test_sharded_bell_matches_oracle(problem, q, v):
    n, edges, queries, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=q, num_vertex_shards=v)
    eng = ShardedBellEngine(mesh, graph)
    got = np.asarray(eng.f_values(padded))
    want = oracle_f_values(n, edges, queries)
    np.testing.assert_array_equal(got, want)
    assert eng.best(padded) == oracle_best(want)


def test_sharded_bell_matches_sharded_csr(problem):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_csr import (
        ShardedEngine,
    )

    n, edges, _, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=2, num_vertex_shards=2)
    a = np.asarray(ShardedBellEngine(mesh, graph).f_values(padded))
    b = np.asarray(ShardedEngine(mesh, graph).f_values(padded))
    np.testing.assert_array_equal(a, b)


def test_sharded_bell_uneven_n():
    """n not divisible by the shard count pads the last block."""
    n, edges = generators.gnm_edges(101, 350, seed=403)  # 101 % 4 != 0
    graph = CSRGraph.from_edges(n, edges)
    queries = generators.random_queries(n, 5, max_group=3, seed=404)
    padded = pad_queries(queries)
    mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
    got = np.asarray(ShardedBellEngine(mesh, graph).f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_sharded_bell_hub_imbalance():
    """A star graph puts every edge in one shard: harmonization must pad
    the other shards' forests with sentinel rows (different level counts)."""
    n_leaves = 300
    n = n_leaves + 1
    edges = np.stack(
        [np.zeros(n_leaves, dtype=np.int64), np.arange(1, n, dtype=np.int64)],
        axis=1,
    )
    graph = CSRGraph.from_edges(n, edges)
    queries = [np.array([0], dtype=np.int32), np.array([7], dtype=np.int32)]
    padded = pad_queries(queries)
    mesh = make_mesh(num_query_shards=1, num_vertex_shards=8)
    got = np.asarray(ShardedBellEngine(mesh, graph).f_values(padded))
    np.testing.assert_array_equal(got, oracle_f_values(n, edges, queries))


def test_sharded_bell_out_of_range_source_dropped():
    """Reference bounds check (main.cu:48-50): a source id >= n is dropped.
    The forest pads n to n_pad = shards * block; an id in [n, n_pad) must
    not become a phantom source that inflates reached/levels stats."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )

    n, edges = generators.gnm_edges(101, 350, seed=405)  # n_pad = 104 on v=4
    graph = CSRGraph.from_edges(n, edges)
    queries = [
        np.array([0, 102], dtype=np.int32),  # 102 in [n, n_pad): phantom
        np.array([3, 4], dtype=np.int32),
    ]
    padded = pad_queries(queries)
    mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
    eng = ShardedBellEngine(mesh, graph)
    np.testing.assert_array_equal(
        np.asarray(eng.f_values(padded)),
        oracle_f_values(n, edges, [q[q < n] for q in queries]),
    )
    a = eng.query_stats(padded)
    b = BitBellEngine(BellGraph.from_host(graph)).query_stats(padded)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert int(a[1][0]) <= n  # reached count cannot exceed true n


def test_build_sharded_forest_shapes():
    n, edges = generators.rmat_edges(7, edge_factor=6, seed=405)
    g = CSRGraph.from_edges(n, edges)
    stacked, block, n_pad = build_sharded_forest(g, 4)
    assert n_pad == 4 * block >= n
    assert stacked.final_slot.shape == (4, n_pad)
    for per_bucket in stacked.levels:
        lead = {c.shape[0] for c in per_bucket}
        assert lead == {4}  # every bucket stacked over all shards


def test_sharded_bell_query_stats_match_single_chip(problem):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
        BellGraph,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
        BitBellEngine,
    )

    n, edges, _, padded = problem
    graph = CSRGraph.from_edges(n, edges)
    mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
    a = ShardedBellEngine(mesh, graph).query_stats(padded)
    b = BitBellEngine(BellGraph.from_host(graph)).query_stats(padded)
    assert a is not None
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


class TestSparseHalo:
    """Round-3 compacted halo + in-block push: when a level's own-frontier
    rows fit halo_budget, shards exchange (global id, words) pairs instead
    of full planes; when the frontier's in-block edges also fit
    push_budget, the local expansion scatters those pairs directly (no
    forest gather at all).  Every routing combination must be bit-identical
    to the dense reference (docs/PERF_NOTES.md "ICI cost model" names this
    the road-class fix)."""

    def _road(self):
        n = 300
        edges = np.stack(
            [np.arange(n - 1), np.arange(1, n)], axis=1
        ).astype(np.int64)
        queries = [
            np.array([0], dtype=np.int32),
            np.array([n - 1], dtype=np.int32),
            np.array([7, 150], dtype=np.int32),
            np.zeros(0, dtype=np.int32),
        ]
        return n, edges, queries, pad_queries(queries)

    @pytest.mark.parametrize(
        "halo,push",
        [
            (16, None),  # sparse exchange + auto push
            (16, 1),  # sparse exchange, push budget too small -> rebuild
            (16, 0),  # sparse exchange, push disabled -> rebuild+forest
            (0, None),  # dense exchange only (round-2 behavior)
            (None, None),  # full auto
        ],
    )
    def test_road_all_routings_match_oracle(self, halo, push):
        n, edges, queries, padded = self._road()
        g = CSRGraph.from_edges(n, edges)
        mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
        eng = ShardedBellEngine(mesh, g, halo_budget=halo, push_budget=push)
        got = np.asarray(eng.f_values(padded))
        want = oracle_f_values(n, edges, queries)
        np.testing.assert_array_equal(got, want)

    def test_power_law_mixed_branches(self, problem):
        """Fat mid-levels take the dense path, thin head/tail the sparse
        path, within ONE run — stats must still match the oracle."""
        n, edges, queries, padded = problem
        g = CSRGraph.from_edges(n, edges)
        mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
        eng = ShardedBellEngine(mesh, g, halo_budget=4, push_budget=32)
        levels, reached, f = eng.query_stats(padded)
        for i, q in enumerate(queries):
            dist = oracle_bfs(n, edges, q)
            assert f[i] == oracle_f(dist)
            assert reached[i] == int((dist >= 0).sum())

    def test_chunked_composes_with_push_halo(self):
        n, edges, queries, padded = self._road()
        g = CSRGraph.from_edges(n, edges)
        mesh = make_mesh(num_query_shards=1, num_vertex_shards=8)
        ref = ShardedBellEngine(mesh, g, halo_budget=0).query_stats(padded)
        got = ShardedBellEngine(
            mesh, g, halo_budget=8, push_budget=64, level_chunk=16
        ).query_stats(padded)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_level_stats_with_push_halo(self):
        n, edges, queries, padded = self._road()
        g = CSRGraph.from_edges(n, edges)
        mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
        eng = ShardedBellEngine(mesh, g, halo_budget=8, push_budget=64)
        levels, reached, f, lc, secs = eng.level_stats(padded)
        w = eng.query_stats(padded)
        np.testing.assert_array_equal(levels, w[0])
        np.testing.assert_array_equal(reached, w[1])
        np.testing.assert_array_equal(f, w[2])
        np.testing.assert_array_equal(lc.sum(axis=0), reached)

    def test_lone_push_budget_warns(self, capsys):
        """push_budget without halo_budget is dead config — it must warn,
        not silently no-op (ADVICE r3)."""
        n, edges, queries, padded = self._road()
        g = CSRGraph.from_edges(n, edges)
        mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
        eng = ShardedBellEngine(mesh, g, halo_budget=0, push_budget=16)
        assert "halo_budget" in capsys.readouterr().err
        assert eng.push is None and eng.push_budget == 0
        np.testing.assert_array_equal(
            np.asarray(eng.f_values(padded)),
            oracle_f_values(n, edges, queries),
        )

    def test_edgeless_graph_push_guard(self):
        g = CSRGraph.from_edges(5, np.zeros((0, 2), dtype=np.int64))
        mesh = make_mesh(num_query_shards=2, num_vertex_shards=4)
        eng = ShardedBellEngine(mesh, g, halo_budget=4, push_budget=16)
        padded = pad_queries([np.array([2], dtype=np.int32)])
        levels, reached, f = eng.query_stats(padded)
        assert reached[0] == 1 and f[0] == 0 and levels[0] == 1

    def test_halo_byte_counters_exact(self):
        """VERDICT r3 item 5: the ICI byte claims as counters.  The
        per-level own-frontier rows, route and wire bytes recorded by
        level_stats must match an INDEPENDENT host computation from
        oracle BFS distances, for both routings in one run."""
        n, edges = generators.grid_edges(16, 16)  # n = 256
        g = CSRGraph.from_edges(n, edges)
        queries = [
            np.array([0], dtype=np.int32),
            np.array([255], dtype=np.int32),
        ]
        padded = pad_queries(queries)
        p, budget = 8, 2
        mesh = make_mesh(num_query_shards=1, num_vertex_shards=p)
        eng = ShardedBellEngine(mesh, g, halo_budget=budget)
        eng.level_stats(padded)
        trace = eng.last_halo_trace
        L = -(-n // p)
        n_pad = p * L
        w_words = 1  # 2 queries pad to one 32-bit plane word
        dists = [oracle_bfs(n, edges, q) for q in queries]
        expected_rows = []
        d = 0
        while True:
            front = np.zeros(n, dtype=bool)
            for dist in dists:
                front |= dist == d
            if not front.any():
                break
            expected_rows.append(
                max(
                    int(front[b * L : (b + 1) * L].sum()) for b in range(p)
                )
            )
            d += 1
        assert len(trace) == len(expected_rows)
        routes_seen = set()
        for row, rows in zip(trace, expected_rows):
            assert row["own_rows"] == rows
            if rows <= budget:
                assert row["routes"] == ["sparse"]
                assert row["bytes"] == p * budget * 4 * (1 + w_words)
            else:
                assert row["routes"] == ["dense"]
                assert row["bytes"] == n_pad * w_words * 4
            routes_seen.add(row["routes"][0])
        assert routes_seen == {"sparse", "dense"}  # both branches ran

    def test_budget_defaults(self):
        from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
            default_halo_budget,
            default_push_halo_budget,
        )

        assert default_halo_budget(1 << 20, 8) == max(2048, (1 << 20) // 512)
        assert default_push_halo_budget(1 << 26, 8) == (1 << 26) // 512
        assert default_push_halo_budget(0, 8) == 1 << 14  # floor
        assert default_push_halo_budget(1 << 40, 8) == 1 << 22  # cap
