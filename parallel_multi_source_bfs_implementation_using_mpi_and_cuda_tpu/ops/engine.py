"""Single-device query engine: batched BFS + objective with chunked vmap.

This is the device-compute orchestrator that replaces the reference's serial
per-query loop (main.cu:312-322).  Queries are vmap-batched in chunks of
``query_chunk`` (a memory/throughput knob: the per-level intermediates are
O(chunk * E), so chunking bounds HBM pressure on large graphs) and the chunk
loop is a ``lax.map`` — everything stays inside one jitted program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.csr import DeviceCSR
from ..utils.donation import donating_jit
from ..utils.timing import record_dispatch
from .bfs import (
    distance_carry_init,
    distance_chunk,
    graph_expand,
    host_chunked_loop,
    multi_source_bfs,
    validate_level_chunk,
)
from .objective import f_of_u, select_best_jit


def frontier_activity(frontier: jax.Array, edge_counts: jax.Array):
    """(active, cnt, edges) frontier-density estimate: the per-level
    measurement every direction decision in the repo shares (bitbell /
    lowk hybrid routing, the mxu push/matmul switch).  ``frontier`` is
    any (n, lanes) plane layout where a nonzero row means "vertex is in
    the frontier" — uint32 bit planes and uint8 byte flags both qualify;
    ``edge_counts`` is the per-vertex dedup out-degree.  Returns the
    (n,) bool active mask, the int32 active-row count, and the int32
    outgoing-edge total of the active rows."""
    active = (frontier != 0).any(axis=1)
    cnt = jnp.sum(active, dtype=jnp.int32)
    edges = jnp.sum(jnp.where(active, edge_counts, 0), dtype=jnp.int32)
    return active, cnt, edges


def source_band(queries, n: int):
    """Host-side initial frontier band ``[lo, hi)`` from (K, S) padded
    queries: the active-row estimate the stencil window sizes its first
    chunk from (StencilEngine._band_of) — ``[0, 0]`` when no source is
    in range.  Pure NumPy; callers gate on "queries are host arrays"
    themselves."""
    q = np.asarray(queries)
    valid = (q >= 0) & (q < n)
    if not valid.any():
        return [0, 0]
    vs = q[valid]
    return [int(vs.min()), int(vs.max()) + 1]


@partial(jax.jit, static_argnames=("max_levels", "expand"))
def _f_values_chunked(graph, queries, max_levels, expand):
    """(C, J, S) int32 padded queries -> (C, J) int64 F values."""

    def one(q):
        dist = multi_source_bfs(graph, q, max_levels=max_levels, expand=expand)
        return f_of_u(dist)

    return lax.map(jax.vmap(one), queries)


@partial(jax.jit, static_argnames=("max_levels", "expand"))
def _stats_chunked(graph, queries, max_levels, expand):
    """(C, J, S) queries -> per-query (levels, reached, F), each (C, J)."""
    from .bfs import stats_from_distances

    def one(q):
        dist = multi_source_bfs(graph, q, max_levels=max_levels, expand=expand)
        return stats_from_distances(dist)

    return lax.map(jax.vmap(one), queries)


@jax.jit
def _carry_init_batch(graph, queries):
    """(J, S) queries -> per-query (dist, level, updated) carry batch."""
    return jax.vmap(
        lambda q: distance_carry_init(graph.n, q, state_size=graph.n_pad)
    )(queries)


@donating_jit(
    donate_argnums=(1,), static_argnames=("chunk", "max_levels", "expand")
)
def _advance_batch(graph, carry, chunk, max_levels, expand):
    """One bounded dispatch: each of the J queries advances by <= ``chunk``
    levels (converged lanes are fixed points).  The carry is DONATED: the
    host driver rebinds it every step, so XLA updates the (J, n_pad)
    distance state in place instead of round-tripping it through fresh
    allocations (utils.donation)."""
    return jax.vmap(
        lambda c: distance_chunk(
            c, lambda d, lvl: expand(d, lvl, graph), chunk, max_levels
        )
    )(carry)


@jax.jit
def _f_from_dist_batch(dist):
    return jax.vmap(f_of_u)(dist)


@jax.jit
def _stats_from_dist_batch(dist):
    from .bfs import stats_from_distances

    return jax.vmap(stats_from_distances)(dist)


class QueryEngineBase:
    """Shared selection/compile surface over any ``f_values`` implementation
    (single-device, replicated-distributed, vertex-sharded).

    ``CAPABILITIES`` declares what an engine class can structurally do
    beyond the base contract — the tokens routing decisions key on
    (:func:`negotiate_engine`) instead of isinstance chains:

      * ``query_sharded`` — queries split over a mesh axis;
      * ``vertex_sharded`` — the graph itself split over a mesh axis
        (serves graphs beyond one chip's HBM);
      * ``mesh2d`` — 2D (row-block, col-block) adjacency tiling over an
        ('r', 'c') mesh (parallel.partition2d);
      * ``reshard`` — ``without_ranks`` rebuilds onto survivors after a
        chip loss (the supervisor's degrade-to-survivors path);
      * ``collective_bytes`` — per-level ICI payload is recorded through
        utils.timing.record_collective_bytes (the wire-roofline model);
      * ``streamed`` — the graph structure can stay host-resident and
        stream through the device per level (over-HBM residency: the
        single-chip ops.streamed engine, and Mesh2DEngine's
        ``residency="streamed"`` composition — routes ask for
        ``mesh2d`` + ``streamed`` together rather than a bespoke engine);
      * ``async`` — the engine supports a bounded-staleness drive
        (MSBFS_ASYNC_LEVELS > 1: several local level steps per
        reconciling collective round, bit-identical results via
        quiet-round termination) — like ``streamed``, a mode negotiated
        on Mesh2DEngine rather than a bespoke engine class.
    """

    CAPABILITIES: frozenset = frozenset()

    def capabilities(self) -> frozenset:
        """This engine's capability tokens (class-declared; instances of
        one class all negotiate identically)."""
        return self.CAPABILITIES

    def f_values(self, queries) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError

    def best(self, queries) -> Tuple[int, int]:
        """Run all groups; return (minF, minK) — reference main.cu:309-397."""
        # Queries pass through UNCONVERTED: an eager jnp.asarray here
        # would commit host queries to device before f_values' own
        # host-side padding (ops.packed._pad_queries / _chunk_grid) gets
        # to keep the whole batch riding the jitted program's argument
        # upload — re-introducing the dispatch the padding avoids.
        f = self.f_values(queries)
        # One transfer for both scalars (sequential int() reads each pay
        # a tunnel round-trip on this platform).
        min_f, min_k = jax.device_get(select_best_jit(f, f >= 0))
        record_dispatch()
        return int(min_f), int(min_k)

    def compile(
        self,
        queries_shape: Tuple[int, int],
        warm_stats: bool = False,
        warm_levels: bool = False,
    ) -> None:
        """Pre-trace/compile for a given (K, S) query shape so compile time
        lands in the preprocessing span (the CUDA reference's kernels are
        compiled offline by nvcc; see utils.timing).  ``warm_stats`` also
        compiles the query_stats program, ``warm_levels`` the stepped
        per-level program (each used when the caller will take that path in
        the timed span; ``warm_levels`` is a no-op on engines without
        :meth:`level_stats`)."""
        dummy = np.full(queries_shape, -1, dtype=np.int32)
        self.best(dummy)
        if warm_stats and queries_shape[0]:
            self.query_stats(dummy)
        if warm_levels and queries_shape[0] and callable(
            getattr(self, "level_stats", None)
        ):
            self.level_stats(dummy)
        # Warmed-shape ledger for the serving runtime (serve/caches.py):
        # a shape in this set has its programs in XLA's jit cache, so a
        # same-shape dispatch is executable reuse, not a recompile.
        # Lazily created — engines' __init__s never call up here.
        if not hasattr(self, "warmed_shapes"):
            self.warmed_shapes = set()
        self.warmed_shapes.add(tuple(int(d) for d in queries_shape))

    def is_warmed(self, queries_shape: Tuple[int, int]) -> bool:
        """True when :meth:`compile` already warmed this exact shape on
        THIS engine instance (a rebuilt engine starts cold)."""
        return tuple(int(d) for d in queries_shape) in getattr(
            self, "warmed_shapes", ()
        )

    def query_stats(self, queries):
        """Optional diagnostic: per-query (levels, reached, F) arrays.
        Engines that don't expose distances return None."""
        return None


# ---------------------------------------------------------------------------
# The engine lattice: four orthogonal, negotiated axes.
#
# An engine is a *configuration* on these axes, not a class: the same
# Mesh2DEngine instance can run bit or byte planes, HBM or streamed
# residency, XLA-pull or MXU tile-matmul kernels.  Routing code resolves
# a backend name plus knobs to an ``axes`` dict via :func:`resolve_axes`,
# turns it into capability tokens (``axis:value`` strings) and lets
# :func:`negotiate_engine` pick a class that declares them — so an
# impossible combination fails loud naming the missing token instead of
# silently running a lesser engine, and the agreement matrix stops
# growing one hand-wired class per combination.
AXES = {
    "plane": ("bit", "byte", "word"),
    "residency": ("hbm", "streamed"),
    "partition": ("single", "1d", "mesh2d"),
    "kernel": ("xla", "pallas", "mxu"),
}

#: backend name -> the axis values that backend pins (unset axes keep
#: the lattice defaults: bit planes, HBM residency, XLA kernel).
BACKEND_AXES = {
    "bitbell": {"plane": "bit"},
    "bell": {"plane": "word"},
    "lowk": {"plane": "byte"},
    "mxu": {"plane": "bit", "kernel": "mxu"},
    "streamed": {"plane": "bit", "residency": "streamed"},
    "stencil": {"plane": "bit"},
    "packed": {"plane": "word"},
    "ppush": {"plane": "word"},
    "push": {"plane": "word"},
    "dense": {"plane": "word"},
    "vmap": {"plane": "word"},
    "pallas": {"plane": "word", "kernel": "pallas"},
}

#: extra (non-axis) tokens a backend demands beyond its axis values.
BACKEND_EXTRAS = {
    "stencil": frozenset({"banded"}),
}


class NegotiationError(ValueError):
    """A knob combination that cannot negotiate.

    Subclasses ValueError so every existing ``except ValueError`` route
    (CLI fail-loud paths, serve routing) keeps working; the distinct
    type lets the negotiation property sweep assert *typed* failure —
    no silent fallback, no bare crash."""


def axis_tokens(axes) -> frozenset:
    """``axes`` dict -> the ``axis:value`` capability tokens it demands."""
    return frozenset(f"{axis}:{value}" for axis, value in axes.items())


# Axis-value pairs that no engine composes (and none is planned to):
# checked up front so the failure names the *pair*, not just a missing
# token on whichever candidate happened to be tried first.
_INCOMPATIBLE = (
    # MXU tile-matmul consumes packed bit planes (unpack_byte_planes on
    # a (n, W) uint32 frontier); byte planes never reach it.
    ("plane:byte", "kernel:mxu"),
    # The async negated-distance drive runs int32 word planes; the byte
    # plane's 0/1 flags carry no distance to relax chaotically.
    ("plane:byte", "async"),
    # MXU tiles are device-resident adjacency blocks; streaming them
    # per level would re-upload the whole tile set every dispatch.
    ("kernel:mxu", "residency:streamed"),
    ("kernel:mxu", "async"),
)


def resolve_axes(
    backend: str,
    partition: str = "single",
    residency: Optional[str] = None,
    plane: Optional[str] = None,
    kernel: Optional[str] = None,
    async_levels: int = 1,
    weighted: bool = False,
):
    """Map a backend name + routing knobs to the lattice.

    Returns ``(axes, required)``: the resolved axes dict and the full
    capability-token set a route should demand from
    :func:`negotiate_engine`.  ``residency``/``plane``/``kernel`` are
    the direct axis knobs (MSBFS_MESH_RESIDENCY / MSBFS_MESH_PLANE /
    MSBFS_MESH_KERNEL) — an explicit value overrides the backend's
    default for that axis.  Raises :class:`NegotiationError` for a
    combination no engine composes (naming the offending tokens) or an
    unknown backend/axis value — the typed fail-loud contract the
    negotiation sweep test pins."""
    if backend not in BACKEND_AXES:
        raise NegotiationError(
            f"unknown backend {backend!r}: not on the engine lattice "
            f"(known: {', '.join(sorted(BACKEND_AXES))})"
        )
    if partition not in AXES["partition"]:
        raise NegotiationError(
            f"unknown partition {partition!r} (axis values: "
            f"{', '.join(AXES['partition'])})"
        )
    for axis, value in (
        ("residency", residency), ("plane", plane), ("kernel", kernel)
    ):
        if value is not None and value not in AXES[axis]:
            raise NegotiationError(
                f"unknown {axis} {value!r} (axis values: "
                f"{', '.join(AXES[axis])})"
            )
    axes = {
        "plane": "bit",
        "residency": "hbm",
        "partition": partition,
        "kernel": "xla",
    }
    axes.update(BACKEND_AXES[backend])
    # Explicit axis knobs override the backend default for that axis
    # (backend "streamed" already pinned residency, "mxu" the kernel —
    # an explicit knob can still re-point them, and the incompatibility
    # screen below judges the RESULT, wherever each value came from).
    if residency is not None:
        axes["residency"] = residency
    if plane is not None:
        axes["plane"] = plane
    if kernel is not None:
        axes["kernel"] = kernel
    required = set(axis_tokens(axes))
    required |= BACKEND_EXTRAS.get(backend, frozenset())
    if axes["partition"] == "mesh2d":
        # Mesh routes always demand survivability: the supervisor's
        # degrade-to-survivors path needs without_ranks.
        required.add("reshard")
    if async_levels > 1:
        required.add("async")
    if weighted:
        required.add("weighted")
    bad = [
        (a, b)
        for a, b in _INCOMPATIBLE
        if a in required and b in required
    ]
    if bad:
        raise NegotiationError(
            "no engine composes "
            + " or ".join(f"{a} with {b}" for a, b in bad)
            + f" (backend={backend}, partition={axes['partition']})"
        )
    return axes, frozenset(required)


def engine_label(axes, async_levels: int = 1, extras=()) -> str:
    """Canonical engine label derived from resolved axes.

    This is the single source for ``label``/``describe`` strings and
    the ``detail.*`` bench keys — derived from the token set, never
    hand-built per class, so a rename can't silently fork the trend
    gate's config matching.  Existing labels are preserved exactly
    ("mesh2d", "mesh2d+streamed", "mesh2d+asyncK", "bitbell", ...)."""
    if axes.get("partition") == "mesh2d":
        label = "mesh2d"
        if axes.get("plane") == "byte":
            label += "+byte"
        if axes.get("kernel") == "mxu":
            label += "+mxu"
        if axes.get("residency") == "streamed":
            label += "+streamed"
        if async_levels > 1:
            label += f"+async{async_levels}"
        return label
    if axes.get("kernel") == "mxu":
        return "mxu"
    if axes.get("kernel") == "pallas":
        return "pallas"
    if "banded" in extras:
        return "stencil"
    if axes.get("residency") == "streamed":
        return "streamed"
    if axes.get("plane") == "byte":
        return "lowk"
    if axes.get("plane") == "word":
        return "dense"
    return "bitbell"


def negotiate_engine(required, candidates):
    """Pick the first candidate whose declared capabilities cover
    ``required``.

    ``candidates`` is a sequence of ``(label, engine_cls, factory)``
    triples in preference order; the winner's ``factory()`` is invoked
    (construction is the expensive part — losers never build) and
    ``(label, engine)`` returned.  No winner raises
    :class:`NegotiationError` (a ValueError) naming
    every candidate's missing tokens, so a route asked for an impossible
    combination (e.g. ``MSBFS_MESH`` with an engine family that cannot
    tile) fails loud instead of silently running a lesser engine."""
    required = frozenset(required)
    misses = []
    for label, engine_cls, factory in candidates:
        have = frozenset(getattr(engine_cls, "CAPABILITIES", ()))
        missing = required - have
        if not missing:
            return label, factory()
        misses.append(f"{label} lacks {{{', '.join(sorted(missing))}}}")
    raise NegotiationError(
        f"no engine provides {{{', '.join(sorted(required))}}}: "
        + "; ".join(misses)
    )


class Engine(QueryEngineBase):
    """Holds a device-resident graph and runs query groups against it.

    The graph lives in HBM once (reference main.cu:282-295); every call reuses
    it.  ``query_chunk=None`` runs all K queries in a single vmap batch.
    ``level_chunk`` bounds per-dispatch work to that many BFS levels (the
    high-diameter safety the bit-plane engines pioneered, now available to
    every graph representation this engine hosts — CSR pull, dense-MXU,
    Pallas-ELL); None keeps the whole BFS in one fused dispatch.
    """

    # Lattice axes: the generic word-plane host.  Declares BOTH kernel
    # values — the ``expand`` argument is the kernel axis here (CSR pull
    # and dense-MXU run XLA, the ELL slab runs the Pallas chain), the
    # same one-class-many-configurations shape as Mesh2DEngine.
    CAPABILITIES = frozenset(
        {
            "plane:word",
            "residency:hbm",
            "partition:single",
            "kernel:xla",
            "kernel:pallas",
        }
    )

    def __init__(
        self,
        graph: DeviceCSR,
        max_levels: Optional[int] = None,
        query_chunk: Optional[int] = None,
        expand=graph_expand,
        level_chunk: Optional[int] = None,
    ):
        self.graph = graph
        self.max_levels = max_levels
        self.query_chunk = query_chunk
        self.expand = expand
        self.level_chunk = validate_level_chunk(level_chunk)

    def _chunk_grid(self, queries) -> Tuple[jax.Array, int]:
        """Pad K to the chunk multiple and reshape to (C, chunk, S).

        Host-side NumPy padding whenever the input is host data (the CLI,
        bench and serve paths all pass NumPy): an eager jnp.concatenate
        here would be its own dispatched device program — a whole ~100 ms
        tunnel round-trip per query batch on this platform (the round-5
        "dispatch diet" fixed the packed engines' twin in
        PackedEngineBase._pad_queries; this is the generic engine's
        straggler, round-6 sweep)."""
        if not isinstance(queries, jax.Array):
            queries = np.asarray(queries, dtype=np.int32)
            K, S = queries.shape
            chunk = self.query_chunk or max(K, 1)
            pad = (-K) % chunk
            if pad:
                queries = np.concatenate(
                    [queries, np.full((pad, S), -1, dtype=np.int32)], axis=0
                )
            return queries.reshape((K + pad) // chunk, chunk, S), K
        queries = jnp.asarray(queries, dtype=jnp.int32)
        K, S = queries.shape
        chunk = self.query_chunk or max(K, 1)
        pad = (-K) % chunk
        if pad:
            queries = jnp.concatenate(
                [queries, jnp.full((pad, S), -1, dtype=jnp.int32)], axis=0
            )
        return queries.reshape((K + pad) // chunk, chunk, S), K

    def _dist_batch(self, queries_batch) -> jax.Array:
        """Bounded-dispatch path for ONE (J, S) query chunk: final
        (J, n_pad) distances via the host-chunked driver (one bounded
        dispatch per ``level_chunk`` levels, carry on device).  Chunks are
        driven one at a time so only one chunk's distance state is ever
        resident — the same memory bound as the fused path."""
        carry = host_chunked_loop(
            _carry_init_batch(self.graph, queries_batch),
            lambda c: _advance_batch(
                self.graph, c, self.level_chunk, self.max_levels, self.expand
            ),
            self.max_levels,
        )
        return carry[0]

    def f_values(self, queries: jax.Array) -> jax.Array:
        """(K, S) int32 -1-padded queries -> (K,) int64 F values."""
        grid, K = self._chunk_grid(queries)
        if grid.shape[0] == 0:  # K = 0: nothing to run on either path
            return jnp.zeros((0,), dtype=jnp.int64)
        if self.level_chunk:
            out = jnp.concatenate(
                [_f_from_dist_batch(self._dist_batch(row)) for row in grid]
            )
        else:
            out = _f_values_chunked(
                self.graph, grid, self.max_levels, self.expand
            ).reshape(-1)
        return out[:K]

    def query_stats(self, queries):
        """Per-query (levels, reached, F) — the tracing subsystem's data
        source (SURVEY.md section 5: new capability, reference has none).
        Respects query_chunk: the same O(chunk * E) per-level memory bound
        as f_values (the chunked path runs one query chunk's carry at a
        time)."""
        grid, K = self._chunk_grid(queries)
        if grid.shape[0] == 0:  # K = 0
            z = np.zeros(0, dtype=np.int64)
            return z.astype(np.int32), z.astype(np.int32), z
        if self.level_chunk:
            rows = [_stats_from_dist_batch(self._dist_batch(r)) for r in grid]
            levels, reached, f = (
                np.concatenate([np.asarray(x) for x in col])
                for col in zip(*rows)
            )
            return levels[:K], reached[:K], f[:K]
        levels, reached, f = _stats_chunked(
            self.graph, grid, self.max_levels, self.expand
        )
        return (
            np.asarray(levels).reshape(-1)[:K],
            np.asarray(reached).reshape(-1)[:K],
            np.asarray(f).reshape(-1)[:K],
        )
