"""Randomized property fuzzing: many seeds x graph families x query
shapes, every result checked against the host deque-BFS oracle.

The per-engine suites pin fixed fixtures; this sweep hunts the input
space — duplicate/self-loop-heavy multigraphs, disconnected pieces,
empty and out-of-range query groups, K not a multiple of the word width,
single-vertex and edgeless graphs — through the default single-chip
engine and (one seed per family) the distributed route."""

import numpy as np
import pytest

import jax

from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu import (
    CSRGraph,
    pad_queries,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models import (
    generators,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.models.bell import (
    BellGraph,
)
from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.bitbell import (
    BitBellEngine,
)

from oracle import oracle_best, oracle_bfs, oracle_f


def random_problem(rng: np.random.Generator):
    family = rng.choice(["gnm", "rmat", "grid", "multi", "edgeless"])
    if family == "gnm":
        n = int(rng.integers(2, 220))
        m = int(rng.integers(0, 3 * n))
        n, edges = generators.gnm_edges(n, m, seed=int(rng.integers(1 << 30)))
    elif family == "rmat":
        n, edges = generators.rmat_edges(
            int(rng.integers(4, 9)),
            edge_factor=int(rng.integers(2, 12)),
            seed=int(rng.integers(1 << 30)),
        )
    elif family == "grid":
        n, edges = generators.grid_edges(
            int(rng.integers(2, 24)), int(rng.integers(2, 24))
        )
        # Random deletions: disconnects pieces, keeps the road profile.
        keep = rng.random(edges.shape[0]) < 0.8
        edges = edges[keep]
    elif family == "multi":
        # Duplicate- and self-loop-heavy multigraph.
        n = int(rng.integers(2, 80))
        base = rng.integers(0, n, size=(int(rng.integers(1, 4 * n)), 2))
        loops = np.stack([np.arange(min(n, 5))] * 2, axis=1)
        edges = np.concatenate([base, base[:: 2], loops]).astype(np.int64)
    else:
        n = int(rng.integers(1, 40))
        edges = np.zeros((0, 2), dtype=np.int64)

    k = int(rng.integers(1, 12))
    queries = []
    for _ in range(k):
        size = int(rng.integers(0, 6))
        q = rng.integers(0, max(n, 1), size=size)
        if size and rng.random() < 0.3:
            q[0] = rng.choice([-1, n, n + 7])  # out-of-range sources drop
        queries.append(q.astype(np.int32))
    return n, edges, queries


@pytest.mark.parametrize(
    "seed",
    # ~3 s per seed: half the seed sweep stays in tier-1, the other
    # half is slow-marked for wall-clock budget (`make test` runs all).
    [
        s if s < 6 else pytest.param(s, marks=pytest.mark.slow)
        for s in range(12)
    ],
)
def test_fuzz_bitbell_matches_oracle(seed):
    rng = np.random.default_rng(1000 + seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = BitBellEngine(BellGraph.from_host(g))
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")
    assert eng.best(padded) == oracle_best(want), f"seed={seed}"


def random_banded_problem(rng: np.random.Generator):
    """A random BANDED graph the stencil engine must accept: a few random
    diffs applied on random vertex subsets (symmetrized by CSRGraph's
    undirected doubling), plus optional long links that ride the
    residual and optional sparse diffs that trigger offset demotion."""
    n = int(rng.integers(40, 600))
    num_offsets = int(rng.integers(1, 6))
    diffs = rng.choice(
        np.arange(1, max(2, n // 3)), size=num_offsets, replace=False
    )
    rows = []
    for d in diffs:
        u = np.nonzero(rng.random(n - int(d)) < rng.uniform(0.4, 0.95))[0]
        rows.append(np.stack([u, u + int(d)], axis=1))
    # A handful of long links -> residual; a very sparse diff -> demotion.
    extra = rng.integers(0, n, size=(int(rng.integers(0, 4)), 2))
    sparse_d = int(rng.integers(1, n // 2 + 1))
    sparse_u = rng.integers(0, max(n - sparse_d, 1), size=int(rng.integers(0, 3)))
    sparse = np.stack([sparse_u, sparse_u + sparse_d], axis=1)
    edges = np.concatenate(rows + [extra, sparse]).astype(np.int64)
    k = int(rng.integers(1, 10))
    queries = []
    for _ in range(k):
        size = int(rng.integers(0, 5))
        q = rng.integers(0, n, size=size)
        if size and rng.random() < 0.3:
            q[0] = rng.choice([-1, n, n + 7])
        queries.append(q.astype(np.int32))
    return n, edges, queries


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_stencil_matches_oracle(seed):
    """Stencil engine (detection -> demotion -> packed masks -> compact
    residual -> fused best) against the oracle on random banded graphs.
    Wide detection limits so every generated graph routes here; chunked
    on odd seeds."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.stencil import (
        StencilEngine,
        StencilGraph,
    )

    rng = np.random.default_rng(7000 + seed)
    n, edges, queries = random_banded_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    sg = StencilGraph.from_host(g, max_offsets=16, max_residual_frac=0.9)
    padded = pad_queries(queries)
    eng = StencilEngine(sg, level_chunk=3 if seed % 2 else None)
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")
    assert eng.best(padded) == oracle_best(want), f"seed={seed}"


@pytest.mark.parametrize("seed", [2000, 2001, 2002])
def test_fuzz_distributed_matches_oracle(seed):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device test mesh")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.distributed import (
        DistributedEngine,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = DistributedEngine(make_mesh(num_query_shards=8), g)
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", [3000, 3001])
def test_fuzz_sharded_sparse_matches_oracle(seed):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device test mesh")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.sharded_bell import (
        ShardedBellEngine,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = ShardedBellEngine(
        make_mesh(num_query_shards=2, num_vertex_shards=4),
        g,
        halo_budget=int(rng.integers(1, 32)),
        push_budget=int(rng.integers(1, 128)),
        level_chunk=int(rng.integers(1, 8)),
    )
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", [4000, 4001, 4002, 4003])
def test_fuzz_push_matches_oracle(seed):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.push import (
        PaddedAdjacency,
        PushEngine,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = PushEngine(PaddedAdjacency.from_host(g, max_width=1024))
    if rng.random() < 0.5:
        eng.capacity = int(rng.integers(1, 8))  # force auto-grow retries
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", [4500, 4501, 4502])
def test_fuzz_packed_push_matches_oracle(seed):
    """Union-frontier packed-lane push (round 4) on random shapes, with
    tiny random capacities forcing the overflow/growth protocol over the
    UNION queue."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.push import (
        PaddedAdjacency,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.ops.push_packed import (
        PackedPushEngine,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = PackedPushEngine(PaddedAdjacency.from_host(g, max_width=1024))
    if rng.random() < 0.5:
        eng.capacity = int(rng.integers(1, 8))  # force auto-grow retries
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", [5000, 5001])
def test_fuzz_distributed_push_matches_oracle(seed):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device test mesh")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.push_dist import (
        DistributedPushEngine,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    eng = DistributedPushEngine(
        make_mesh(num_query_shards=int(rng.choice([2, 4, 8]))),
        g,
        max_width=1024,
    )
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", [6000, 6001])
def test_fuzz_sharded_push_matches_oracle(seed):
    """Owner-partitioned push (round 4) on random shapes: random mesh
    split, tiny random capacities to force the overflow/growth protocol,
    random level chunk."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device test mesh")
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.mesh import (
        make_mesh,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.parallel.push_sharded import (
        ShardedPushEngine,
    )

    rng = np.random.default_rng(seed)
    n, edges, queries = random_problem(rng)
    g = CSRGraph.from_edges(n, edges)
    padded = pad_queries(queries)
    vs = int(rng.choice([2, 4, 8]))
    eng = ShardedPushEngine(
        make_mesh(num_query_shards=8 // vs, num_vertex_shards=vs),
        g,
        max_width=1024,
        level_chunk=int(rng.integers(1, 8)),
    )
    if rng.random() < 0.5:
        eng.capacity = int(rng.integers(1, 6))  # force auto-grow retries
        eng.boundary = int(rng.integers(1, 6))
    got = np.asarray(eng.f_values(padded))
    want = [oracle_f(oracle_bfs(n, edges, q)) for q in queries]
    np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


# ---------------------------------------------------------------------------
# Loader corruption fuzz: truncated/bit-flipped binaries through BOTH the
# Python and native loaders must land in the same taxonomy class
# (runtime.supervisor.classify -> InputError), never diverge, never crash
# the process (docs/RESILIENCE.md).
# ---------------------------------------------------------------------------


def _graph_load_outcome(path, native):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (
        MsbfsError,
        classify,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_graph_bin,
    )

    try:
        g = load_graph_bin(path, native=native)
        return ("ok", g.n, g.num_directed_edges)
    except Exception as exc:
        err = classify(exc)
        assert isinstance(err, MsbfsError)
        return ("err", type(err).__name__)


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_corrupt_graph_bin_loader_parity(seed, tmp_path):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        save_graph_bin,
    )

    if not native_loader.available():
        pytest.skip("native loader not built (make native)")
    rng = np.random.default_rng(9200 + seed)
    n, edges = generators.gnm_edges(40, 100, seed=9300 + seed)
    good = tmp_path / "good.bin"
    save_graph_bin(str(good), n, edges)
    blob = bytearray(good.read_bytes())
    for case in range(12):
        bad = bytearray(blob)
        mode = case % 3
        if mode == 0:  # truncate anywhere, header included
            bad = bad[: int(rng.integers(0, len(bad)))]
        elif mode == 1:  # flip bytes in the count header
            for _ in range(int(rng.integers(1, 4))):
                bad[int(rng.integers(0, min(8, len(bad))))] = int(
                    rng.integers(0, 256)
                )
        else:  # flip bytes anywhere in the payload
            for _ in range(int(rng.integers(1, 8))):
                bad[int(rng.integers(0, len(bad)))] = int(rng.integers(0, 256))
        p = tmp_path / f"bad_{seed}_{case}.bin"
        p.write_bytes(bytes(bad))
        got_py = _graph_load_outcome(str(p), native=False)
        got_nat = _graph_load_outcome(str(p), native=True)
        assert got_py == got_nat, (
            f"loader divergence on seed={seed} case={case}: "
            f"python={got_py} native={got_nat}"
        )


def test_fuzz_truncated_query_bin_is_input_error(tmp_path):
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime.supervisor import (
        InputError,
        classify,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_query_bin,
        save_query_bin,
    )

    good = tmp_path / "q.bin"
    save_query_bin(str(good), [np.array([1, 2], dtype=np.int32)])
    blob = good.read_bytes()
    for cut in range(len(blob)):
        p = tmp_path / f"q_{cut}.bin"
        p.write_bytes(blob[:cut])
        with pytest.raises(Exception) as ei:
            load_query_bin(str(p))
        assert isinstance(classify(ei.value), InputError)


def test_gr_header_parity_malformed_n_and_absent_m(tmp_path):
    """Both .gr parsers agree on the two header edge cases: a non-integer
    n token fails loud on both paths (Python's int() raise), and a
    header with m absent loads on both (neither parser reads m)."""
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.runtime import (
        native_loader,
    )
    from parallel_multi_source_bfs_implementation_using_mpi_and_cuda_tpu.utils.io import (
        load_dimacs_gr,
    )

    natives = [False, True] if native_loader.available() else [False]
    bad = tmp_path / "bad.gr"
    bad.write_text("p sp 12x3 9\na 1 2 7\n")
    for native in natives:
        with pytest.raises(ValueError):
            load_dimacs_gr(str(bad), native=native)
    ok = tmp_path / "ok.gr"
    ok.write_text("p sp 100\na 1 2 7\n")
    for native in natives:
        got_n, got_edges = load_dimacs_gr(str(ok), native=native)
        assert got_n == 100
        assert got_edges.tolist() == [[0, 1]]
